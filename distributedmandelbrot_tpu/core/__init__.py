"""Pure domain model: tile geometry, workload identity, chunk data."""

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import (CHUNK_PIXELS, CHUNK_WIDTH,
                                                     MAX_AXIS, MIN_AXIS,
                                                     TileSpec, chunk_origin,
                                                     level_chunk_range,
                                                     validate_indices)
from distributedmandelbrot_tpu.core.workload import (WORKLOAD_WIRE_SIZE,
                                                     LevelSetting, Workload,
                                                     parse_level_settings)

__all__ = [
    "CHUNK_PIXELS", "CHUNK_WIDTH", "MAX_AXIS", "MIN_AXIS", "TileSpec",
    "chunk_origin", "level_chunk_range", "validate_indices", "Chunk",
    "WORKLOAD_WIRE_SIZE", "LevelSetting", "Workload", "parse_level_settings",
]
