"""The chunk data model: a computed tile's pixels plus its grid identity.

Pixel value semantics (uint8), matching the reference
(``DistributedMandelbrotWorkerCUDA.py:96-98`` and ``DataChunk.cs:82-87``):

- ``0``  — the point never escaped within ``max_iter`` (treated as in-set;
  rendered black by the viewer)
- otherwise ``ceil(escape_iteration * 256 / max_iter)`` cast to uint8.

Chunks whose pixels are *all 0* (:attr:`Chunk.is_never`) or *all 1*
(:attr:`Chunk.is_immediate`) are classified specially so storage can record
them as a tag instead of a 16 MiB file (``DataChunk.cs:82-87,126-142``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from distributedmandelbrot_tpu import codecs
from distributedmandelbrot_tpu.core.geometry import (CHUNK_PIXELS, CHUNK_WIDTH,
                                                     validate_indices)


@dataclass(frozen=True)
class Chunk:
    """An immutable computed tile: grid identity + flat uint8 pixel data."""

    level: int
    index_real: int
    index_imag: int
    data: np.ndarray = field(repr=False)

    def __post_init__(self) -> None:
        validate_indices(self.level, self.index_real, self.index_imag)
        # Always copy: a view would alias the caller's buffer, and freezing a
        # view does not freeze its base — the caller could mutate "immutable"
        # chunk data (e.g. a worker reusing its pixel buffer).
        data = np.array(self.data, dtype=np.uint8, copy=True).ravel()
        if data.size != CHUNK_PIXELS:
            raise ValueError(
                f"chunk data must have {CHUNK_PIXELS} elements, got {data.size}")
        data.setflags(write=False)
        object.__setattr__(self, "data", data)

    @property
    def key(self) -> tuple[int, int, int]:
        return (self.level, self.index_real, self.index_imag)

    @property
    def is_never(self) -> bool:
        """All pixels 0: nothing in the tile escaped (tile entirely in-set)."""
        return bool((self.data == 0).all())

    @property
    def is_immediate(self) -> bool:
        """All pixels 1: everything escaped in the first scaled bucket."""
        return bool((self.data == 1).all())

    @staticmethod
    def filled(level: int, index_real: int, index_imag: int, value: int) -> "Chunk":
        return Chunk(level, index_real, index_imag,
                     np.full(CHUNK_PIXELS, value, dtype=np.uint8))

    @staticmethod
    def never(level: int, index_real: int, index_imag: int) -> "Chunk":
        return Chunk.filled(level, index_real, index_imag, 0)

    @staticmethod
    def immediate(level: int, index_real: int, index_imag: int) -> "Chunk":
        return Chunk.filled(level, index_real, index_imag, 1)

    def serialize(self) -> bytes:
        """Full codec payload (code byte + body), smallest codec wins."""
        return codecs.serialize(self.data)

    @staticmethod
    def deserialize_data(payload: bytes) -> np.ndarray:
        """Decode a codec payload into flat uint8 pixels of chunk size."""
        return codecs.deserialize(payload, CHUNK_PIXELS)

    def as_image(self) -> np.ndarray:
        """Pixels as a ``(4096, 4096)`` array; row = imag index, col = real."""
        return self.data.reshape((CHUNK_WIDTH, CHUNK_WIDTH))
