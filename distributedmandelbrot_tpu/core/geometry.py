"""Tile-grid geometry for the rendered complex-plane domain.

The rendered domain is the fixed square ``[-2, 2] x [-2, 2]`` of the complex
plane.  A *level* ``l`` tiles that square into an ``l x l`` grid of *chunks*;
each chunk is a fixed ``4096 x 4096`` pixel tile, one byte per pixel, so the
full image at level ``l`` is ``4096*l`` pixels on a side.

These invariants mirror the reference system so output stays bit-identical
(reference: ``DistributedMandelbrot/DataChunk.cs:14-27,32-33,59-72`` and
``DistributedMandelbrotWorkerCUDA/DistributedMandelbrotWorkerCUDA.py:7-8,19-37,75-78``):

- chunk side length in plane units: ``(MAX_AXIS - MIN_AXIS) / level = 4 / level``
- chunk origin: ``MIN_AXIS + chunk_range * index``
- pixel grids use **inclusive endpoints** (``np.linspace(start, start + range,
  num=4096)``), so the pixel pitch is ``range / 4095`` and adjacent chunks
  share their boundary row/column
- the flat pixel array is real-fastest: real values tiled, imaginary values
  repeated, i.e. row index = imaginary index, column index = real index.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# Bounds of the rendered square of the complex plane.
MIN_AXIS: float = -2.0
MAX_AXIS: float = 2.0

# Fixed chunk tile: CHUNK_WIDTH x CHUNK_WIDTH pixels, one byte per pixel.
CHUNK_WIDTH: int = 4096
CHUNK_PIXELS: int = CHUNK_WIDTH * CHUNK_WIDTH  # 16,777,216


def level_chunk_range(level: int) -> float:
    """Side length of one chunk in complex-plane units at ``level``."""
    if level < 1:
        raise ValueError(f"level must be >= 1, got {level}")
    return (MAX_AXIS - MIN_AXIS) / level


def chunk_origin(level: int, index_real: int, index_imag: int) -> tuple[float, float]:
    """Complex-plane coordinates of the chunk's low corner (start values)."""
    validate_indices(level, index_real, index_imag)
    r = level_chunk_range(level)
    return (MIN_AXIS + r * index_real, MIN_AXIS + r * index_imag)


def validate_indices(level: int, index_real: int, index_imag: int) -> None:
    """Chunk indices live in ``[0, level)`` on each axis."""
    if level < 1:
        raise ValueError(f"level must be >= 1, got {level}")
    if not (0 <= index_real < level):
        raise ValueError(f"index_real {index_real} out of range for level {level}")
    if not (0 <= index_imag < level):
        raise ValueError(f"index_imag {index_imag} out of range for level {level}")


def f32_pitch_adequate(start: float, range_: float, n: int,
                       min_ulps: float = 4.0) -> bool:
    """Whether an ``n``-sample axis over ``[start, start + range_]`` is
    resolvable in float32: the pixel pitch must span at least
    ``min_ulps`` f32 ulps at the axis's largest-magnitude coordinate.
    Below ~1 ulp/pixel adjacent samples collapse to the same f32 value
    (banded, aliased renders); ``min_ulps=4`` leaves headroom for the
    in-kernel ``start + i*step`` rounding.  Used by the f32 fast paths
    to decline views only float64 (or perturbation) can render.
    """
    if n <= 1:
        return True
    pitch = abs(range_) / (n - 1)
    maxc = max(abs(start), abs(start + range_))
    return pitch >= min_ulps * float(np.spacing(np.float32(max(maxc,
                                                               1e-30))))


def spec_f32_resolvable(spec: "TileSpec") -> bool:
    """Both axes of ``spec`` pass :func:`f32_pitch_adequate` — the single
    policy every f32 fast path consults (Pallas dispatch rejection, the
    worker fallback's dtype choice, the CLI's default-dtype upgrade), so
    the threshold can never desynchronize between them."""
    return (f32_pitch_adequate(spec.start_real, spec.range_real, spec.width)
            and f32_pitch_adequate(spec.start_imag, spec.range_imag,
                                   spec.height))


@dataclass(frozen=True)
class TileSpec:
    """Geometry of one tile to compute: where it sits and how finely sampled.

    Decoupled from the fixed chunk grid so the same kernels serve arbitrary
    window renders (benchmarks, deep zooms) as well as canonical chunks.
    """

    start_real: float
    start_imag: float
    range_real: float
    range_imag: float
    width: int = CHUNK_WIDTH
    height: int = CHUNK_WIDTH

    @staticmethod
    def for_chunk(level: int, index_real: int, index_imag: int,
                  definition: int = CHUNK_WIDTH) -> "TileSpec":
        start_r, start_i = chunk_origin(level, index_real, index_imag)
        rng = level_chunk_range(level)
        return TileSpec(start_r, start_i, rng, rng, definition, definition)

    def axes(self) -> tuple[np.ndarray, np.ndarray]:
        """Inclusive-endpoint sample axes (real, imag) as float64 numpy arrays.

        Computed with ``np.linspace`` so endpoint arithmetic is bit-identical
        to the reference worker's grid generation.
        """
        re = np.linspace(self.start_real, self.start_real + self.range_real,
                         num=self.width)
        im = np.linspace(self.start_imag, self.start_imag + self.range_imag,
                         num=self.height)
        return re, im

    def grid_flat(self) -> tuple[np.ndarray, np.ndarray]:
        """Flat (real, imag) coordinate arrays, real-fastest ordering."""
        re, im = self.axes()
        return np.tile(re, self.height), np.repeat(im, self.width)

    def grid_2d(self) -> tuple[np.ndarray, np.ndarray]:
        """2-D (height, width) coordinate arrays; row = imag, col = real."""
        re, im = self.axes()
        return np.broadcast_to(re, (self.height, self.width)).copy(), \
            np.broadcast_to(im[:, None], (self.height, self.width)).copy()
