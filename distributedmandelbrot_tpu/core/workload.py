"""Workload identity and wire encoding.

A workload (tile job) is the 4-tuple ``(level, max_iter, index_real,
index_imag)``, all uint32 little-endian on the wire (reference:
``DistributedMandelbrot/DistributerWorkload.cs:9-29,53-100``).

``max_iter`` (the reference's ``maximumRecursionDepth``) is optional in
memory: jobs reloaded from the on-disk index do not store it, so the
reference treats a missing value as a wildcard in equality
(``DistributerWorkload.cs:14-17,31-38``).  The reference breaks the
hash/equality contract doing so (``GetHashCode`` is identity,
``DistributerWorkload.cs:50-51``), making resume dedup best-effort; here
completion is instead keyed on :meth:`Workload.key` — ``(level, i, j)``
only — which is the fix the survey prescribes.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

_WIRE = struct.Struct("<IIII")

WORKLOAD_WIRE_SIZE: int = _WIRE.size  # 16 bytes


@dataclass(frozen=True)
class Workload:
    """One tile job: which chunk to compute and to what iteration depth."""

    level: int
    max_iter: Optional[int]
    index_real: int
    index_imag: int

    def __post_init__(self) -> None:
        for name in ("level", "index_real", "index_imag"):
            v = getattr(self, name)
            if not (0 <= v <= 0xFFFFFFFF):
                raise ValueError(f"{name}={v} out of uint32 range")
        if self.max_iter is not None and not (0 <= self.max_iter <= 0xFFFFFFFF):
            raise ValueError(f"max_iter={self.max_iter} out of uint32 range")

    @property
    def key(self) -> tuple[int, int, int]:
        """Completion identity: ``(level, index_real, index_imag)``.

        ``max_iter`` is deliberately excluded — the on-disk index does not
        record it, so resume matching must not depend on it.
        """
        return (self.level, self.index_real, self.index_imag)

    def matches(self, other: "Workload") -> bool:
        """Equality with ``max_iter=None`` acting as a wildcard on either side."""
        if self.key != other.key:
            return False
        if self.max_iter is None or other.max_iter is None:
            return True
        return self.max_iter == other.max_iter

    def to_wire(self) -> bytes:
        """16-byte little-endian encoding ``(level, max_iter, i_real, i_imag)``."""
        if self.max_iter is None:
            raise ValueError("cannot wire-encode a workload with max_iter=None")
        return _WIRE.pack(self.level, self.max_iter, self.index_real,
                          self.index_imag)

    @staticmethod
    def from_wire(data: bytes) -> "Workload":
        if len(data) != WORKLOAD_WIRE_SIZE:
            raise ValueError(
                f"workload wire data must be {WORKLOAD_WIRE_SIZE} bytes, "
                f"got {len(data)}")
        level, max_iter, index_real, index_imag = _WIRE.unpack(data)
        return Workload(level, max_iter, index_real, index_imag)


@dataclass(frozen=True)
class LevelSetting:
    """One entry of the coordinator's work definition: a level and its depth."""

    level: int
    max_iter: int

    def __post_init__(self) -> None:
        if self.level < 1:
            raise ValueError(f"level must be >= 1, got {self.level}")
        if self.max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {self.max_iter}")

    @property
    def tile_count(self) -> int:
        return self.level * self.level


def parse_level_settings(spec: str) -> tuple[LevelSetting, ...]:
    """Parse a ``level:max_iter[,level:max_iter...]`` spec string.

    Same surface as the reference CLI's ``-l`` flag
    (``DistributedMandelbrot/Program.cs:227-257``).
    """
    settings: list[LevelSetting] = []
    seen: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            level_s, mrd_s = part.split(":")
            setting = LevelSetting(int(level_s), int(mrd_s))
        except ValueError as e:
            raise ValueError(f"bad level setting {part!r}: expected "
                             f"'level:max_iter' with positive integers") from e
        if setting.level in seen:
            raise ValueError(f"level {setting.level} specified more than once")
        seen.add(setting.level)
        settings.append(setting)
    if not settings:
        raise ValueError("no level settings given")
    return tuple(settings)
