"""Device-mesh parallelism: tile batching, within-tile sharding, multi-host."""

from distributedmandelbrot_tpu.parallel.backend import MeshBackend
from distributedmandelbrot_tpu.parallel.mesh import (ROW_AXIS, TILE_AXIS,
                                                     local_devices, tile_mesh,
                                                     tile_row_mesh)
from distributedmandelbrot_tpu.parallel.sharding import (
    batched_escape_pixels, batched_escape_pixels_pallas,
    compute_tile_row_sharded)

__all__ = ["MeshBackend", "ROW_AXIS", "TILE_AXIS", "local_devices",
           "tile_mesh", "tile_row_mesh", "batched_escape_pixels",
           "batched_escape_pixels_pallas", "compute_tile_row_sharded"]
