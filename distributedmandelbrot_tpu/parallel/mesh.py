"""Device-mesh construction helpers.

The reference scales by running N independent worker processes, one GPU each
(survey §2 parallelism inventory).  The TPU-native shape is the inverse: one
fat worker process drives all local devices through a
:class:`jax.sharding.Mesh`, and scale-out across hosts extends the same mesh
via ``jax.distributed`` (see :mod:`distributedmandelbrot_tpu.parallel.multihost`).

Two mesh shapes cover the framework's parallelism:

- 1-D ``(tiles,)`` — data-parallel over a batch of tiles (the throughput
  shape; one tile per device per step)
- 2-D ``(tiles, rows)`` — batch sharding combined with within-tile row
  sharding (the latency shape for single huge tiles / deep zooms).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

TILE_AXIS = "tiles"
ROW_AXIS = "rows"


def local_devices() -> list[jax.Device]:
    return jax.local_devices()


def device_ring(n_devices: Optional[int] = None) -> list[jax.Device]:
    """Local devices in canonical placement order — the ONE ordering
    shared by the mesh backend (:func:`tile_mesh`) and the pipelined
    worker executor's round-robin dispatch, so a host running both
    assigns tile ``i`` of a batch to the same chip either way."""
    devices = local_devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return devices


def tile_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over local devices for tile-batch data parallelism."""
    return Mesh(np.array(device_ring(n_devices)), (TILE_AXIS,))


def tile_row_mesh(tiles: int, rows: int,
                  devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D mesh: ``tiles x rows`` devices; rows shard within each tile."""
    devs = list(devices) if devices is not None else local_devices()
    if tiles * rows > len(devs):
        raise ValueError(
            f"mesh {tiles}x{rows} needs {tiles * rows} devices, "
            f"have {len(devs)}")
    grid = np.array(devs[:tiles * rows]).reshape(tiles, rows)
    return Mesh(grid, (TILE_AXIS, ROW_AXIS))
