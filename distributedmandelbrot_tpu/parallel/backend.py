"""Mesh compute backend: the worker backend that drives a whole device mesh.

One fat worker leases a batch of tiles (batched dispatch) and computes them
in a single sharded dispatch — the TPU-native replacement for the
reference's N independent one-GPU worker processes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from jax.sharding import Mesh

from distributedmandelbrot_tpu.core.geometry import CHUNK_WIDTH, TileSpec
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.ops.escape_time import DEFAULT_SEGMENT
from distributedmandelbrot_tpu.parallel.mesh import tile_mesh
from distributedmandelbrot_tpu.parallel.sharding import batched_escape_pixels


class MeshBackend:
    """Computes tile batches sharded over a device mesh.

    ``kernel``: ``"auto"`` uses the Pallas block-early-exit kernel under
    shard_map on a live TPU (f32 batches whose tile shape fits the block
    granule), the XLA path otherwise; ``"xla"`` / ``"pallas"`` force."""

    def __init__(self, definition: int = CHUNK_WIDTH,
                 dtype: np.dtype = np.float32,
                 segment: int = DEFAULT_SEGMENT,
                 mesh: Optional[Mesh] = None,
                 kernel: str = "auto") -> None:
        if kernel not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown kernel {kernel!r}")
        if kernel == "pallas" and np.dtype(dtype) != np.float32:
            # Fail at construction, not after a tile has been leased.
            raise ValueError("kernel='pallas' is f32-only")
        self.definition = definition
        self.dtype = dtype
        self.segment = segment
        self.mesh = mesh if mesh is not None else tile_mesh()
        self.kernel = kernel

    def _use_pallas(self) -> bool:
        if self.kernel == "pallas":
            return True  # dtype validated at construction
        if self.kernel == "xla" or np.dtype(self.dtype) != np.float32:
            return False
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            pallas_available)
        return pallas_available()

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        if not workloads:
            return []
        params = np.empty((len(workloads), 3), dtype=np.float64)
        mrds = np.empty(len(workloads), dtype=np.int64)
        for i, w in enumerate(workloads):
            spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                      definition=self.definition)
            params[i] = (spec.start_real, spec.start_imag,
                         spec.range_real / (self.definition - 1))
            mrds[i] = w.max_iter
        pixels = None
        if self._use_pallas():
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                PallasUnsupported)
            from distributedmandelbrot_tpu.parallel.sharding import (
                batched_escape_pixels_pallas)
            try:
                pixels = batched_escape_pixels_pallas(
                    self.mesh, params, mrds, definition=self.definition)
            except PallasUnsupported:
                # Intentional granule/cap rejection -> XLA path; genuine
                # kernel errors propagate (see PallasUnsupported).
                if self.kernel == "pallas":
                    raise
                pixels = None
        if pixels is None:
            pixels = batched_escape_pixels(self.mesh, params, mrds,
                                           definition=self.definition,
                                           dtype=self.dtype,
                                           segment=self.segment)
        out = [pixels[i].ravel() for i in range(len(workloads))]
        if np.dtype(self.dtype) == np.float32:
            # Tiles whose pixel pitch aliases in f32 (levels beyond
            # ~1000 at 4096^2) would persist banded from the mesh path;
            # recompute those few in f64 so tile content never depends
            # on which backend leased it.
            from distributedmandelbrot_tpu.worker.backends import (
                recompute_unresolvable_f32)
            recompute_unresolvable_f32(workloads, out, self.definition)
        return out
