"""Mesh compute backend: the worker backend that drives a whole device mesh.

One fat worker leases a batch of tiles (batched dispatch) and computes them
in a single sharded dispatch — the TPU-native replacement for the
reference's N independent one-GPU worker processes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from jax.sharding import Mesh

from distributedmandelbrot_tpu.core.geometry import CHUNK_WIDTH, TileSpec
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.ops.escape_time import DEFAULT_SEGMENT
from distributedmandelbrot_tpu.parallel.mesh import tile_mesh
from distributedmandelbrot_tpu.parallel.sharding import batched_escape_pixels


class MeshBackend:
    """Computes tile batches sharded over a device mesh."""

    def __init__(self, definition: int = CHUNK_WIDTH,
                 dtype: np.dtype = np.float32,
                 segment: int = DEFAULT_SEGMENT,
                 mesh: Optional[Mesh] = None) -> None:
        self.definition = definition
        self.dtype = dtype
        self.segment = segment
        self.mesh = mesh if mesh is not None else tile_mesh()

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        if not workloads:
            return []
        params = np.empty((len(workloads), 3), dtype=np.float64)
        mrds = np.empty(len(workloads), dtype=np.int64)
        for i, w in enumerate(workloads):
            spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                      definition=self.definition)
            params[i] = (spec.start_real, spec.start_imag,
                         spec.range_real / (self.definition - 1))
            mrds[i] = w.max_iter
        pixels = batched_escape_pixels(self.mesh, params, mrds,
                                       definition=self.definition,
                                       dtype=self.dtype, segment=self.segment)
        return [pixels[i].ravel() for i in range(len(workloads))]
