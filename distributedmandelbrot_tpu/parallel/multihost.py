"""Multi-host mesh initialization.

The reference's cross-machine story is N independent TCP workers; the
TPU-native equivalent keeps that *control plane* (each host's worker process
still pulls leases over TCP) but lets a single worker span a multi-host TPU
slice: ``jax.distributed.initialize`` connects the hosts, local devices
join a global mesh, and XLA moves tile data over ICI/DCN — no NCCL/MPI
(survey §5.8).

Typical use on an N-host slice (same invocation on every host):

    from distributedmandelbrot_tpu.parallel import multihost
    multihost.initialize()          # env-driven on Cloud TPU
    mesh = multihost.global_tile_mesh()
    # rank 0 talks to the coordinator; the mesh computes everywhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (no-op when already initialized).

    With no arguments, relies on the TPU environment's auto-detection, the
    standard Cloud TPU path.
    """
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "already initialized" not in str(e).lower():
            raise


def is_primary() -> bool:
    """True on the process that should own coordinator-facing I/O."""
    return jax.process_index() == 0


def global_tile_mesh() -> Mesh:
    """1-D mesh over every device of every participating host."""
    return Mesh(np.array(jax.devices()), (TILE_AXIS,))
