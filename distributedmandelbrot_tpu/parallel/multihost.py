"""Multi-host mesh initialization.

The reference's cross-machine story is N independent TCP workers; the
TPU-native equivalent keeps that *control plane* (each host's worker process
still pulls leases over TCP) but lets a single worker span a multi-host TPU
slice: ``jax.distributed.initialize`` connects the hosts, local devices
join a global mesh, and XLA moves tile data over ICI/DCN — no NCCL/MPI
(survey §5.8).

Typical use on an N-host slice (same invocation on every host):

    from distributedmandelbrot_tpu.parallel import multihost
    multihost.initialize()          # env-driven on Cloud TPU
    mesh = multihost.global_tile_mesh()
    # rank 0 talks to the coordinator; the mesh computes everywhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (no-op when already initialized).

    With no arguments, relies on the TPU environment's auto-detection, the
    standard Cloud TPU path.
    """
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "already initialized" not in str(e).lower():
            raise


def is_primary() -> bool:
    """True on the process that should own coordinator-facing I/O."""
    return jax.process_index() == 0


def global_tile_mesh() -> Mesh:
    """1-D mesh over every device of every participating host."""
    return Mesh(np.array(jax.devices()), (TILE_AXIS,))


def batched_escape_pixels_multihost(mesh: Mesh,
                                    starts_steps_local: np.ndarray,
                                    mrds_local: np.ndarray, *,
                                    definition: int,
                                    dtype=np.float32,
                                    segment: Optional[int] = None,
                                    clamp: bool = False) -> np.ndarray:
    """SPMD tile batch over a multi-host mesh.

    Every process calls this with its *own* tiles (the global batch is the
    concatenation in process order); each gets back its local results as
    uint8 ``(k_local, definition, definition)``.  Compilation is a
    collective — all processes must make the same call with the same
    static shapes, the SPMD contract of ``jax.distributed``.  The local
    tile count must be identical on every process and a multiple of the
    local device count (lease batching already works in device-count
    multiples, so this falls out of batched dispatch).
    """
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedmandelbrot_tpu.ops.escape_time import DEFAULT_SEGMENT
    from distributedmandelbrot_tpu.parallel.sharding import (
        INT32_SCALE_LIMIT, _batched_escape_sharded)

    if segment is None:
        segment = DEFAULT_SEGMENT
    k_local = starts_steps_local.shape[0]
    n_local = jax.local_device_count()
    cap_local = int(mrds_local.max()) if k_local else 0
    # One collective establishes BOTH agreement points before any branch
    # can raise: validating k_local before the allgather would strand the
    # other processes inside the collective when one host's batch is bad
    # (they'd hang, not error).  The static iteration cap must be global
    # because it shapes the compiled program; callers batch per level, so
    # this is a max over identical values in practice.
    # The alignment flag is gathered too: with heterogeneous local device
    # counts, validating k_local % n_local process-locally would raise on
    # one host while the rest proceed into the sharded collective (hang).
    ok_local = int(k_local > 0 and k_local % n_local == 0)
    gathered = multihost_utils.process_allgather(
        np.asarray([k_local, cap_local, ok_local], np.int64)).reshape(-1, 3)
    ks = gathered[:, 0]
    cap = int(gathered[:, 1].max())
    if (ks != k_local).any() or not gathered[:, 2].all():
        raise ValueError(
            f"every process must contribute the same non-zero multiple of "
            f"its local device count; local batches were {ks.tolist()}, "
            f"alignment flags {gathered[:, 2].tolist()}")
    # Same widening policy as the single-host batched_escape_pixels
    # (sharding.py): counts*256 must not overflow int32.
    if cap - 1 >= INT32_SCALE_LIMIT or np.dtype(dtype) == np.float64:
        from distributedmandelbrot_tpu.utils.precision import ensure_x64
        ensure_x64()
    mrd_dtype = np.int64 if cap - 1 >= INT32_SCALE_LIMIT else np.int32

    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.make_array_from_process_local_data(
        sharding, np.asarray(starts_steps_local, dtype))
    mrd_arr = jax.make_array_from_process_local_data(
        sharding, np.asarray(mrds_local, mrd_dtype))
    out = _batched_escape_sharded(params, mrd_arr, mesh=mesh,
                                  definition=definition, max_iter_cap=cap,
                                  segment=segment, clamp=clamp)
    shards = sorted(out.addressable_shards, key=lambda s: s.index[0].start)
    return np.concatenate([np.asarray(s.data) for s in shards])
