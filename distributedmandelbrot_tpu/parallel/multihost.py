"""Multi-host mesh initialization.

The reference's cross-machine story is N independent TCP workers; the
TPU-native equivalent keeps that *control plane* (each host's worker process
still pulls leases over TCP) but lets a single worker span a multi-host TPU
slice: ``jax.distributed.initialize`` connects the hosts, local devices
join a global mesh, and XLA moves tile data over ICI/DCN — no NCCL/MPI
(survey §5.8).

Typical use on an N-host slice (same invocation on every host):

    from distributedmandelbrot_tpu.parallel import multihost
    multihost.initialize()          # env-driven on Cloud TPU
    mesh = multihost.global_tile_mesh()
    # rank 0 talks to the coordinator; the mesh computes everywhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (no-op when already initialized).

    With no arguments, relies on the TPU environment's auto-detection, the
    standard Cloud TPU path.
    """
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "already initialized" not in str(e).lower():
            raise


def is_primary() -> bool:
    """True on the process that should own coordinator-facing I/O."""
    return jax.process_index() == 0


def global_tile_mesh() -> Mesh:
    """1-D mesh over every device of every participating host."""
    return Mesh(np.array(jax.devices()), (TILE_AXIS,))


def batched_escape_pixels_multihost(mesh: Mesh,
                                    starts_steps_local: np.ndarray,
                                    mrds_local: np.ndarray, *,
                                    definition: int,
                                    dtype=np.float32,
                                    segment: Optional[int] = None,
                                    clamp: bool = False,
                                    kernel: str = "auto",
                                    interpret: Optional[bool] = None
                                    ) -> np.ndarray:
    """SPMD tile batch over a multi-host mesh.

    Every process calls this with its *own* tiles (the global batch is the
    concatenation in process order); each gets back its local results as
    uint8 ``(k_local, definition, definition)``.  ``segment`` tunes the
    XLA path's escape-check granularity only — when the Pallas kernel is
    selected (``kernel='auto'`` on an all-TPU slice) the analogous knob
    is the kernel's unroll, and ``segment`` is not consulted.  Compilation is a
    collective — all processes must make the same call with the same
    static shapes, the SPMD contract of ``jax.distributed``.  The local
    tile count must be identical on every process and a multiple of the
    local device count (lease batching already works in device-count
    multiples, so this falls out of batched dispatch).
    """
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedmandelbrot_tpu.ops.escape_time import DEFAULT_SEGMENT
    from distributedmandelbrot_tpu.parallel.sharding import (
        INT32_SCALE_LIMIT, _batched_escape_sharded)

    if kernel not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown kernel {kernel!r}")
    if segment is None:
        segment = DEFAULT_SEGMENT
    k_local = starts_steps_local.shape[0]
    n_local = jax.local_device_count()
    cap_local = int(mrds_local.max()) if k_local else 0
    # One collective establishes BOTH agreement points before any branch
    # can raise: validating k_local before the allgather would strand the
    # other processes inside the collective when one host's batch is bad
    # (they'd hang, not error).  The static iteration cap must be global
    # because it shapes the compiled program; callers batch per level, so
    # this is a max over identical values in practice.
    # The alignment flag is gathered too: with heterogeneous local device
    # counts, validating k_local % n_local process-locally would raise on
    # one host while the rest proceed into the sharded collective (hang).
    ok_local = int(k_local > 0 and k_local % n_local == 0)
    # Kernel eligibility is part of the SPMD agreement: compilation is a
    # collective, so EVERY rank must take the same kernel branch (a host
    # missing the Pallas backend must demote the whole slice to XLA).
    if kernel == "xla":
        pallas_local = 0
    else:
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            PallasUnsupported, fit_blocks, pallas_available)
        try:
            fit_blocks(definition, definition)
            pallas_local = int((pallas_available() or interpret is True)
                               and np.dtype(dtype) == np.float32)
        except PallasUnsupported:
            pallas_local = 0
    gathered = multihost_utils.process_allgather(
        np.asarray([k_local, cap_local, ok_local, pallas_local],
                   np.int64)).reshape(-1, 4)
    ks = gathered[:, 0]
    cap = int(gathered[:, 1].max())
    if (ks != k_local).any() or not gathered[:, 2].all():
        raise ValueError(
            f"every process must contribute the same non-zero multiple of "
            f"its local device count; local batches were {ks.tolist()}, "
            f"alignment flags {gathered[:, 2].tolist()}")
    use_pallas = bool(gathered[:, 3].all()) and cap - 1 < INT32_SCALE_LIMIT
    if kernel == "pallas" and not use_pallas:
        raise ValueError("kernel='pallas' requested but not every rank "
                         "can run it (availability/dtype/cap)")
    # Same widening policy as the single-host batched_escape_pixels
    # (sharding.py): counts*256 must not overflow int32.
    if cap - 1 >= INT32_SCALE_LIMIT or np.dtype(dtype) == np.float64:
        from distributedmandelbrot_tpu.utils.precision import ensure_x64
        ensure_x64()
    mrd_dtype = np.int64 if cap - 1 >= INT32_SCALE_LIMIT else np.int32

    sharding = NamedSharding(mesh, P(TILE_AXIS))
    if use_pallas:
        from distributedmandelbrot_tpu.parallel.sharding import (
            _batched_pallas_sharded, pallas_batch_config,
            widen_square_pitch)
        # One shared static-dispatch policy with the single-host path
        # (bucketed cap, TRUE-budget probe resolution, block shape) —
        # computed from the globally-agreed cap so every rank compiles
        # the identical executable.
        cfg = pallas_batch_config(definition, cap, interpret=interpret)
        params = jax.make_array_from_process_local_data(
            sharding, widen_square_pitch(
                np.asarray(starts_steps_local, np.float64)).astype(
                    np.float32))
        mrd_arr = jax.make_array_from_process_local_data(
            sharding, np.asarray(mrds_local, np.int32))
        out = _batched_pallas_sharded(
            params, mrd_arr, mesh=mesh, definition=definition,
            clamp=clamp, **cfg)
    else:
        params = jax.make_array_from_process_local_data(
            sharding, np.asarray(starts_steps_local, dtype))
        mrd_arr = jax.make_array_from_process_local_data(
            sharding, np.asarray(mrds_local, mrd_dtype))
        out = _batched_escape_sharded(params, mrd_arr, mesh=mesh,
                                      definition=definition,
                                      max_iter_cap=cap,
                                      segment=segment, clamp=clamp)
    shards = sorted(out.addressable_shards, key=lambda s: s.index[0].start)
    return np.concatenate([np.asarray(s.data) for s in shards])


def run_spmd_worker(host: str, port: int, *, definition: int | None = None,
                    batch_per_device: int = 1, poll: float = 0.0,
                    dtype=np.float32, clamp: bool = False,
                    mesh: Optional[Mesh] = None,
                    kernel: str = "auto") -> int:
    """The multi-host farm worker: one slice-spanning SPMD pull loop.

    Run the same invocation on every process of the slice (after
    :func:`initialize`).  The control plane stays the reference's pull
    model — but per *slice*, not per host: the primary process leases a
    batch sized to the GLOBAL device count and uploads the results over
    TCP; every process computes its local shard of each batch through
    :func:`batched_escape_pixels_multihost` (XLA moves tile data over
    ICI/DCN).  This is the "few fat workers x many cores" shape of
    survey §5.8, scaled across hosts.

    SPMD discipline: every rank must execute the same collectives in the
    same order, so the leased batch is broadcast from the primary each
    round — padded to a fixed ``global_devices * batch_per_device`` rows
    (trivial rows compute a level-1 tile at budget 1) — and the "no more
    work" decision rides the same broadcast, keeping ranks in lockstep
    through polling and shutdown.  Results are allgathered, so every
    host briefly materializes the full batch (k x definition^2 bytes);
    only the primary uploads.

    Returns the number of non-empty rounds (identical on every rank).
    """
    import time

    from jax.experimental import multihost_utils

    from distributedmandelbrot_tpu.core.geometry import level_chunk_range, \
        MIN_AXIS
    from distributedmandelbrot_tpu.core.workload import Workload

    if definition is None:
        from distributedmandelbrot_tpu.core.geometry import CHUNK_WIDTH
        definition = CHUNK_WIDTH
    if mesh is None:
        mesh = global_tile_mesh()
    primary = is_primary()
    n_proc = jax.process_count()
    k_global = mesh.devices.size * batch_per_device
    k_local = k_global // n_proc
    if k_global % n_proc:
        raise ValueError(f"global batch {k_global} must divide evenly "
                         f"across {n_proc} processes")
    client = None
    if primary:
        from distributedmandelbrot_tpu.worker.client import DistributerClient
        client = DistributerClient(host, port)

    rounds = 0
    pending_err: Optional[BaseException] = None
    while True:
        rows = np.zeros((k_global, 5), np.int64)  # level, mrd, i, j, real
        if primary:
            # SPMD anti-hang discipline (cf. the allgather note in
            # batched_escape_pixels_multihost): a primary-only
            # lease/upload failure must NOT kill rank 0 before the
            # broadcast — the other ranks would block in the collective
            # until the distributed heartbeat hard-kills them.  Failures
            # ride the broadcast as a sentinel so every rank raises
            # together.
            if pending_err is None:
                try:
                    grants = client.request_batch(k_global)
                    for r, w in enumerate(grants):
                        rows[r] = (w.level, w.max_iter, w.index_real,
                                   w.index_imag, 1)
                except Exception as e:
                    pending_err = e
            if pending_err is not None:
                rows[:, 4] = -1  # abort sentinel
        rows = multihost_utils.broadcast_one_to_all(rows)
        if (rows[:, 4] < 0).any():
            if primary:
                raise RuntimeError(
                    "multihost worker aborting: coordinator I/O failed "
                    "on the primary") from pending_err
            raise RuntimeError(
                "multihost worker aborting: the primary reported a "
                "coordinator I/O failure")
        n_real = int(rows[:, 4].sum())
        if n_real == 0:
            if poll <= 0:
                return rounds
            time.sleep(poll)  # every rank saw the same empty broadcast
            continue
        rounds += 1
        params = np.empty((k_global, 3))
        for r in range(k_global):
            level, mrd, i, j, real = rows[r]
            if not real:  # trivial pad: level-1 tile at budget 1
                level, mrd, i, j = 1, 1, 0, 0
            rng = level_chunk_range(int(level))
            params[r] = (MIN_AXIS + rng * int(i), MIN_AXIS + rng * int(j),
                         rng / (definition - 1))
        lo = jax.process_index() * k_local
        out_local = batched_escape_pixels_multihost(
            mesh, params[lo:lo + k_local],
            np.maximum(rows[lo:lo + k_local, 1], 1),
            definition=definition, dtype=dtype, clamp=clamp,
            kernel=kernel)
        gathered = multihost_utils.process_allgather(out_local)
        if primary:
            full = gathered.reshape(k_global, definition, definition)
            wls = [Workload(int(rows[r, 0]), int(rows[r, 1]),
                            int(rows[r, 2]), int(rows[r, 3]))
                   for r in range(k_global) if rows[r, 4]]
            pix = [full[r].ravel() for r in range(k_global) if rows[r, 4]]
            if np.dtype(dtype) == np.float32:
                # Sub-f32-resolution tiles would upload banded; the
                # primary recomputes those few in f64 locally (no
                # collectives involved, so ranks stay in lockstep).
                # List-slot replacement, never in-place writes: the
                # allgathered buffer is read-only.
                from distributedmandelbrot_tpu.worker.backends import (
                    recompute_unresolvable_f32)
                recompute_unresolvable_f32(wls, pix, definition,
                                           clamp=clamp)
            try:
                client.submit_batch(list(zip(wls, pix)))
            except Exception as e:
                pending_err = e  # abort sentinel on the next broadcast
