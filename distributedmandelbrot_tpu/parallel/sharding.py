"""Sharded escape-time compute over a device mesh.

Two shardings, matching the two scaling axes (survey §2/§5.7):

- :func:`batched_escape_pixels` — *tile batch* data parallelism: a batch of
  k tiles (possibly from different levels, each with its own ``max_iter``)
  is sharded over the mesh's ``tiles`` axis with ``shard_map``; each device
  walks its tiles with ``lax.map`` so every tile keeps its own segmented
  early exit.  This is the throughput path behind batched dispatch.
- :func:`compute_tile_row_sharded` — *within-tile* row sharding: one tile's
  rows are split across devices (rows are embarrassingly parallel — the
  halo-free analog of sequence parallelism here).  This is the latency path
  for single huge tiles / deep zooms.

Grids are generated **on device** from ``(start, step)`` scalars via
``broadcasted_iota`` — no 256 MB host grid, no H2D transfer of coordinates
(the reference ships full coordinate arrays to the GPU,
``DistributedMandelbrotWorkerCUDA.py:82-90``).  Device grid generation uses
``start + index*step`` without numpy-linspace's forced exact endpoint; for
the f32 fast path this is irrelevant and the bit-exact parity anchor
remains the host-grid paths (see ops/escape_time.py).

Per-tile ``max_iter`` in a mixed batch: the kernel iterates to the batch's
static cap, then zeroes counts ``> mrd_i - 1`` — identical to running each
tile to its own budget, since escape counts are monotone in the budget.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.ops.escape_time import (DEFAULT_SEGMENT,
                                                       INT32_SCALE_LIMIT,
                                                       escape_loop,
                                                       mandelbrot_interior,
                                                       resolve_cycle_check)
from distributedmandelbrot_tpu.parallel.mesh import ROW_AXIS, TILE_AXIS

try:
    from jax import shard_map as _shard_map  # JAX >= 0.8
except ImportError:  # older JAX
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

# The "skip the static sharding checker" kwarg was renamed check_rep ->
# check_vma across JAX versions; resolve once at import.  Every wrapper
# here runs with the checker OFF: the per-tile computations carry no
# collectives (nothing for the check to protect), pallas_call out_shapes
# carry no varying-mesh-axes annotation (the vma checker rejects them),
# and older JAX has no replication rule for while_loop at all (the
# rep checker rejects the escape loop itself).
import inspect as _inspect

_SHARD_CHECK_KW = ("check_vma" if "check_vma"
                   in _inspect.signature(_shard_map).parameters
                   else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    kwargs.setdefault(_SHARD_CHECK_KW, False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def _device_grid(start_r, start_i, step, shape, dtype, row_offset=0):
    """(c_real, c_imag) grids from scalars, generated on device."""
    col = lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)
    row = lax.broadcasted_iota(jnp.int32, shape, len(shape) - 2) + row_offset
    c_real = start_r + col.astype(dtype) * step
    c_imag = start_i + row.astype(dtype) * step
    return c_real, c_imag


def _masked_escape(c_real, c_imag, max_iter_cap: int, segment: int,
                   cycle_check: bool | None = None,
                   interior_check: bool = True):
    """The segmented escape loop (ops.escape_time.escape_loop; see there
    for the recurrence and count recovery).  The shortcut toggles are
    output-identical; off only for timing the raw loop (bench)."""
    total_steps = max_iter_cap - 1
    if total_steps <= 0:
        return jnp.zeros(c_real.shape, jnp.int32)

    # Derive the initial z from BOTH coordinate arrays rather than one
    # input alone so that, under shard_map, every while_loop carry has the
    # union of the inputs' varying-manual-axes — e.g. in the row-sharded
    # path c_imag varies over the rows axis but c_real is replicated, and
    # a carry typed off only one of them fails while_loop typing when the
    # body mixes in the other.
    zr0 = c_real + 0.0 * c_imag
    zi0 = c_imag + 0.0 * c_real
    # Both sharded paths render the Mandelbrot family (z0 == c), so the
    # closed-form interior shortcut always applies (output-identical;
    # see ops.escape_time.mandelbrot_interior); deep budgets also get the
    # Brent cycle probe (same policy as escape_counts).
    interior = mandelbrot_interior(zr0, zi0) if interior_check else None
    return escape_loop(zr0, zi0, c_real, c_imag, total_steps=total_steps,
                       segment=segment, interior=interior,
                       cycle_check=resolve_cycle_check(cycle_check,
                                                       max_iter_cap))


def _scale_pixels(counts, mrd, clamp: bool):
    """Exact integer uint8 scaling; widens when counts*256 could overflow
    int32 (same policy as ops.escape_time._scale_counts_jit)."""
    wide = jnp.int64 if counts.dtype == jnp.int64 else jnp.int32
    mrd = mrd.astype(wide) if hasattr(mrd, "astype") else mrd
    vals = (counts.astype(wide) * 256 + (mrd - 1)) // mrd
    if clamp:
        vals = jnp.minimum(vals, 255)
    return vals.astype(jnp.uint8)


def _one_tile_pixels(params, mrd, *, definition: int, max_iter_cap: int,
                     segment: int, clamp: bool,
                     cycle_check: bool | None = None,
                     interior_check: bool = True):
    """params = (start_r, start_i, step) scalars; mrd = per-tile budget."""
    start_r, start_i, step = params[0], params[1], params[2]
    c_real, c_imag = _device_grid(start_r, start_i, step,
                                  (definition, definition), params.dtype)
    counts = _masked_escape(c_real, c_imag, max_iter_cap, segment,
                            cycle_check=cycle_check,
                            interior_check=interior_check)
    counts = jnp.where(counts <= mrd - 1, counts, 0)
    if max_iter_cap - 1 >= INT32_SCALE_LIMIT:
        counts = counts.astype(jnp.int64)
    return _scale_pixels(counts, mrd, clamp)


def widen_square_pitch(starts_steps: np.ndarray) -> np.ndarray:
    """(k, 3) square-pitch batch rows -> the Pallas kernel's (k, 4)
    per-axis-pitch params layout (duplicate the step).  Every raw caller
    of ``_pallas_escape``/``_batched_pallas_sharded`` must widen through
    here; the batched APIs are square-pitch by construction."""
    return np.concatenate([starts_steps, starts_steps[:, 2:3]], axis=1)


def pad_to_mesh(starts_steps: np.ndarray, mrds: np.ndarray,
                n_dev: int) -> tuple[np.ndarray, np.ndarray]:
    """Right-pad a tile batch to a multiple of the mesh size with trivial
    tiles (far outside the set, budget 1 — they escape immediately)."""
    pad = (-starts_steps.shape[0]) % n_dev
    if pad:
        pad_params = np.tile(np.array([[3.0, 3.0, 0.0]]), (pad, 1))
        starts_steps = np.concatenate(
            [starts_steps, pad_params.astype(starts_steps.dtype)])
        mrds = np.concatenate([mrds, np.ones(pad, mrds.dtype)])
    return starts_steps, mrds


@partial(jax.jit,
         static_argnames=("mesh", "definition", "max_iter_cap", "segment",
                          "clamp", "cycle_check", "interior_check"))
def _batched_escape_sharded(params, mrds, *, mesh: Mesh, definition: int,
                            max_iter_cap: int, segment: int, clamp: bool,
                            cycle_check: bool | None = None,
                            interior_check: bool = True):
    tile_fn = partial(_one_tile_pixels, definition=definition,
                      max_iter_cap=max_iter_cap, segment=segment, clamp=clamp,
                      cycle_check=cycle_check, interior_check=interior_check)

    def shard_fn(p_shard, m_shard):
        # Sequential walk of this device's tiles: each keeps its own
        # early-exit while_loop instead of lockstepping with batch peers.
        return lax.map(lambda args: tile_fn(*args), (p_shard, m_shard))

    return shard_map(shard_fn, mesh=mesh,
                     in_specs=(P(TILE_AXIS), P(TILE_AXIS)),
                     out_specs=P(TILE_AXIS))(params, mrds)


def batched_escape_pixels(mesh: Mesh, starts_steps: np.ndarray,
                          mrds: np.ndarray, *, definition: int,
                          dtype=np.float32, segment: int = DEFAULT_SEGMENT,
                          clamp: bool = False,
                          cycle_check: bool | None = None,
                          interior_check: bool = True) -> np.ndarray:
    """Compute a batch of tiles sharded over ``mesh``'s ``tiles`` axis.

    ``starts_steps``: float (k, 3) of ``(start_real, start_imag, step)``;
    ``mrds``: int (k,) per-tile iteration budgets.  Returns uint8
    ``(k, definition, definition)``.  The batch is padded on the right to a
    multiple of the mesh size with trivial tiles and unpadded on return.
    """
    k = starts_steps.shape[0]
    if k == 0:
        return np.zeros((0, definition, definition), np.uint8)
    starts_steps, mrds = pad_to_mesh(starts_steps, mrds, mesh.devices.size)
    cap = int(mrds.max())
    if cap - 1 >= INT32_SCALE_LIMIT:  # counts*256 must not overflow int32
        from distributedmandelbrot_tpu.utils.precision import ensure_x64
        ensure_x64()
        mrd_dtype = jnp.int64
    else:
        mrd_dtype = jnp.int32
    params = jnp.asarray(starts_steps, dtype=dtype)
    mrd_arr = jnp.asarray(mrds, dtype=mrd_dtype)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(params, sharding)
    mrd_arr = jax.device_put(mrd_arr, sharding)
    out = _batched_escape_sharded(params, mrd_arr, mesh=mesh,
                                  definition=definition, max_iter_cap=cap,
                                  segment=segment, clamp=clamp,
                                  cycle_check=cycle_check,
                                  interior_check=interior_check)
    return np.asarray(out)[:k]


@partial(jax.jit,
         static_argnames=("mesh", "definition", "max_iter_cap", "unroll",
                          "block_h", "block_w", "clamp", "interpret",
                          "cycle_check", "batch_grid", "compact"))
def _batched_pallas_sharded(params, mrds, *, mesh: Mesh, definition: int,
                            max_iter_cap: int, unroll: int, block_h: int,
                            block_w: int, clamp: bool,
                            interpret: bool = False,
                            cycle_check: bool | None = None,
                            batch_grid: bool = False,
                            compact: bool = False):
    """The Pallas kernel under shard_map: each device runs its tile shard
    with its own traced per-tile budget (static cap = the batch max).

    Deep budgets (``batch_grid=True``, decided by pallas_batch_config
    from the TRUE deepest budget — not the padded compile cap) dispatch
    the whole shard as ONE batch-grid kernel launch — consecutive deep
    grid programs pipeline ~2x better (see the batch-grid note in
    ops/pallas_escape.py); shallow budgets keep the per-tile ``lax.map``
    chain, whose early-exit views measure a few percent faster."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape, _pallas_escape_batch)

    def one_tile(p, m):
        return _pallas_escape(p[None, :], m[None, None].astype(jnp.int32),
                              height=definition, width=definition,
                              max_iter=max_iter_cap, unroll=unroll,
                              block_h=block_h, block_w=block_w, clamp=clamp,
                              interpret=interpret, cycle_check=cycle_check)

    def shard_fn(p_shard, m_shard):
        k_loc = p_shard.shape[0]
        if compact:
            # Opt-in (DMTPU_COMPACT=1) two-phase compacted dispatch —
            # measured negative on the bench stack, see
            # ops/compact_escape.prefer_compaction.
            from distributedmandelbrot_tpu.ops.compact_escape import (
                compact_escape_batch)
            # cycle_check forwards the ALREADY-RESOLVED policy (from the
            # true cap): re-resolving against the bucketed compile cap
            # would wrongly arm the probe for true caps just below
            # CYCLE_CHECK_MIN_ITER whose bucket rounds past it (the
            # 513-1023 band since round 5) and reject the dispatch
            # (round-4 review finding).
            return compact_escape_batch(
                p_shard, m_shard[:, None].astype(jnp.int32), k=k_loc,
                height=definition, width=definition, max_iter=max_iter_cap,
                unroll=unroll, block_h=block_h, block_w=block_w,
                clamp=clamp, cycle_check=cycle_check, interpret=interpret)
        if batch_grid and k_loc > 1:
            return _pallas_escape_batch(
                p_shard, m_shard[:, None].astype(jnp.int32), k=k_loc,
                height=definition, width=definition, max_iter=max_iter_cap,
                unroll=unroll, block_h=block_h, block_w=block_w,
                clamp=clamp, interpret=interpret, cycle_check=cycle_check)
        return lax.map(lambda args: one_tile(*args), (p_shard, m_shard))

    # Checker off (see the module-level shard_map wrapper): pallas_call's
    # out_shape is a plain ShapeDtypeStruct with no varying-mesh-axes
    # annotation, which the vma checker rejects; the computation is
    # per-tile with no collectives, so there is nothing to protect.
    return shard_map(shard_fn, mesh=mesh,
                     in_specs=(P(TILE_AXIS), P(TILE_AXIS)),
                     out_specs=P(TILE_AXIS))(params, mrds)


def pallas_batch_config(definition: int, cap: int,
                        interpret: bool | None = None) -> dict:
    """The shared static-dispatch policy for a Pallas tile batch —
    bucketed compile cap, block shape, probe resolution from the TRUE
    deepest budget (not the padded cap), interpret auto-selection — used
    by both the single-host and the multihost sharded paths so the two
    can never drift.  Raises PallasUnsupported for int64 caps and
    unsupported tile extents."""
    from distributedmandelbrot_tpu.ops.compact_escape import (
        prefer_compaction)
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        DEFAULT_UNROLL, PallasUnsupported, bucket_cap, fit_blocks,
        pallas_available, prefer_batch_grid)

    if cap - 1 >= INT32_SCALE_LIMIT:
        raise PallasUnsupported(
            "pallas path is int32-only; cap needs the XLA path")
    block_h, block_w = fit_blocks(definition, definition)
    return {"max_iter_cap": bucket_cap(cap),
            "cycle_check": resolve_cycle_check(None, cap),
            # Policy from the TRUE deepest budget, not the padded
            # compile cap (round-2 advisor principle): budgets
            # 2049-4095 bucket to 4096 but stay on the per-tile chain.
            "batch_grid": prefer_batch_grid(cap, definition, definition,
                                            block_h, block_w),
            "compact": prefer_compaction(cap, definition * definition),
            "block_h": block_h, "block_w": block_w,
            "unroll": DEFAULT_UNROLL,
            "interpret": (not pallas_available() if interpret is None
                          else interpret)}


def batched_escape_pixels_pallas(mesh: Mesh, starts_steps: np.ndarray,
                                 mrds: np.ndarray, *, definition: int,
                                 clamp: bool = False,
                                 interpret: bool | None = None,
                                 cycle_check: bool | None = None
                                 ) -> np.ndarray:
    """Pallas-kernel twin of :func:`batched_escape_pixels` (f32 only).

    Raises :class:`~...ops.pallas_escape.PallasUnsupported` when the tile
    shape doesn't fit the kernel's block granule or the iteration cap
    needs int64 — callers fall back to the XLA path (see
    :meth:`MeshBackend.compute_batch`).
    """
    k = starts_steps.shape[0]
    if k == 0:
        return np.zeros((0, definition, definition), np.uint8)
    cfg = pallas_batch_config(definition, int(mrds.max()),
                              interpret=interpret)
    if cycle_check is not None:
        cfg["cycle_check"] = cycle_check
        if cycle_check and cfg.get("compact"):
            # prefer_compaction assumed the probe resolved False; an
            # explicit cycle_check=True override is incompatible with the
            # compacted dispatch (it would raise PallasUnsupported and
            # hard-fail the whole backend), so demote to the plain
            # batch-grid path instead (round-4 advisor finding).
            cfg["compact"] = False
    starts_steps, mrds = pad_to_mesh(starts_steps, mrds, mesh.devices.size)
    starts_steps = widen_square_pitch(starts_steps)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(jnp.asarray(starts_steps, jnp.float32), sharding)
    mrd_arr = jax.device_put(jnp.asarray(mrds, jnp.int32), sharding)
    out = _batched_pallas_sharded(params, mrd_arr, mesh=mesh,
                                  definition=definition, clamp=clamp,
                                  **cfg)
    return np.asarray(out)[:k]


def _pad_mega(rows: list, mrd_rows: list, n_dev: int) -> tuple[list, list]:
    """Right-pad megakernel params/budget rows to a multiple of the mesh
    size with trivial tiles (z0 far outside the set, budget 1 — they
    escape immediately; same policy as :func:`pad_to_mesh`, in the mega
    kernel's per-axis-pitch row layout)."""
    pad = (-len(rows)) % n_dev
    if pad:
        trivial = [3.0, 3.0] + [0.0] * (len(rows[0]) - 2)
        rows = list(rows) + [list(trivial) for _ in range(pad)]
        mrd_rows = list(mrd_rows) + [[1] for _ in range(pad)]
    return rows, mrd_rows


@partial(jax.jit,
         static_argnames=("mesh", "k_loc", "height", "width", "max_iter",
                          "unroll", "block_h", "block_w", "clamp",
                          "interpret", "interior_check", "cycle_check",
                          "scout_segments", "julia", "power", "burning",
                          "use_mxu"))
def _mega_sharded(params, mrds, *, mesh: Mesh, k_loc: int, height: int,
                  width: int, max_iter: int, unroll: int, block_h: int,
                  block_w: int, clamp: bool, interpret: bool,
                  interior_check: bool, cycle_check: bool,
                  scout_segments: int, julia: bool, power: int,
                  burning: bool, use_mxu: bool):
    """The megakernel under shard_map: each device runs ONE fused
    ``k_loc``-tile launch over its shard of the ``tiles`` axis, so a
    K-tile batch costs one dispatch constant per *host call*, not per
    device-tile.  Per-tile outputs (pixels + scout census) stay sharded;
    slicing tile ``i`` off the global array lands on the device that
    computed it.  Statics arrive pre-resolved from mega_dispatch_plan —
    every device compiles the identical executable."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape_mega)

    def shard_fn(p_shard, m_shard):
        return _pallas_escape_mega(
            p_shard, m_shard, k=k_loc, height=height, width=width,
            max_iter=max_iter, unroll=unroll, block_h=block_h,
            block_w=block_w, clamp=clamp, interpret=interpret,
            interior_check=interior_check, cycle_check=cycle_check,
            scout_segments=scout_segments, julia=julia, power=power,
            burning=burning, use_mxu=use_mxu)

    # Checker off for the same reason as _batched_pallas_sharded: the
    # pallas_call out_shape carries no varying-mesh-axes annotation, and
    # the computation is per-tile with no collectives.
    return shard_map(shard_fn, mesh=mesh,
                     in_specs=(P(TILE_AXIS), P(TILE_AXIS)),
                     out_specs=(P(TILE_AXIS), P(TILE_AXIS)))(params, mrds)


def compute_tiles_mega_sharded(specs, max_iters, *, mesh: Mesh | None = None,
                               clamp: bool = False,
                               interpret: bool | None = None,
                               interior_check: bool = True,
                               cycle_check: bool | None = None,
                               scout_segments: int | None = None,
                               power: int = 2, burning: bool = False,
                               julia_cs=None, use_mxu: bool | None = None,
                               unroll: int | None = None,
                               block_h: int | None = None,
                               block_w: int | None = None):
    """Mesh twin of ops/pallas_escape.compute_tiles_mega_pallas: ONE
    fused K-tile batch sharded over the ``tiles`` axis across all of
    ``mesh``'s devices (default: every local device in device_ring
    order).  Returns ``(tiles, scout)`` still on device — (k, h, w)
    uint8 and (k, 1) int32, batch order, padding already stripped.

    Bit-identity: every static dispatch decision comes from the same
    mega_dispatch_plan as the single-device route, and each device runs
    the unmodified megakernel on its shard — so mesh output is
    bit-identical to the single-device megakernel (and hence to k
    single-tile calls) by construction, for any device count.  Raises
    :class:`~...ops.pallas_escape.PallasUnsupported` on the same
    shape/pitch/budget limits; callers fall back to the single-device
    route."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        DEFAULT_BLOCK_H, DEFAULT_UNROLL, mega_dispatch_plan)
    if mesh is None:
        from distributedmandelbrot_tpu.parallel.mesh import tile_mesh
        mesh = tile_mesh()
    n_dev = mesh.devices.size
    rows, mrd_rows, kw = mega_dispatch_plan(
        specs, max_iters,
        unroll=DEFAULT_UNROLL if unroll is None else unroll,
        block_h=DEFAULT_BLOCK_H if block_h is None else block_h,
        block_w=block_w, clamp=clamp, interpret=interpret,
        interior_check=interior_check, cycle_check=cycle_check,
        scout_segments=scout_segments, power=power, burning=burning,
        julia_cs=julia_cs, use_mxu=use_mxu)
    k = len(rows)
    rows, mrd_rows = _pad_mega(rows, mrd_rows, n_dev)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(jnp.asarray(rows, jnp.float32), sharding)
    mrds = jax.device_put(jnp.asarray(mrd_rows, jnp.int32), sharding)
    tiles, scout = _mega_sharded(params, mrds, mesh=mesh,
                                 k_loc=len(rows) // n_dev, **kw)
    return tiles[:k], scout[:k]


@partial(jax.jit, static_argnames=("mesh", "definition", "max_iter", "segment",
                                   "clamp", "cycle_check"))
def _row_sharded_tile(start_r, start_i, step, *, mesh: Mesh, definition: int,
                      max_iter: int, segment: int, clamp: bool,
                      cycle_check: bool | None = None):
    n_rows = mesh.shape[ROW_AXIS]
    rows_per = definition // n_rows

    def shard_fn(sr, si, st):
        offset = lax.axis_index(ROW_AXIS) * rows_per
        c_real, c_imag = _device_grid(sr, si, st, (rows_per, definition),
                                      sr.dtype, row_offset=offset)
        counts = _masked_escape(c_real, c_imag, max_iter, segment,
                                cycle_check=cycle_check)
        if max_iter - 1 >= INT32_SCALE_LIMIT:
            counts = counts.astype(jnp.int64)
        return _scale_pixels(counts, jnp.asarray(max_iter, counts.dtype),
                             clamp)

    return shard_map(shard_fn, mesh=mesh, in_specs=(P(), P(), P()),
                     out_specs=P(ROW_AXIS))(start_r, start_i, step)


def compute_tile_row_sharded(mesh: Mesh, spec: TileSpec, max_iter: int, *,
                             dtype=np.float32, segment: int = DEFAULT_SEGMENT,
                             clamp: bool = False,
                             cycle_check: bool | None = None) -> np.ndarray:
    """One tile's rows sharded across the mesh's ``rows`` axis (latency path)."""
    n_rows = mesh.shape[ROW_AXIS]
    if spec.height % n_rows:
        raise ValueError(
            f"tile height {spec.height} not divisible by {n_rows} row shards")
    if spec.width != spec.height:
        raise ValueError("row sharding currently requires square tiles")
    if max_iter - 1 >= INT32_SCALE_LIMIT:  # int64 scaling needs x64 types
        from distributedmandelbrot_tpu.utils.precision import ensure_x64
        ensure_x64()
    step = spec.range_real / (spec.width - 1)
    out = _row_sharded_tile(jnp.asarray(spec.start_real, dtype),
                            jnp.asarray(spec.start_imag, dtype),
                            jnp.asarray(step, dtype), mesh=mesh,
                            definition=spec.width, max_iter=max_iter,
                            segment=segment, clamp=clamp,
                            cycle_check=cycle_check)
    return np.asarray(out)
