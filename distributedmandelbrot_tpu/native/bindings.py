"""numpy-facing wrappers over the native library (None-safe: callers check
:func:`distributedmandelbrot_tpu.native.build.available` or catch
``RuntimeError`` and fall back to the Python paths)."""

from __future__ import annotations

import ctypes
import sys

import numpy as np

from distributedmandelbrot_tpu.native import build

_U8P = ctypes.POINTER(ctypes.c_uint8)
_F64P = ctypes.POINTER(ctypes.c_double)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _lib():
    lib = build.load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return lib


def _u8ptr(arr: np.ndarray):
    return arr.ctypes.data_as(_U8P)


def native_supported() -> bool:
    # The record format is little-endian; the C++ writes host-endian.
    return sys.byteorder == "little" and build.available()


def rle_encoded_size(data: np.ndarray) -> int:
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    return int(_lib().dmtpu_rle_encoded_size(_u8ptr(data), data.size))


def rle_encode(data: np.ndarray) -> bytes:
    lib = _lib()
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    size = int(lib.dmtpu_rle_encoded_size(_u8ptr(data), data.size))
    out = np.empty(size, dtype=np.uint8)
    written = int(lib.dmtpu_rle_encode(_u8ptr(data), data.size,
                                       _u8ptr(out), out.size))
    if written != size:
        raise RuntimeError(f"native RLE encode wrote {written}, "
                           f"expected {size}")
    return out.tobytes()


def rle_decode(body: bytes, expected_size: int) -> np.ndarray:
    lib = _lib()
    src = np.frombuffer(body, dtype=np.uint8)
    out = np.empty(expected_size, dtype=np.uint8)
    rc = int(lib.dmtpu_rle_decode(_u8ptr(src), src.size, _u8ptr(out),
                                  out.size))
    if rc == -1:
        raise ValueError(
            f"RLE body length {len(body)} is not a multiple of 5")
    if rc == -2:
        raise ValueError("encountered RLE run of length 0")
    if rc in (-3, -4):
        raise ValueError(f"RLE decodes to the wrong total "
                         f"(expected {expected_size})")
    if rc != 0:
        raise RuntimeError(f"native RLE decode failed: {rc}")
    return out


def escape_pixels(c_real: np.ndarray, c_imag: np.ndarray, max_iter: int, *,
                  clamp: bool = False, n_threads: int = 0) -> np.ndarray:
    """uint8 pixels, bit-identical to the numpy golden path, multithreaded."""
    lib = _lib()
    c_real = np.ascontiguousarray(c_real, dtype=np.float64).ravel()
    c_imag = np.ascontiguousarray(c_imag, dtype=np.float64).ravel()
    if c_real.size != c_imag.size:
        raise ValueError("coordinate arrays must have equal size")
    out = np.empty(c_real.size, dtype=np.uint8)
    lib.dmtpu_escape_pixels_f64(
        c_real.ctypes.data_as(_F64P), c_imag.ctypes.data_as(_F64P),
        c_real.size, max_iter, int(clamp), _u8ptr(out), n_threads)
    return out


def escape_counts(c_real: np.ndarray, c_imag: np.ndarray, max_iter: int, *,
                  n_threads: int = 0) -> np.ndarray:
    """Raw int32 escape counts (for smooth coloring / analysis)."""
    lib = _lib()
    c_real = np.ascontiguousarray(c_real, dtype=np.float64).ravel()
    c_imag = np.ascontiguousarray(c_imag, dtype=np.float64).ravel()
    if c_real.size != c_imag.size:
        raise ValueError("coordinate arrays must have equal size")
    out = np.empty(c_real.size, dtype=np.int32)
    lib.dmtpu_escape_counts_f64(
        c_real.ctypes.data_as(_F64P), c_imag.ctypes.data_as(_F64P),
        c_real.size, max_iter, out.ctypes.data_as(_I32P), n_threads)
    return out


# -- arbitrary-precision fixed-point kernels (fixed.cc) --------------------

_U64P = ctypes.POINTER(ctypes.c_uint64)


def _limbs(value: int, n_limbs: int) -> np.ndarray:
    """|value| as n_limbs little-endian uint64 magnitudes."""
    return np.frombuffer(abs(value).to_bytes(n_limbs * 8, "little"),
                         dtype="<u8")


def _u64ptr(arr: np.ndarray):
    return arr.ctypes.data_as(_U64P)


def fixed_escape(za: int, zb: int, ca: int, cb: int, max_iter: int,
                 bits: int) -> int:
    """Exact-parity native replacement for the Python-bigint escape loop
    (ops/perturbation.py:_escape_count_fixed)."""
    lib = _lib()
    # Pre-escape magnitudes stay under 2^(bits+4); one guard limb
    # suffices (see fixed.cc bound analysis).
    n = (bits + 63) // 64 + 1
    four = _limbs(4 << (2 * bits), 2 * n + 1)
    args = [_limbs(za, n), 1 if za < 0 else 0,
            _limbs(zb, n), 1 if zb < 0 else 0,
            _limbs(ca, n), 1 if ca < 0 else 0,
            _limbs(cb, n), 1 if cb < 0 else 0]
    return int(lib.dmtpu_fixed_escape(
        _u64ptr(args[0]), args[1], _u64ptr(args[2]), args[3],
        _u64ptr(args[4]), args[5], _u64ptr(args[6]), args[7],
        _u64ptr(four), n, bits, max_iter))


def fixed_orbit(za: int, zb: int, ca: int, cb: int, max_iter: int,
                bits: int, extra: int
                ) -> tuple[np.ndarray, np.ndarray, int]:
    """Exact-parity native replacement for the Python-bigint orbit loop
    (ops/perturbation.py:_orbit_fixed): float64 orbit arrays plus the
    tested-orbit length."""
    lib = _lib()
    # The post-escape extension runs values up to ~10^100 * 2^bits
    # before the huge-threshold stop; six guard limbs (384 bits) cover
    # the ~333-bit growth with headroom (see fixed.cc).
    n = (bits + 63) // 64 + 6
    four = _limbs(4 << (2 * bits), 2 * n + 1)
    huge = _limbs((10 ** 100) << (2 * bits), 2 * n + 1)
    steps = max(1, max_iter)
    z_re = np.empty(steps + extra, np.float64)
    z_im = np.empty(steps + extra, np.float64)
    valid = ctypes.c_int32(0)
    args = [_limbs(za, n), 1 if za < 0 else 0,
            _limbs(zb, n), 1 if zb < 0 else 0,
            _limbs(ca, n), 1 if ca < 0 else 0,
            _limbs(cb, n), 1 if cb < 0 else 0]
    written = int(lib.dmtpu_fixed_orbit(
        _u64ptr(args[0]), args[1], _u64ptr(args[2]), args[3],
        _u64ptr(args[4]), args[5], _u64ptr(args[6]), args[7],
        _u64ptr(four), _u64ptr(huge), n, bits, max_iter, extra,
        z_re.ctypes.data_as(_F64P), z_im.ctypes.data_as(_F64P),
        ctypes.byref(valid)))
    return z_re[:written], z_im[:written], int(valid.value)


def fixed_escape_batch(points: list[tuple[int, int]], max_iter: int,
                       bits: int, julia_c: tuple[int, int] | None = None,
                       n_threads: int = 0) -> np.ndarray:
    """Escape counts for a batch of fixed-point points (the glitch-
    repair exact loop): parallelized in C++ over all cores.  ``points``
    are (za, zb) fixed-point ints; ``julia_c`` switches every point to
    the shared Julia constant."""
    lib = _lib()
    n = (bits + 63) // 64 + 1
    k = len(points)
    za = np.empty(k * n, dtype="<u8")
    zb = np.empty(k * n, dtype="<u8")
    za_neg = np.empty(k, dtype=np.uint8)
    zb_neg = np.empty(k, dtype=np.uint8)
    for i, (a, b) in enumerate(points):
        za[i * n:(i + 1) * n] = _limbs(a, n)
        zb[i * n:(i + 1) * n] = _limbs(b, n)
        za_neg[i] = 1 if a < 0 else 0
        zb_neg[i] = 1 if b < 0 else 0
    four = _limbs(4 << (2 * bits), 2 * n + 1)
    if julia_c is not None:
        ca, cb = julia_c
        ca_l, cb_l = _limbs(ca, n), _limbs(cb, n)
        ca_neg, cb_neg, julia = 1 if ca < 0 else 0, 1 if cb < 0 else 0, 1
    else:
        ca_l, cb_l = np.zeros(n, dtype="<u8"), np.zeros(n, dtype="<u8")
        ca_neg = cb_neg = julia = 0
    out = np.empty(k, dtype=np.int32)
    lib.dmtpu_fixed_escape_batch(
        _u64ptr(za), _u8ptr(za_neg), _u64ptr(zb), _u8ptr(zb_neg),
        _u64ptr(ca_l), ca_neg, _u64ptr(cb_l), cb_neg, julia,
        _u64ptr(four), n, bits, max_iter, k,
        out.ctypes.data_as(_I32P), n_threads)
    return out
