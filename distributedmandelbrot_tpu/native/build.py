"""Lazy native build: compile the C++ sources into one shared library.

Built on first use with g++ (cached; rebuilt when sources change), loaded
via ctypes.  Everything native is optional — callers fall back to the pure
numpy paths when the toolchain or library is unavailable, and
``DMTPU_NATIVE=0`` disables it outright.

``-ffp-contract=off`` is load-bearing: it keeps the escape kernel's float64
arithmetic bit-identical to the numpy golden (XLA's FMA contraction is
exactly what makes the JAX paths *non*-bit-exact; see ops/escape_time.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("dmtpu.native")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libdmtpu_native.so")
_SOURCES = ("rle.cc", "escape.cc", "fixed.cc")

_CXXFLAGS = ["-O3", "-shared", "-fPIC", "-std=c++17", "-ffp-contract=off",
             "-pthread"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime
               for s in _SOURCES)


def _build() -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    sources = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    tmp = _LIB_PATH + ".tmp"
    cmd = ["g++", *_CXXFLAGS, "-o", tmp, *sources]
    logger.info("building native library: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, _LIB_PATH)


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on demand; None when unavailable.

    Thread-safe for concurrent FIRST use: ``_tried`` is set only after
    the build/load attempt fully concludes, so a caller racing the
    builder blocks on the lock and gets the finished library — it must
    never see a half-done attempt as "unavailable" (that made two of
    three concurrently-constructed NativeBackends fall back to Python
    while the third compiled the library).
    """
    global _lib, _tried
    if _tried:
        # Attempt concluded: _lib is final (library or None-forever).
        return _lib
    with _lock:
        if _tried:
            return _lib
        try:
            if os.environ.get("DMTPU_NATIVE", "1") == "0":
                logger.info("native library disabled via DMTPU_NATIVE=0")
                return None
            try:
                if _needs_build():
                    _build()
                lib = ctypes.CDLL(_LIB_PATH)
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                logger.warning("native library unavailable, using "
                               "pure-Python paths: %s",
                               detail.strip()[:500])
                return None
            _configure(lib)
            _lib = lib
            return _lib
        finally:
            _tried = True


def _configure(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f64p = ctypes.POINTER(ctypes.c_double)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.dmtpu_rle_encoded_size.restype = ctypes.c_size_t
    lib.dmtpu_rle_encoded_size.argtypes = [u8p, ctypes.c_size_t]
    lib.dmtpu_rle_encode.restype = ctypes.c_size_t
    lib.dmtpu_rle_encode.argtypes = [u8p, ctypes.c_size_t, u8p,
                                     ctypes.c_size_t]
    lib.dmtpu_rle_decode.restype = ctypes.c_int
    lib.dmtpu_rle_decode.argtypes = [u8p, ctypes.c_size_t, u8p,
                                     ctypes.c_size_t]
    lib.dmtpu_escape_pixels_f64.restype = None
    lib.dmtpu_escape_pixels_f64.argtypes = [f64p, f64p, ctypes.c_size_t,
                                            ctypes.c_int32, ctypes.c_int,
                                            u8p, ctypes.c_int]
    lib.dmtpu_escape_counts_f64.restype = None
    lib.dmtpu_escape_counts_f64.argtypes = [f64p, f64p, ctypes.c_size_t,
                                            ctypes.c_int32, i32p,
                                            ctypes.c_int]


def available() -> bool:
    return load() is not None
