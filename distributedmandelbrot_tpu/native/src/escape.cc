// Escape-time kernel, native CPU path.
//
// The framework's bit-exact *and fast* CPU compute: per-pixel early exit
// (impossible on SIMD accelerators), scalar IEEE float64 with FP
// contraction disabled at build time (-ffp-contract=off), so results are
// bit-identical to the numpy golden (ops/reference.py) and to the
// reference semantics (DistributedMandelbrotWorkerCUDA.py:39-68): z starts
// at c, iterations count 1..max_iter-1, post-update bailout |z|^2 >= 4,
// 0 if never escaped.  uint8 scaling is exact integer ceil-division with
// the reference's wrap at 256 (or clamp to 255 in quality mode).
//
// The caller supplies the coordinate arrays (numpy linspace grids), keeping
// endpoint arithmetic bit-identical to the golden path.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Closed-form interior test (main cardioid + period-2 bulb), strict by a
// margin far above the expression's f64 rounding error, so a true result
// PROVES the exact orbit never escapes — returning 0 without iterating is
// output-identical to the full loop (mirrors
// ops/escape_time.py:mandelbrot_interior; see there for the margin math).
// This is where set-crossing tiles spend ~90%+ of their iteration budget.
inline bool provably_interior(double cr, double ci) {
    const double margin = 1e-12;
    const double y2 = ci * ci;
    const double xm = cr - 0.25;
    const double q = xm * xm + y2;
    if (q * (q + xm) < 0.25 * y2 - margin) return true;  // main cardioid
    const double xp = cr + 1.0;
    return xp * xp + y2 < 0.0625 - margin;  // period-2 bulb
}

inline std::int32_t escape_iter(double cr, double ci, std::int32_t max_iter) {
    if (provably_interior(cr, ci)) return 0;
    double zr = cr;
    double zi = ci;
    for (std::int32_t it = 1; it < max_iter; ++it) {
        const double new_zr = zr * zr - zi * zi + cr;
        const double new_zi = 2.0 * zr * zi + ci;
        zr = new_zr;
        zi = new_zi;
        if (zr * zr + zi * zi >= 4.0) return it;
    }
    return 0;
}

inline std::uint8_t scale_value(std::int64_t v, std::int64_t max_iter,
                                bool clamp) {
    std::int64_t scaled = (v * 256 + max_iter - 1) / max_iter;
    if (clamp && scaled > 255) scaled = 255;
    return static_cast<std::uint8_t>(scaled & 0xFF);
}

}  // namespace

extern "C" {

// Compute pixels for `n` points; parallelized over `n_threads` (<=0 means
// hardware concurrency).
void dmtpu_escape_pixels_f64(const double* c_real, const double* c_imag,
                             std::size_t n, std::int32_t max_iter,
                             int clamp, std::uint8_t* out, int n_threads) {
    unsigned hw = std::thread::hardware_concurrency();
    unsigned workers = n_threads > 0 ? static_cast<unsigned>(n_threads)
                                     : (hw ? hw : 1);
    if (workers > n && n > 0) workers = static_cast<unsigned>(n);
    if (workers <= 1) {
        for (std::size_t i = 0; i < n; ++i)
            out[i] = scale_value(escape_iter(c_real[i], c_imag[i], max_iter),
                                 max_iter, clamp != 0);
        return;
    }
    std::vector<std::thread> threads;
    threads.reserve(workers);
    const std::size_t stride = (n + workers - 1) / workers;
    for (unsigned t = 0; t < workers; ++t) {
        const std::size_t lo = t * stride;
        const std::size_t hi = lo + stride < n ? lo + stride : n;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            for (std::size_t i = lo; i < hi; ++i)
                out[i] = scale_value(
                    escape_iter(c_real[i], c_imag[i], max_iter),
                    max_iter, clamp != 0);
        });
    }
    for (auto& th : threads) th.join();
}

// Raw escape iteration counts (no uint8 scaling) — for smooth coloring and
// analysis paths.
void dmtpu_escape_counts_f64(const double* c_real, const double* c_imag,
                             std::size_t n, std::int32_t max_iter,
                             std::int32_t* out, int n_threads) {
    unsigned hw = std::thread::hardware_concurrency();
    unsigned workers = n_threads > 0 ? static_cast<unsigned>(n_threads)
                                     : (hw ? hw : 1);
    if (workers > n && n > 0) workers = static_cast<unsigned>(n);
    if (workers <= 1) {
        for (std::size_t i = 0; i < n; ++i)
            out[i] = escape_iter(c_real[i], c_imag[i], max_iter);
        return;
    }
    std::vector<std::thread> threads;
    threads.reserve(workers);
    const std::size_t stride = (n + workers - 1) / workers;
    for (unsigned t = 0; t < workers; ++t) {
        const std::size_t lo = t * stride;
        const std::size_t hi = lo + stride < n ? lo + stride : n;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            for (std::size_t i = lo; i < hi; ++i)
                out[i] = escape_iter(c_real[i], c_imag[i], max_iter);
        });
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
