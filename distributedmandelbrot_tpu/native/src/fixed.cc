// Arbitrary-precision fixed-point escape kernels, native CPU path.
//
// Exact replacements for the Python-bigint loops in ops/perturbation.py
// (_escape_count_fixed, _orbit_fixed): the per-pixel glitch repair and
// the reference-orbit computation are the only host-side hot loops in
// the deep-zoom path, and CPython bigints pay ~1.6 us per iteration in
// interpreter overhead where these limb loops pay tens of ns.
//
// Numbers are sign-magnitude, little-endian uint64 limbs.  Parity with
// Python's arbitrary-precision semantics is exact by construction:
//   - magnitudes never overflow their buffers (the caller sizes limb
//     counts from the algebraic bounds: values stay under 2^(bits+4)
//     in the bailout-4 count kernel and under 10^100 * 2^bits in the
//     orbit kernel, whose extension stops at the `huge` threshold);
//   - Python's `>>` on negatives is floor division, reproduced here as
//     truncate-toward-zero on the magnitude plus one when any dropped
//     bit was set;
//   - fixed -> float64 conversion mirrors _fixed_to_float's explicit
//     round-to-nearest (ties away from zero on the magnitude, exactly
//     as `(m + (1 << (shift-1))) >> shift` behaves).
//
// All scratch lives on the stack/heap per call; every entry point is
// pure and thread-safe.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using u64 = std::uint64_t;
using u128 = unsigned __int128;

// magnitude helpers ------------------------------------------------------

inline int mag_cmp(const u64* x, const u64* y, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (x[i] != y[i]) return x[i] < y[i] ? -1 : 1;
    }
    return 0;
}

inline bool mag_is_zero(const u64* x, int n) {
    for (int i = 0; i < n; ++i)
        if (x[i]) return false;
    return true;
}

// dst = x + y (all n limbs); returns the carry out.
inline u64 mag_add(u64* dst, const u64* x, const u64* y, int n) {
    u64 carry = 0;
    for (int i = 0; i < n; ++i) {
        u128 s = (u128)x[i] + y[i] + carry;
        dst[i] = (u64)s;
        carry = (u64)(s >> 64);
    }
    return carry;
}

// dst = x - y, requires x >= y.
inline void mag_sub(u64* dst, const u64* x, const u64* y, int n) {
    u64 borrow = 0;
    for (int i = 0; i < n; ++i) {
        u64 yi = y[i];
        u64 xi = x[i];
        u64 d = xi - yi - borrow;
        borrow = (xi < yi || (borrow && xi == yi)) ? 1 : 0;
        dst[i] = d;
    }
}

// dst[2n] = x[n] * y[n] (schoolbook; dst must not alias x/y).
inline void mag_mul(u64* dst, const u64* x, const u64* y, int n) {
    std::memset(dst, 0, sizeof(u64) * 2 * n);
    for (int i = 0; i < n; ++i) {
        if (!x[i]) continue;
        u64 carry = 0;
        for (int j = 0; j < n; ++j) {
            u128 cur = (u128)x[i] * y[j] + dst[i + j] + carry;
            dst[i + j] = (u64)cur;
            carry = (u64)(cur >> 64);
        }
        dst[i + n] = carry;
    }
}

// dst[dst_n] = src[src_n] >> shift on the magnitude, reporting whether
// any dropped bit was set (the floor-correction signal for negatives).
inline bool mag_shr(u64* dst, int dst_n, const u64* src, int src_n,
                    int shift) {
    const int limb = shift / 64;
    const int bit = shift % 64;
    bool dropped = false;
    for (int i = 0; i < limb && i < src_n; ++i)
        if (src[i]) dropped = true;
    if (bit && limb < src_n && (src[limb] & ((u64(1) << bit) - 1)))
        dropped = true;
    for (int i = 0; i < dst_n; ++i) {
        const int lo = i + limb;
        u64 v = lo < src_n ? src[lo] : 0;
        if (bit) {
            v >>= bit;
            if (lo + 1 < src_n) v |= src[lo + 1] << (64 - bit);
        }
        dst[i] = v;
    }
    return dropped;
}

// dst += 1 (n limbs).
inline void mag_inc(u64* dst, int n) {
    for (int i = 0; i < n; ++i) {
        if (++dst[i]) return;
    }
}

// signed helpers (sign-magnitude; neg is meaningless when mag == 0) ------

// dst = x + y with signs; n limbs each; dst may alias x.
inline void signed_add(u64* dst, bool* dst_neg, const u64* x, bool x_neg,
                       const u64* y, bool y_neg, int n) {
    if (x_neg == y_neg) {
        mag_add(dst, x, y, n);
        *dst_neg = x_neg;
        return;
    }
    const int c = mag_cmp(x, y, n);
    if (c >= 0) {
        mag_sub(dst, x, y, n);
        *dst_neg = c == 0 ? false : x_neg;
    } else {
        mag_sub(dst, y, x, n);
        *dst_neg = y_neg;
    }
}

// Python floor-shift of a signed value: truncate the magnitude, then
// add one when negative and any dropped bit was set.
inline void signed_shr(u64* dst, int dst_n, const u64* src, int src_n,
                       bool neg, int shift) {
    const bool dropped = mag_shr(dst, dst_n, src, src_n, shift);
    if (neg && dropped) mag_inc(dst, dst_n);
}

inline int bit_length(const u64* x, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (x[i]) return 64 * i + (64 - __builtin_clzll(x[i]));
    }
    return 0;
}

// _fixed_to_float parity: round-to-nearest (ties away from zero) of the
// magnitude to 53 significant bits, then ldexp.
inline double fixed_to_double(const u64* mag, int n, bool neg, int bits) {
    const int bl = bit_length(mag, n);
    if (bl == 0) return 0.0;
    double out;
    if (bl > 53) {
        const int shift = bl - 53;
        // m2 = (m + (1 << (shift-1))) >> shift without a full-width
        // add: shift first, then increment when the dropped prefix
        // means the rounding constant carries into the kept window.
        // Adding 1 << (shift-1) flips the bit at shift-1; the result's
        // kept window increments iff that bit was already 1.
        const int limb = (shift - 1) / 64;
        const int bit = (shift - 1) % 64;
        u64 kept[2] = {0, 0};
        mag_shr(kept, 2, mag, n, shift);
        const bool round_up = limb < n && (mag[limb] >> bit) & 1;
        u128 m2 = ((u128)kept[1] << 64) | kept[0];
        if (round_up) m2 += 1;
        out = std::ldexp((double)(u64)(m2 & ~u64(0)) +
                             std::ldexp((double)(u64)(m2 >> 64), 64),
                         shift - bits);
    } else {
        u128 m = ((u128)(n > 1 ? mag[1] : 0) << 64) | mag[0];
        out = std::ldexp((double)(u64)(m & ~u64(0)) +
                             std::ldexp((double)(u64)(m >> 64), 64),
                         -bits);
    }
    return neg ? -out : out;
}

// One reference-convention iteration shared by both kernels.  State a/b
// is n limbs; a2/b2/t/u are 2n-limb scratch.  Updates a, b in place:
//   a, b = ((a2 - b2) >> bits) + ca, ((a*b) >> (bits-1)) + cb
struct IterState {
    int n;
    int bits;
    std::vector<u64> a, b, na, nb;
    std::vector<u64> a2, b2, t, u, sum;
    bool a_neg = false, b_neg = false;

    IterState(int n_limbs, int bits_)
        : n(n_limbs), bits(bits_), a(n_limbs), b(n_limbs), na(n_limbs),
          nb(n_limbs), a2(2 * n_limbs), b2(2 * n_limbs), t(2 * n_limbs),
          u(2 * n_limbs), sum(2 * n_limbs + 1) {}

    void square_both() {
        mag_mul(a2.data(), a.data(), a.data(), n);
        mag_mul(b2.data(), b.data(), b.data(), n);
    }

    // a2 + b2 >= threshold?  threshold is 2n+1 limbs.
    bool mag2_at_least(const u64* threshold) {
        sum[2 * n] = mag_add(sum.data(), a2.data(), b2.data(), 2 * n);
        return mag_cmp(sum.data(), threshold, 2 * n + 1) >= 0;
    }

    void update(const u64* ca, bool ca_neg, const u64* cb, bool cb_neg) {
        // t = a2 - b2 (signed; squares are non-negative)
        bool t_neg;
        const int c = mag_cmp(a2.data(), b2.data(), 2 * n);
        if (c >= 0) {
            mag_sub(t.data(), a2.data(), b2.data(), 2 * n);
            t_neg = false;
        } else {
            mag_sub(t.data(), b2.data(), a2.data(), 2 * n);
            t_neg = true;
        }
        signed_shr(na.data(), n, t.data(), 2 * n, t_neg, bits);
        bool na_neg = t_neg && !mag_is_zero(na.data(), n);
        // u = a * b (signed)
        mag_mul(u.data(), a.data(), b.data(), n);
        const bool u_neg = (a_neg != b_neg) && !mag_is_zero(u.data(), 2 * n);
        signed_shr(nb.data(), n, u.data(), 2 * n, u_neg, bits - 1);
        bool nb_neg = u_neg && !mag_is_zero(nb.data(), n);
        signed_add(a.data(), &a_neg, na.data(), na_neg, ca, ca_neg, n);
        signed_add(b.data(), &b_neg, nb.data(), nb_neg, cb, cb_neg, n);
    }
};

}  // namespace

extern "C" {

// _escape_count_fixed parity: escape iteration in 1..max_iter-1, or 0 if
// the point never escaped bailout-4 within the budget.  All magnitudes
// are n_limbs little-endian uint64; `four` is 2*n_limbs+1 limbs holding
// 4 << (2*bits).  Caller guarantees n_limbs*64 >= bits + 64.
std::int32_t dmtpu_fixed_escape(
    const u64* za, std::int32_t za_neg, const u64* zb, std::int32_t zb_neg,
    const u64* ca, std::int32_t ca_neg, const u64* cb, std::int32_t cb_neg,
    const u64* four, std::int32_t n_limbs, std::int32_t bits,
    std::int32_t max_iter) {
    IterState s(n_limbs, bits);
    std::memcpy(s.a.data(), za, sizeof(u64) * n_limbs);
    std::memcpy(s.b.data(), zb, sizeof(u64) * n_limbs);
    s.a_neg = za_neg != 0;
    s.b_neg = zb_neg != 0;
    s.square_both();
    for (std::int32_t it = 1; it < max_iter; ++it) {
        s.update(ca, ca_neg != 0, cb, cb_neg != 0);
        s.square_both();
        if (s.mag2_at_least(four)) return it;
    }
    return 0;
}

// Batch of escape counts: k points, each with its own start (za, zb)
// packed as k consecutive n_limbs-limb magnitudes (+ per-point sign
// bytes).  Family selection: julia == 0 means Mandelbrot — each point's
// start doubles as its constant and the ca/cb arguments are ignored;
// julia == 1 means Julia — ca/cb is a SINGLE shared n_limbs-limb
// constant applied to every point.  Parallelized over n_threads (<= 0
// means hardware concurrency) — the glitch-repair exact loop hands over
// thousands of independent pixels at production tile sizes.
void dmtpu_fixed_escape_batch(
    const u64* za, const std::uint8_t* za_neg,
    const u64* zb, const std::uint8_t* zb_neg,
    const u64* ca, std::int32_t ca_neg,
    const u64* cb, std::int32_t cb_neg, std::int32_t julia,
    const u64* four, std::int32_t n_limbs, std::int32_t bits,
    std::int32_t max_iter, std::int32_t k, std::int32_t* out,
    std::int32_t n_threads) {
    unsigned hw = std::thread::hardware_concurrency();
    unsigned workers = n_threads > 0 ? static_cast<unsigned>(n_threads)
                                     : (hw ? hw : 1);
    if (k > 0 && workers > static_cast<unsigned>(k))
        workers = static_cast<unsigned>(k);
    auto run = [=](std::int32_t lo, std::int32_t hi) {
        for (std::int32_t i = lo; i < hi; ++i) {
            const u64* zai = za + (std::size_t)i * n_limbs;
            const u64* zbi = zb + (std::size_t)i * n_limbs;
            const u64* cai = julia ? ca : zai;
            const u64* cbi = julia ? cb : zbi;
            const std::int32_t cani = julia ? ca_neg : za_neg[i];
            const std::int32_t cbni = julia ? cb_neg : zb_neg[i];
            out[i] = dmtpu_fixed_escape(zai, za_neg[i], zbi, zb_neg[i],
                                        cai, cani, cbi, cbni, four,
                                        n_limbs, bits, max_iter);
        }
    };
    if (workers <= 1) {
        run(0, k);
        return;
    }
    std::vector<std::thread> threads;
    threads.reserve(workers);
    const std::int32_t stride = (k + (std::int32_t)workers - 1)
                                / (std::int32_t)workers;
    for (unsigned t = 0; t < workers; ++t) {
        const std::int32_t lo = (std::int32_t)t * stride;
        const std::int32_t hi = lo + stride < k ? lo + stride : k;
        if (lo >= hi) break;
        threads.emplace_back([=] { run(lo, hi); });
    }
    for (auto& th : threads) th.join();
}

// _orbit_fixed parity: emits float64 orbit entries z_1.. into z_re/z_im
// (capacity max(1, max_iter) + extra each), stopping `extra` entries
// past the first bailout-4 escape or earlier at the `huge` overflow
// threshold (10^100 << 2*bits, 2*n_limbs+1 limbs, matching the Python
// loop).  Returns the number of entries written; *valid_out receives
// the tested-orbit length.  Caller guarantees n_limbs*64 is comfortably
// above bits + 400 (values reach ~10^100 * 2^bits before the stop).
std::int32_t dmtpu_fixed_orbit(
    const u64* za, std::int32_t za_neg, const u64* zb, std::int32_t zb_neg,
    const u64* ca, std::int32_t ca_neg, const u64* cb, std::int32_t cb_neg,
    const u64* four, const u64* huge, std::int32_t n_limbs,
    std::int32_t bits, std::int32_t max_iter, std::int32_t extra,
    double* z_re, double* z_im, std::int32_t* valid_out) {
    const std::int32_t steps = max_iter > 1 ? max_iter : 1;
    IterState s(n_limbs, bits);
    std::memcpy(s.a.data(), za, sizeof(u64) * n_limbs);
    std::memcpy(s.b.data(), zb, sizeof(u64) * n_limbs);
    s.a_neg = za_neg != 0;
    s.b_neg = zb_neg != 0;
    std::int32_t n = 0;
    std::int32_t valid = -1;
    while (n < steps + extra) {
        z_re[n] = fixed_to_double(s.a.data(), n_limbs, s.a_neg, bits);
        z_im[n] = fixed_to_double(s.b.data(), n_limbs, s.b_neg, bits);
        ++n;
        s.square_both();
        if (valid < 0 && (n >= steps || s.mag2_at_least(four))) valid = n;
        if (valid >= 0 && (n >= valid + extra || s.mag2_at_least(huge)))
            break;
        s.update(ca, ca_neg != 0, cb, cb_neg != 0);
    }
    *valid_out = valid >= 0 ? valid : n;
    return n;
}

}  // extern "C"
