// Run-length codec, native fast path.
//
// Same record format as the Python codec (codecs/rle.py) and the reference
// (DistributedMandelbrot/DataChunkSerializer.cs:51-142): little-endian
// uint32 run length + uint8 value per record.  This file assumes a
// little-endian host (x86/ARM/TPU VM hosts all qualify); the Python layer
// keeps using the portable numpy path on anything else.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {
constexpr std::size_t kRecordSize = 5;
}

extern "C" {

// Number of bytes rle_encode would write for `n` input bytes.
std::size_t dmtpu_rle_encoded_size(const std::uint8_t* data, std::size_t n) {
    if (n == 0) return 0;
    std::size_t runs = 1;
    for (std::size_t i = 1; i < n; ++i) runs += (data[i] != data[i - 1]);
    return runs * kRecordSize;
}

// Encode into `out` (capacity `out_cap`); returns bytes written, or 0 if
// the capacity is insufficient or n == 0.
std::size_t dmtpu_rle_encode(const std::uint8_t* data, std::size_t n,
                             std::uint8_t* out, std::size_t out_cap) {
    if (n == 0) return 0;
    std::size_t pos = 0;
    std::size_t run_start = 0;
    for (std::size_t i = 1; i <= n; ++i) {
        if (i == n || data[i] != data[run_start]) {
            if (pos + kRecordSize > out_cap) return 0;
            std::uint32_t len = static_cast<std::uint32_t>(i - run_start);
            std::memcpy(out + pos, &len, 4);
            out[pos + 4] = data[run_start];
            pos += kRecordSize;
            run_start = i;
        }
    }
    return pos;
}

// Decode `body` into exactly `out_len` bytes.  Returns 0 on success,
// -1 malformed body length, -2 zero-length run, -3 output overflow,
// -4 output underfill.
int dmtpu_rle_decode(const std::uint8_t* body, std::size_t body_len,
                     std::uint8_t* out, std::size_t out_len) {
    if (body_len % kRecordSize != 0) return -1;
    std::size_t pos = 0;
    for (std::size_t i = 0; i < body_len; i += kRecordSize) {
        std::uint32_t len;
        std::memcpy(&len, body + i, 4);
        if (len == 0) return -2;
        if (pos + len > out_len) return -3;
        std::memset(out + pos, body[i + 4], len);
        pos += len;
    }
    return pos == out_len ? 0 : -4;
}

}  // extern "C"
