"""Optional native (C++) fast paths: RLE codec + bit-exact CPU escape kernel.

Everything here degrades gracefully to the pure-Python implementations when
g++ or the built library is unavailable (or ``DMTPU_NATIVE=0``).
"""

from distributedmandelbrot_tpu.native.bindings import (escape_counts,
                                                       escape_pixels,
                                                       native_supported,
                                                       rle_decode, rle_encode,
                                                       rle_encoded_size)
from distributedmandelbrot_tpu.native.build import available

__all__ = ["available", "native_supported", "rle_encode", "rle_decode",
           "rle_encoded_size", "escape_pixels", "escape_counts"]
