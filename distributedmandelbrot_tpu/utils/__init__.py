"""Cross-cutting utilities: precision, logging, metrics."""

from distributedmandelbrot_tpu.utils.precision import ensure_x64, x64_enabled

__all__ = ["ensure_x64", "x64_enabled"]
