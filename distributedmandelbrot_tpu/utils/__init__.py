"""Cross-cutting utilities: precision, logging, metrics.

The precision helpers are re-exported lazily (PEP 562): they import jax,
and an eager re-export would make *every* transitive importer of this
package (storage, serve, loadgen, the analysis CLI) require jax at
import time — the read path and the checkers are jax-free by design.
"""

__all__ = ["ensure_x64", "x64_enabled"]


def __getattr__(name: str):
    if name in __all__:
        from distributedmandelbrot_tpu.utils import precision
        return getattr(precision, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
