"""Precision helpers.

JAX disables 64-bit types by default; the float64 parity/deep-zoom paths
need them.  ``jax.config.update`` is the only mechanism that reliably works
across JAX builds (the ``JAX_ENABLE_X64`` env var is not honored by all),
so callers that are about to run an f64 kernel call :func:`ensure_x64`.
"""

from __future__ import annotations

import jax


def ensure_x64() -> None:
    """Enable 64-bit types globally (idempotent)."""
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


def x64_enabled() -> bool:
    return bool(jax.config.jax_enable_x64)
