"""Back-compat counter facade over :mod:`distributedmandelbrot_tpu.obs`.

Historically this module WAS the metrics system: a lock and a
``defaultdict(int)``.  It is now a thin shim over
:class:`~distributedmandelbrot_tpu.obs.metrics.Registry` so every
pre-registry ``counters.inc(...)`` call site lands in the same registry
the HTTP exporter serves, without touching those call sites.

Semantics preserved (and one bug fixed):

- ``inc``/``get``/``snapshot`` keep their signatures;
- ``get`` no longer MUTATES: the old ``defaultdict`` inserted every
  probed key, so asking about ``save_errors`` made it appear in
  ``snapshot()`` forever — now a missing name reads 0 and stays absent;
- legacy spellings (:data:`~distributedmandelbrot_tpu.obs.names.
  LEGACY_ALIASES`) remain readable: ``get("results_accepted")`` sums the
  ``worker_``/``coord_``-prefixed canonical counters, and ``snapshot()``
  carries both spellings, so the bench harness and the embedded
  coordinator's settle loop work against either generation of names.
"""

from __future__ import annotations

from typing import Optional

from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.names import LEGACY_ALIASES


class Counters:
    """Counter-only facade; share a :class:`Registry` to share counters."""

    def __init__(self, registry: Optional[Registry] = None) -> None:
        self.registry = registry if registry is not None else Registry()

    def inc(self, name: str, by: int = 1) -> None:
        self.registry.inc(name, by)

    def get(self, name: str) -> int:
        value = self.registry.counter_value(name)
        if value is not None:
            return value
        # Legacy spelling: sum the canonical counters behind it, which
        # reproduces what the old shared-Counters instance reported.
        total, found = 0, False
        for canonical, legacy in LEGACY_ALIASES.items():
            if legacy == name:
                v = self.registry.counter_value(canonical)
                if v is not None:
                    total += v
                    found = True
        return total if found else 0

    def snapshot(self) -> dict[str, int]:
        snap = {name: value for name, value
                in self.registry.snapshot()["counters"].items()
                if "{" not in name}  # labeled children aren't plain counts
        for canonical, legacy in LEGACY_ALIASES.items():
            if canonical in snap:
                snap[legacy] = snap.get(legacy, 0) + snap[canonical]
        return snap
