"""Minimal thread-safe counters for coordinator/worker observability.

The reference has no metrics at all (survey §5.5); these power the
coordinator's stats logging and the bench harness without pulling in a
metrics stack.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class Counters:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, int] = defaultdict(int)

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counts[name] += by

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts[name]

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)
