"""Deterministic crash-point fault injection for durability tests.

The durability subsystem's guarantees are all statements about *where* a
crash lands relative to the write order (chunk blob vs. index append vs.
checkpoint swap).  Real crashes are not schedulable, so the write paths
carry named crash points — :func:`hit` calls that are free no-ops until a
test arms them — and a test picks the exact interleaving it wants:

- in-process: :func:`arm` makes the Nth hit raise :class:`CrashPointError`,
  so a unit test can assert what the on-disk state looks like when a save
  dies between its two writes;
- cross-process: arming with ``exit=True`` (or via the ``DMTPU_CRASHPOINTS``
  environment variable, read at import) makes the Nth hit ``os._exit`` the
  whole process — a real kill, releasing flocks the way a crash does — which
  is how the kill-and-restart e2e murders a live coordinator mid-level.

Known points (grep for ``faults.hit`` to enumerate):

- ``store.before_chunk_write``  — save() after filename pick, before blob
- ``store.after_chunk_write``   — blob durable, index entry not yet appended
- ``store.after_index_append``  — index entry durable, save() not returned
- ``recovery.mid_checkpoint``   — checkpoint encoded, atomic swap not done
- ``coord.between_accept_and_persist`` — result accepted, save not scheduled

Environment syntax: ``DMTPU_CRASHPOINTS=point[:after][,point[:after]...]``
where ``after`` (default 1) is the 1-based hit count that fires.  Env-armed
points always hard-exit with :data:`CRASH_EXIT_CODE`.

**Slow points** reuse the same site names but inject latency instead of
death: :func:`arm_slow` (or ``DMTPU_SLOWPOINTS=point:seconds,...``) makes
every subsequent :func:`hit` on that point sleep — how the chaos suite
models a persist path degraded by a slow disk without killing anything.
Slow points are not one-shot; they stay armed until :func:`disarm_slow`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

ENV_VAR = "DMTPU_CRASHPOINTS"
ENV_SLOW_VAR = "DMTPU_SLOWPOINTS"
CRASH_EXIT_CODE = 86  # distinctive; tests assert the kill was ours


class CrashPointError(RuntimeError):
    """An armed in-process crash point fired."""


_lock = threading.Lock()
# point -> [remaining_hits, hard_exit]
_armed: dict[str, list] = {}
# point -> sleep seconds (every hit, until disarmed)
_slow: dict[str, float] = {}
# Fired-point observers: cb(point, hard_exit), called just before the
# crash takes effect.  A hard exit skips atexit and excepthooks, so this
# is the ONLY seam where the flight recorder (obs/flight.py) can dump
# the black box of a crashpoint-murdered process.
_on_fire: list = []


def on_fire(cb) -> None:
    """Register ``cb(point, hard_exit)`` to run when an armed point
    fires (before the raise / ``os._exit``).  Callbacks must not raise;
    failures are swallowed — dying is the point's job, not theirs."""
    with _lock:
        _on_fire.append(cb)


def _notify_fire(point: str, hard_exit: bool) -> None:
    with _lock:
        cbs = list(_on_fire)
    for cb in cbs:
        try:
            cb(point, hard_exit)
        except Exception:
            pass


def arm(point: str, *, after: int = 1, exit: bool = False) -> None:
    """Arm ``point`` to fire on its ``after``-th hit (1 = next hit)."""
    if after < 1:
        raise ValueError(f"after must be >= 1, got {after}")
    with _lock:
        _armed[point] = [after, exit]


def disarm(point: Optional[str] = None) -> None:
    """Disarm one point, or every point when ``point`` is None."""
    with _lock:
        if point is None:
            _armed.clear()
        else:
            _armed.pop(point, None)


def armed() -> dict[str, int]:
    """Remaining-hit counts by point (test introspection)."""
    with _lock:
        return {name: spec[0] for name, spec in _armed.items()}


def arm_slow(point: str, delay: float) -> None:
    """Make every hit on ``point`` sleep ``delay`` seconds (0 disarms)."""
    with _lock:
        if delay > 0:
            _slow[point] = float(delay)
        else:
            _slow.pop(point, None)


def disarm_slow(point: Optional[str] = None) -> None:
    with _lock:
        if point is None:
            _slow.clear()
        else:
            _slow.pop(point, None)


def hit(point: str) -> None:
    """Production-side hook: crash here iff a test armed this point.

    The unlocked emptiness check keeps the disarmed case free — arming
    happens strictly before the workload that should crash, never
    concurrently with it.
    """
    if _slow:
        with _lock:
            delay = _slow.get(point, 0.0)
        if delay > 0:
            time.sleep(delay)
    if not _armed:
        return
    with _lock:
        spec = _armed.get(point)
        if spec is None:
            return
        spec[0] -= 1
        if spec[0] > 0:
            return
        del _armed[point]
        hard_exit = spec[1]
    _notify_fire(point, hard_exit)
    if hard_exit:
        os._exit(CRASH_EXIT_CODE)
    raise CrashPointError(f"armed crash point {point!r} fired")


def arm_from_env(environ=os.environ) -> None:
    """Arm hard-exit points from :data:`ENV_VAR` (subprocess harness)."""
    spec = environ.get(ENV_VAR, "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, count = part.partition(":")
        arm(name, after=int(count) if count else 1, exit=True)


def arm_slow_from_env(environ=os.environ) -> None:
    """Arm latency points from :data:`ENV_SLOW_VAR` (chaos harness)."""
    spec = environ.get(ENV_SLOW_VAR, "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, secs = part.partition(":")
        arm_slow(name, float(secs) if secs else 0.05)


arm_from_env()
arm_slow_from_env()
