"""Prefetch planner: act on predicted tiles before the user asks.

Planning is synchronous and cheap (a residency peek per predicted key);
execution does the real work off the hot path — a store read promoting
the tile into the decoded LRU, falling through to
``scheduler.prioritize`` (compute-on-read at the frontier head) when the
planner has a scheduler and the store has never seen the tile.  A
read-only replica (no scheduler) still gets the cache-warming half,
which is the half that pays under flash-crowd reads.

Every planned key is *marked* against the session first, which is how
hits are scored later: a session query landing on a marked tile is a
prefetch hit, anything else a miss — the ratio gauge is the live
quality signal for the predictor.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.serve.cache import DecodedTileCache
from distributedmandelbrot_tpu.sessions.predict import TrajectoryPredictor
from distributedmandelbrot_tpu.sessions.table import Key, SessionState
from distributedmandelbrot_tpu.utils.metrics import Counters


class PrefetchPlanner:
    def __init__(self, cache: DecodedTileCache, *,
                 predictor: Optional[TrajectoryPredictor] = None,
                 scheduler=None,
                 counters: Optional[Counters] = None) -> None:
        self.cache = cache
        self.predictor = predictor if predictor is not None \
            else TrajectoryPredictor()
        # Duck-typed coordinator.scheduler.TileScheduler (prioritize,
        # level_settings); None on read-only replicas.
        self.scheduler = scheduler
        self._level_max_iter: dict[int, int] = {}
        if scheduler is not None:
            self._level_max_iter = {s.level: s.max_iter
                                    for s in scheduler.level_settings}
        self.counters = counters if counters is not None else Counters()

    def plan(self, state: SessionState) -> list[Key]:
        """Predicted tiles in the run's range, marked against the
        session.  Marks record *prediction* — a later query on a marked
        tile is a hit whether or not warming was needed, so the ratio
        gauge stays a predictor-quality signal on a warm cache.  Keys
        already resident in tier 1 are still marked but not returned
        for execution (nothing to warm)."""
        picked: list[Key] = []
        planned = 0
        for key in self.predictor.predict(state.trajectory()):
            level, index_real, index_imag = key
            if not proto.query_in_range(level, index_real, index_imag):
                continue
            if not state.mark_prefetched(key):
                continue
            planned += 1
            if not self.cache.contains(key):
                picked.append(key)
        if planned:
            self.counters.inc(obs_names.PREFETCH_PLANNED, planned)
        return picked

    async def execute(self, keys: list[Key]) -> None:
        """Warm each planned tile; store misses fall through to
        compute-on-read when a scheduler is attached."""
        for key in keys:
            entry = await asyncio.to_thread(self.cache.load, key)
            if entry is not None:
                self.counters.inc(obs_names.PREFETCH_WARMED)
                continue
            if self.scheduler is None:
                continue
            level, index_real, index_imag = key
            max_iter = self._level_max_iter.get(level)
            if max_iter is None:
                continue
            if self.scheduler.prioritize(
                    Workload(level, max_iter, index_real, index_imag)):
                self.counters.inc(obs_names.PREFETCH_SCHEDULED)
