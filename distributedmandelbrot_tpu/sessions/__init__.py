"""Live interactive sessions: viewport tracking, predictive prefetch,
progressive refinement, per-session fairness.

The stateful layer behind the gateway's ``GATEWAY_SESSION_MAGIC``
framing.  :class:`SessionTable` issues ids and tracks each session's
viewport trajectory; :class:`TrajectoryPredictor` extrapolates pan/zoom
velocity in ``(level, i, j)`` space; :class:`PrefetchPlanner` warms the
cache tiers (or queues compute-on-read) for the predicted tiles before
the user asks; :class:`RefinementTracker` schedules the full-depth
workload behind a cheap low-``max_iter`` first paint; and
:class:`SessionService` is the facade the gateway drives.

The package depends on :mod:`~distributedmandelbrot_tpu.serve` (caches,
token bucket) and optionally a coordinator scheduler — never the other
way round: the gateway takes its ``SessionService`` duck-typed, so a
read-only replica (loadgen's ``GatewayFleet``) runs sessions with
prefetch-by-cache-warming and no farm at all.
"""

from distributedmandelbrot_tpu.sessions.predict import (TrajectoryPredictor,
                                                        predict_tiles)
from distributedmandelbrot_tpu.sessions.prefetch import PrefetchPlanner
from distributedmandelbrot_tpu.sessions.refine import RefinementTracker
from distributedmandelbrot_tpu.sessions.service import (SessionService,
                                                        build_session_service)
from distributedmandelbrot_tpu.sessions.table import (SessionState,
                                                      SessionTable,
                                                      ViewportObs)

__all__ = [
    "PrefetchPlanner",
    "RefinementTracker",
    "SessionService",
    "SessionState",
    "SessionTable",
    "TrajectoryPredictor",
    "ViewportObs",
    "build_session_service",
    "predict_tiles",
]
