"""Trajectory predictor: extrapolate pan/zoom velocity in (level, i, j).

Pure and deterministic — no clocks, no randomness — so the virtual-time
session tests pin exact predictions.

The extrapolation is *step-scaled*: velocities are estimated per mean
inter-arrival gap of the observation window, and predictions are emitted
at 1..horizon such steps ahead.  That makes the output depend on the
direction and per-step magnitude of motion, not on the absolute clock
rate, so a storm replayed under the loadgen virtual timebase (where
consecutive queries land microseconds apart in wall time) predicts the
same tiles a human panning once a second would get.

Pan is extrapolated in fractional viewport coordinates — the tile-center
fraction ``(i + 0.5) / level`` — so a simultaneous zoom rescales the pan
component onto the target grid instead of carrying level-``n`` indices
onto a level-``m`` grid.  Zoom is a per-step level delta, rounded.  The
caller range-checks the emitted keys (``query_in_range``); this module
just does the math.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

from distributedmandelbrot_tpu.sessions.table import Key, ViewportObs


def predict_tiles(trajectory: Sequence[ViewportObs], *,
                  horizon: int = 3) -> list[Key]:
    """Predicted next tile keys, nearest first.

    Returns ``[]`` without a usable fix: fewer than two observations, a
    non-advancing clock, or a stationary viewport (every prediction
    collapses onto the current tile).
    """
    if len(trajectory) < 2 or horizon <= 0:
        return []
    first, last = trajectory[0], trajectory[-1]
    steps = len(trajectory) - 1
    if last.t <= first.t:
        return []
    # Per-step velocities over the window endpoints: level delta (zoom)
    # and tile-center fraction delta (pan).
    d_level = (last.level - first.level) / steps
    fx_first = (first.index_real + 0.5) / first.level
    fy_first = (first.index_imag + 0.5) / first.level
    fx_last = (last.index_real + 0.5) / last.level
    fy_last = (last.index_imag + 0.5) / last.level
    d_fx = (fx_last - fx_first) / steps
    d_fy = (fy_last - fy_first) / steps
    predicted: list[Key] = []
    seen = {last.key}
    for k in range(1, horizon + 1):
        level = int(round(last.level + d_level * k))
        if level < 1:
            continue
        index_real = math.floor((fx_last + d_fx * k) * level)
        index_imag = math.floor((fy_last + d_fy * k) * level)
        key = (level, index_real, index_imag)
        if key in seen:
            continue
        seen.add(key)
        predicted.append(key)
    return predicted


class TrajectoryPredictor:
    """Configured wrapper around :func:`predict_tiles`."""

    def __init__(self, *, horizon: int = 3) -> None:
        self.horizon = horizon

    def predict(self, trajectory: Iterable[ViewportObs]) -> list[Key]:
        return predict_tiles(tuple(trajectory), horizon=self.horizon)
