"""Session service facade: what the gateway actually drives.

Bundles the table, the prefetch planner, and the refinement tracker
behind the handful of calls the gateway's session arm makes per query.
Capability grants fall out of construction: the prefetch bit is offered
iff a planner exists, the refine bit iff a tracker exists and a first
paint depth is configured — so a read-only replica naturally negotiates
refinement away while still granting prefetch.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.serve.cache import DecodedTileCache
from distributedmandelbrot_tpu.sessions.prefetch import PrefetchPlanner
from distributedmandelbrot_tpu.sessions.refine import RefinementTracker
from distributedmandelbrot_tpu.sessions.table import (Key, SessionState,
                                                      SessionTable)
from distributedmandelbrot_tpu.utils.metrics import Counters


class SessionService:
    def __init__(self, table: SessionTable, *,
                 planner: Optional[PrefetchPlanner] = None,
                 refiner: Optional[RefinementTracker] = None,
                 first_paint_max_iter: int = 0,
                 counters: Optional[Counters] = None) -> None:
        self.table = table
        self.planner = planner
        self.refiner = refiner
        self.first_paint_max_iter = first_paint_max_iter
        self.counters = counters if counters is not None else Counters()
        registry = self.counters.registry

        def _hit_ratio() -> float:
            hits = registry.counter_value(obs_names.PREFETCH_HITS) or 0
            misses = registry.counter_value(obs_names.PREFETCH_MISSES) or 0
            total = hits + misses
            return hits / total if total else 0.0

        registry.gauge(obs_names.GAUGE_PREFETCH_HIT_RATIO,
                       help="session queries landing on prefetched tiles",
                       fn=_hit_ratio)

    @property
    def caps(self) -> int:
        """Capability bits this gateway grants (requested ∩ these)."""
        caps = 0
        if self.planner is not None:
            caps |= proto.SESSION_CAP_PREFETCH
        if self.refiner is not None and self.first_paint_max_iter > 0:
            caps |= proto.SESSION_CAP_REFINE
        return caps

    # -- session lifecycle -------------------------------------------------

    def open(self, requested_flags: int) -> SessionState:
        return self.table.open(requested_flags & self.caps)

    def touch(self, session_id: int) -> Optional[SessionState]:
        return self.table.touch(session_id)

    # -- per-query path ----------------------------------------------------

    def note_query(self, state: SessionState, level: int, index_real: int,
                   index_imag: int) -> list[Key]:
        """Record the viewport observation, score the prefetch verdict
        for this tile, and return freshly planned prefetch keys (hand
        them to :meth:`prefetch` off the response path)."""
        prefetching = bool(state.caps & proto.SESSION_CAP_PREFETCH)
        if prefetching:
            if state.consume_prefetch((level, index_real, index_imag)):
                self.counters.inc(obs_names.PREFETCH_HITS)
            else:
                self.counters.inc(obs_names.PREFETCH_MISSES)
        state.observe(level, index_real, index_imag, self.table.now())
        if not prefetching or self.planner is None:
            return []
        return self.planner.plan(state)

    async def prefetch(self, keys: list[Key]) -> None:
        if self.planner is not None and keys:
            await self.planner.execute(keys)

    # -- progressive refinement --------------------------------------------

    def first_paint_iter(self, full_max_iter: Optional[int]) -> Optional[int]:
        """The cheap depth for a first paint, or ``None`` when refinement
        cannot apply (disabled, unknown level, or full depth already at
        or below the first-paint budget)."""
        if self.refiner is None or self.first_paint_max_iter <= 0:
            return None
        if full_max_iter is None \
                or full_max_iter <= self.first_paint_max_iter:
            return None
        return self.first_paint_max_iter

    def schedule_refine(self, w: Workload) -> bool:
        if self.refiner is None:
            return False
        return self.refiner.schedule(w)

    def on_chunk_saved(self, key: Key) -> None:
        if self.refiner is not None:
            self.refiner.on_saved(key)

    def varz(self) -> dict:
        out = self.table.varz()
        out["caps"] = self.caps
        out["prefetch"] = {
            "planned": self.counters.get(obs_names.PREFETCH_PLANNED),
            "warmed": self.counters.get(obs_names.PREFETCH_WARMED),
            "scheduled": self.counters.get(obs_names.PREFETCH_SCHEDULED),
            "hits": self.counters.get(obs_names.PREFETCH_HITS),
            "misses": self.counters.get(obs_names.PREFETCH_MISSES),
        }
        out["refine"] = {
            "first_paint_max_iter": self.first_paint_max_iter,
            "pending": self.refiner.pending if self.refiner else 0,
            "scheduled": self.counters.get(
                obs_names.SESSION_REFINES_SCHEDULED),
            "completed": self.counters.get(
                obs_names.SESSION_REFINES_COMPLETED),
        }
        return out


def build_session_service(
        cache: DecodedTileCache, *, scheduler=None,
        counters: Optional[Counters] = None,
        clock: Callable[[], float] = time.monotonic,
        session_capacity: int = 1024,
        session_ttl: Optional[float] = 300.0,
        session_rate: Optional[float] = None,
        session_burst: float = 32.0,
        prefetch_horizon: int = 3,
        first_paint_max_iter: int = 64) -> SessionService:
    """Wire a full service over one cache and (optionally) a scheduler.

    With no scheduler the service still tracks trajectories and warms
    the cache tiers, but offers neither compute-on-read prefetch nor
    refinement — read-only replicas negotiate those away.
    """
    from distributedmandelbrot_tpu.sessions.predict import TrajectoryPredictor
    table = SessionTable(capacity=session_capacity, ttl=session_ttl,
                         session_rate=session_rate,
                         session_burst=session_burst,
                         clock=clock, counters=counters)
    planner = PrefetchPlanner(
        cache, predictor=TrajectoryPredictor(horizon=prefetch_horizon),
        scheduler=scheduler, counters=counters)
    refiner = RefinementTracker(scheduler, counters=counters) \
        if scheduler is not None else None
    return SessionService(table, planner=planner, refiner=refiner,
                          first_paint_max_iter=first_paint_max_iter,
                          counters=counters)
