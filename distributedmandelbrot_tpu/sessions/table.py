"""Session table: id issuance, viewport trajectories, LRU/TTL expiry.

One :class:`SessionState` per live viewer session.  The table is
thread-safe (the gateway's event loop touches it inline while varz
scrapes read from the exporter thread) and bounded two ways: ``capacity``
evicts the least-recently-touched session, ``ttl`` expires idle ones —
lazily on :meth:`SessionTable.touch` and in bulk via
:meth:`SessionTable.sweep`.  An evicted/expired session is not an error
on the wire: the client's next query gets the soft unknown-session
reject and reopens with id 0.

The clock is injectable so expiry, fairness refill, and trajectory
timestamps are all deterministic under the loadgen virtual timebase.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Optional

from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.serve.gateway import TokenBucket
from distributedmandelbrot_tpu.utils.metrics import Counters

Key = tuple[int, int, int]

# Per-session prefetch marks kept at most; oldest marks fall off first.
# A mark is one predicted tile awaiting its hit/miss verdict — a pan at
# human speed never holds more than a handful.
MAX_PREFETCH_MARKS = 128


@dataclass(frozen=True)
class ViewportObs:
    """One observed viewport sample on a session's trajectory."""

    t: float
    level: int
    index_real: int
    index_imag: int

    @property
    def key(self) -> Key:
        return (self.level, self.index_real, self.index_imag)


class SessionState:
    """One live session: granted capabilities, the trajectory ring, the
    private admission budget, and outstanding prefetch marks.

    ``weight`` scales the session's token budget (weighted fair
    admission): a weight-2 session refills twice as fast and bursts
    twice as deep as a weight-1 one under the same configured rate.
    """

    __slots__ = ("session_id", "caps", "weight", "bucket", "created",
                 "last_seen", "_trajectory", "_prefetched")

    def __init__(self, session_id: int, caps: int, *, weight: float = 1.0,
                 rate: Optional[float] = None, burst: float = 32.0,
                 trajectory_len: int = 8,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.session_id = session_id
        self.caps = caps
        self.weight = weight
        scaled_rate = rate * weight if rate is not None and rate > 0 else rate
        self.bucket = TokenBucket(scaled_rate, burst * weight, clock=clock)
        self.created = clock()
        self.last_seen = self.created
        self._trajectory: deque[ViewportObs] = deque(maxlen=trajectory_len)
        self._prefetched: OrderedDict[Key, None] = OrderedDict()

    def observe(self, level: int, index_real: int, index_imag: int,
                now: float) -> None:
        """Append a viewport sample; the ring keeps the newest ``maxlen``."""
        self._trajectory.append(ViewportObs(now, level, index_real,
                                            index_imag))
        self.last_seen = now

    def trajectory(self) -> tuple[ViewportObs, ...]:
        return tuple(self._trajectory)

    def admit(self) -> bool:
        """Charge one query against this session's budget."""
        return self.bucket.try_acquire()

    def mark_prefetched(self, key: Key) -> bool:
        """Remember that ``key`` was prefetched for this session; False
        if it is already marked (don't replan the same tile)."""
        if key in self._prefetched:
            return False
        self._prefetched[key] = None
        while len(self._prefetched) > MAX_PREFETCH_MARKS:
            self._prefetched.popitem(last=False)
        return True

    def consume_prefetch(self, key: Key) -> bool:
        """Pop ``key``'s mark if present — the query landed on a
        predicted tile (a prefetch hit)."""
        if key in self._prefetched:
            del self._prefetched[key]
            return True
        return False


class SessionTable:
    """Thread-safe registry of live sessions.

    Ids are issued monotonically from 1 — 0 is the wire's open-a-session
    sentinel, so it can never name a live entry.  ``session_rate`` /
    ``session_burst`` parameterize each session's private token budget
    (``None`` rate admits everything — fairness off).
    """

    def __init__(self, *, capacity: int = 1024, ttl: Optional[float] = 300.0,
                 trajectory_len: int = 8,
                 session_rate: Optional[float] = None,
                 session_burst: float = 32.0,
                 clock: Callable[[], float] = time.monotonic,
                 counters: Optional[Counters] = None) -> None:
        self.capacity = capacity
        self.ttl = ttl
        self.trajectory_len = trajectory_len
        self.session_rate = session_rate
        self.session_burst = session_burst
        self.clock = clock
        self.counters = counters if counters is not None else Counters()
        self._sessions: OrderedDict[int, SessionState] = OrderedDict()
        self._lock = threading.Lock()
        self._next_id = 0
        self.counters.registry.gauge(
            obs_names.GAUGE_SESSIONS_ACTIVE,
            help="live interactive sessions",
            fn=lambda: float(len(self)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def now(self) -> float:
        return self.clock()

    def open(self, caps: int, *, weight: float = 1.0) -> SessionState:
        """Issue a new session with the given granted capability bits."""
        with self._lock:
            self._next_id += 1
            state = SessionState(self._next_id, caps, weight=weight,
                                 rate=self.session_rate,
                                 burst=self.session_burst,
                                 trajectory_len=self.trajectory_len,
                                 clock=self.clock)
            self._sessions[state.session_id] = state
            evicted = 0
            while len(self._sessions) > self.capacity:
                self._sessions.popitem(last=False)
                evicted += 1
        self.counters.inc(obs_names.SESSION_OPENS)
        if evicted:
            self.counters.inc(obs_names.SESSION_EVICTED, evicted)
        return state

    def touch(self, session_id: int) -> Optional[SessionState]:
        """Look up a live session, refreshing its LRU position and idle
        clock; ``None`` for unknown or just-expired ids.

        ``session_id`` arrives straight off the wire — it is only ever
        a dict-key probe here, never an index.
        """
        now = self.clock()
        expired = False
        with self._lock:
            state = self._sessions.get(session_id)
            if state is None:
                return None
            if self.ttl is not None and now - state.last_seen > self.ttl:
                del self._sessions[session_id]
                state = None
                expired = True
            else:
                self._sessions.move_to_end(session_id)
                state.last_seen = now
        if expired:
            self.counters.inc(obs_names.SESSION_EXPIRED)
        return state

    def sweep(self) -> int:
        """Expire every idle session in one pass (periodic maintenance —
        touch already expires lazily, this reclaims sessions nobody
        queries again)."""
        if self.ttl is None:
            return 0
        now = self.clock()
        with self._lock:
            idle = [sid for sid, s in self._sessions.items()
                    if now - s.last_seen > self.ttl]
            for sid in idle:
                del self._sessions[sid]
        if idle:
            self.counters.inc(obs_names.SESSION_EXPIRED, len(idle))
        return len(idle)

    def varz(self) -> dict:
        """Aggregate view for the /varz debug page."""
        with self._lock:
            active = len(self._sessions)
            issued = self._next_id
        return {
            "active": active,
            "issued": issued,
            "capacity": self.capacity,
            "ttl": self.ttl,
            "opened": self.counters.get(obs_names.SESSION_OPENS),
            "expired": self.counters.get(obs_names.SESSION_EXPIRED),
            "evicted": self.counters.get(obs_names.SESSION_EVICTED),
        }
