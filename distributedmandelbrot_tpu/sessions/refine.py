"""Progressive refinement: full-depth compute behind a cheap first paint.

A session's first paint of a cold tile is served from a low-``max_iter``
workload — a fraction of the iteration cost, so the user sees pixels
fast.  This tracker then hands the *full-depth* workload back to the
scheduler (``scheduler.refine``: un-complete the 3-tuple, queue at the
frontier head) and remembers the key until the deep variant's save lands
(the coordinator's save hook calls :meth:`on_saved`, right after the
decoded/rendered cache tiers dropped their stale shallow entries).
The workload 4-tuple keys the store by ``max_iter``, so both variants
coexist on disk; reads always see the newest save.
"""

from __future__ import annotations

import threading
from typing import Optional

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.sessions.table import Key
from distributedmandelbrot_tpu.utils.metrics import Counters


class RefinementTracker:
    def __init__(self, scheduler, *,
                 counters: Optional[Counters] = None) -> None:
        # Duck-typed coordinator.scheduler.TileScheduler (refine).
        self.scheduler = scheduler
        self.counters = counters if counters is not None else Counters()
        self._pending: set[Key] = set()
        self._lock = threading.Lock()

    def schedule(self, w: Workload) -> bool:
        """Queue the full-depth workload behind a just-served first
        paint; idempotent while the refinement is in flight."""
        with self._lock:
            if w.key in self._pending:
                return True
        if not self.scheduler.refine(w):
            return False
        with self._lock:
            self._pending.add(w.key)
        self.counters.inc(obs_names.SESSION_REFINES_SCHEDULED)
        return True

    def on_saved(self, key: Key) -> None:
        """A chunk save landed; if it was a pending refinement, the deep
        variant is now durable and the refinement is done."""
        with self._lock:
            if key not in self._pending:
                return
            self._pending.discard(key)
        self.counters.inc(obs_names.SESSION_REFINES_COMPLETED)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)
