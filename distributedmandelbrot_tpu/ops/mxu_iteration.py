"""MXU reformulation of the escape iteration map (opt-in, parity-gated).

The escape loop is VPU-bound (BENCH_r05: 0.874 VPU utilization with the
MXU idle).  "Accelerating Compact Fractals with Tensor Core GPUs"
(PAPERS.md) shows the complex square can ride the matrix units instead:
embed ``z = zr + i*zi`` as the 2x2 rotation-scaling matrix
``[[zr, -zi], [zi, zr]]`` — complex multiplication IS multiplication of
these matrices — and one iteration ``z <- z^2 + c`` becomes a batched
2x2 matmul over the pixel-block panel plus a vector add:

    [zr']   [zr  -zi] [zr]   [cr]
    [zi'] = [zi   zr] [zi] + [ci]

:func:`mxu_step` expresses exactly that with ``lax.dot_general`` (batch
dims = the panel, a 2-element contraction), which Mosaic/XLA can place
on the matrix units, freeing VPU issue slots for the escape test and
count bookkeeping that must stay elementwise.

The gate (mirroring ``ops/mixed_precision.py``'s opt-in contract):

- **off** (default) — ``DMTPU_MXU`` unset/0: nothing changes.
- **full** — ``DMTPU_MXU=1`` *and* :func:`mxu_parity_proven`: the kernel
  recurrence itself runs through :func:`mxu_step`.  Escape counts are a
  bit-exact contract, so full mode is admitted only where the probe
  shows the matmul form rounds identically to the VPU form (a
  2-term dot may contract into an FMA or reassociate — platform
  dependent; f32-via-bf16x3 on real MXU passes never qualifies).
- **census** — ``DMTPU_MXU=1`` but parity unproven: the MXU form runs
  only as an *advisory* shadow (:func:`mxu_census_counts`, a bf16
  panel census like the bf16 scout) and never feeds outputs — the same
  parity-guard contract as ``ops/mixed_precision.py``, which this
  module imports as its sanctioned half-precision gateway.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.ops.mixed_precision import (scout_cast,
                                                           scout_const)

# The opt-in env gate (unset/0 = off); see the module docstring.
MXU_ENV = "DMTPU_MXU"

# Probe geometry: a fixed panel spanning the escape-relevant dynamic
# range, iterated long enough for one ulp of divergence to surface
# (divergence compounds exponentially on boundary orbits, so 32 steps
# exposes any rounding difference the first step introduces).
_PROBE_N = 64
_PROBE_STEPS = 32

# Census panel edge: the advisory shadow samples the tile on a coarse
# sub-grid so a whole batch costs a fraction of one real segment.
CENSUS_PANEL = 32

_parity_cache: dict[str, bool] = {}


def mxu_step(zr, zi, c_real, c_imag):
    """One ``z <- z^2 + c`` step in the 2x2 rotation-matrix matmul form.

    Panel-batched: ``zr/zi/c_real/c_imag`` share any leading shape; the
    dot contracts the trailing 2-vector against the per-pixel 2x2
    embed.  Mathematically identical to the VPU form (``zr^2 - zi^2 +
    cr``, ``2*zr*zi + ci``); bit-identity depends on how the platform
    rounds the 2-term contraction, which is exactly what
    :func:`mxu_parity_proven` probes."""
    state = jnp.stack([zr, zi], axis=-1)
    embed = jnp.stack([jnp.stack([zr, -zi], axis=-1),
                       jnp.stack([zi, zr], axis=-1)], axis=-2)
    n_batch = state.ndim - 1
    batch = tuple(range(n_batch))
    sq = lax.dot_general(
        embed, state,
        dimension_numbers=(((embed.ndim - 1,), (state.ndim - 1,)), (batch, batch)),
        preferred_element_type=state.dtype)
    return sq[..., 0] + c_real, sq[..., 1] + c_imag


def _probe_vpu(zr, zi, c_real, c_imag):
    """The kernel recurrence's exact rounding order (_run_seg_loop's
    cached-squares form), chained _PROBE_STEPS times."""
    zr2 = zr * zr
    zi2 = zi * zi
    for _ in range(_PROBE_STEPS):
        cross = (zr + zr) * zi
        zi = cross + c_imag
        zr = zr2 - zi2 + c_real
        zr2 = zr * zr
        zi2 = zi * zi
    return zr, zi


def _probe_mxu(zr, zi, c_real, c_imag):
    for _ in range(_PROBE_STEPS):
        zr, zi = mxu_step(zr, zi, c_real, c_imag)
    return zr, zi


def mxu_parity_proven() -> bool:
    """True when the matmul form rounds bit-identically to the VPU form
    on this platform (cached per process; NaN lanes compared as bit
    patterns, so an orbit that overflows to inf/NaN must do so in both
    forms to pass)."""
    key = jax.default_backend()
    hit = _parity_cache.get(key)
    if hit is not None:
        return hit
    xs = np.linspace(-2.0, 1.0, _PROBE_N, dtype=np.float32)
    ys = np.linspace(-1.5, 1.5, _PROBE_N, dtype=np.float32)
    cr, ci = np.meshgrid(xs, ys)
    args = (jnp.asarray(cr), jnp.asarray(ci), jnp.asarray(cr),
            jnp.asarray(ci))
    v = jax.jit(_probe_vpu)(*args)
    m = jax.jit(_probe_mxu)(*args)
    proven = all(
        np.array_equal(np.asarray(a).view(np.int32),
                       np.asarray(b).view(np.int32))
        for a, b in zip(v, m))
    _parity_cache[key] = proven
    return proven


def mxu_mode() -> str:
    """Resolve the gate: ``"off"`` / ``"census"`` / ``"full"`` (see the
    module docstring).  Full requires proven bit-parity; an enabled but
    unproven platform demotes to the advisory census."""
    if os.environ.get(MXU_ENV, "0") == "0":
        return "off"
    return "full" if mxu_parity_proven() else "census"


def reset_mxu_cache() -> None:
    """Drop the cached parity verdict (tests that monkeypatch platforms)."""
    _parity_cache.clear()


@partial(jax.jit, static_argnames=("k", "panel", "steps"))
def _census_panel(params, mrds, *, k: int, panel: int, steps: int):
    """bf16 MXU-form shadow over a coarse per-tile panel: count pixels
    predicted to escape within ``steps`` iterations (capped by each
    tile's own budget).  Advisory only — bf16 orbits diverge on boundary
    pixels and the panel undersamples; both are fine for an occupancy
    census (the parity-guard contract)."""
    col = lax.broadcasted_iota(jnp.int32, (k, panel, panel), 2)
    row = lax.broadcasted_iota(jnp.int32, (k, panel, panel), 1)
    start_r = params[:, 0][:, None, None]
    start_i = params[:, 1][:, None, None]
    step_r = params[:, 2][:, None, None]
    step_i = params[:, 3][:, None, None]
    c_real = scout_cast(start_r + col.astype(jnp.float32) * step_r)
    c_imag = scout_cast(start_i + row.astype(jnp.float32) * step_i)
    four = scout_const(4.0)
    zr = c_real
    zi = c_imag
    act = jnp.ones((k, panel, panel), jnp.int32)
    esc = jnp.zeros((k, panel, panel), jnp.int32)
    for it in range(steps):
        zr, zi = mxu_step(zr, zi, c_real, c_imag)
        in_budget = jnp.asarray(it + 1, jnp.int32) <= mrds[:, None, None]
        hit = jnp.where((zr * zr + zi * zi >= four) & in_budget, act, 0)
        esc = esc + hit
        act = act - hit
    return jnp.sum(esc, axis=(1, 2))


def mxu_census_counts(params, max_iters, *, height: int, width: int,
                      steps: int = 64,
                      panel: int = CENSUS_PANEL) -> np.ndarray:
    """The census-only fallback: run the bf16 MXU-form shadow on a
    ``panel x panel`` sub-grid of each tile and return the per-tile
    count of panel pixels predicted to escape within ``min(steps,
    budget)`` iterations.  ``params`` is the kernel's (k, 4) per-axis
    pitch rows (host array); the pitch is stretched by
    ``(extent - 1) / (panel - 1)`` so the panel spans the same complex
    window the full ``height x width`` tile covers."""
    params = np.array(params, np.float32, copy=True)
    k = params.shape[0]
    if k == 0:
        return np.zeros((0,), np.int32)
    if panel > 1:
        params[:, 2] *= (width - 1) / (panel - 1)
        params[:, 3] *= (height - 1) / (panel - 1)
    mrds = jnp.asarray([int(m) for m in max_iters], jnp.int32)
    out = _census_panel(jnp.asarray(params), mrds, k=k, panel=int(panel),
                        steps=int(steps))
    return np.asarray(out, np.int32)
