"""Pallas TPU escape-time kernel.

Why a hand kernel when XLA already fuses the masked loop: *block-granular
early exit*.  The XLA path's segmented ``while_loop`` iterates until the
slowest pixel of the whole tile finishes; this kernel walks the tile in
``(block_h, width)`` VMEM blocks — the grid is sequential on a TPU core —
and each block runs its own escape loop, exiting as soon as *its* pixels
are done.  On mixed tiles (fast-escaping sky + deep interior) that recovers
most of the CUDA reference's per-pixel early-return
(``DistributedMandelbrotWorkerCUDA.py:62-67``) without divergent control
flow: VPU-friendly masked math inside, coarse-grained exit outside.

Everything stays on device: coordinates are generated in-kernel from three
scalars (SMEM), output is the uint8 tile block (VMEM), no HBM coordinate
traffic at all.  f32 only — this is the TPU throughput path; parity
anchors live elsewhere (see ops/escape_time.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.ops.escape_time import escape_loop


def _pallas():
    """Import pallas lazily: on some builds the import itself fails unless
    the TPU platform plugin registered (e.g. CPU-forced test processes)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl, pltpu

DEFAULT_BLOCK_H = 128  # 5 f32 + 1 i32 carries x 128x1024 ~ 3 MB, well under
                       # the ~16 MB scoped-VMEM limit (256 rows OOMed at 23.5M)
DEFAULT_SEGMENT = 32


def _escape_block_kernel(params_ref, out_ref, *, max_iter: int, segment: int,
                         block_h: int, clamp: bool):
    """One (block_h, W) block: device grid -> masked escape loop -> uint8."""
    pl, _ = _pallas()
    i = pl.program_id(0)
    start_r = params_ref[0, 0]
    start_i = params_ref[0, 1]
    step = params_ref[0, 2]
    shape = out_ref.shape
    dtype = params_ref.dtype

    col = lax.broadcasted_iota(jnp.int32, shape, 1)
    row = lax.broadcasted_iota(jnp.int32, shape, 0) + i * block_h
    c_real = start_r + col.astype(dtype) * step
    c_imag = start_i + row.astype(dtype) * step

    total_steps = max_iter - 1

    # Shared recurrence with the XLA/sharded paths — see
    # ops/escape_time.py:escape_loop for the select-free form, the sticky
    # active mask, and the count recovery.
    if total_steps <= 0:
        counts = jnp.zeros(shape, jnp.int32)
    else:
        counts = escape_loop(c_real, c_imag, c_real, c_imag,
                             total_steps=total_steps, segment=segment)

    vals = (counts * 256 + (max_iter - 1)) // max_iter
    if clamp:
        vals = jnp.minimum(vals, 255)
    out_ref[:] = vals.astype(jnp.uint8)


@partial(jax.jit, static_argnames=("height", "width", "max_iter", "segment",
                                   "block_h", "clamp", "interpret"))
def _pallas_escape(params, *, height: int, width: int, max_iter: int,
                   segment: int = DEFAULT_SEGMENT,
                   block_h: int = DEFAULT_BLOCK_H, clamp: bool = False,
                   interpret: bool = False):
    pl, pltpu = _pallas()
    kernel = partial(_escape_block_kernel, max_iter=max_iter,
                     segment=max(1, min(segment, max(1, max_iter - 1))),
                     block_h=block_h, clamp=clamp)
    return pl.pallas_call(
        kernel,
        grid=(height // block_h,),
        in_specs=[pl.BlockSpec((1, 3), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((block_h, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((height, width), jnp.uint8),
        interpret=interpret,
    )(params)


def pallas_available() -> bool:
    """True when pallas imports and a TPU backend is live (interpret mode
    covers functional testing elsewhere)."""
    try:
        _pallas()
    except Exception:
        return False
    return jax.default_backend() == "tpu"


def pallas_importable() -> bool:
    try:
        _pallas()
        return True
    except Exception:
        return False


def compute_tile_pallas(spec: TileSpec, max_iter: int, *,
                        segment: int = DEFAULT_SEGMENT,
                        block_h: int = DEFAULT_BLOCK_H,
                        clamp: bool = False,
                        interpret: bool | None = None) -> np.ndarray:
    """Compute one tile with the Pallas kernel; flat uint8, real-fastest.

    ``interpret=None`` auto-selects interpreter mode off-TPU (slow; for
    functional testing only).
    """
    if spec.height % block_h:
        block_h = max(32, 1 << (spec.height.bit_length() - 1))
        while spec.height % block_h:
            block_h //= 2
        if block_h < 8:
            raise ValueError(
                f"tile height {spec.height} unsupported by pallas path")
    if interpret is None:
        interpret = not pallas_available()
    step = spec.range_real / (spec.width - 1)
    params = jnp.asarray([[spec.start_real, spec.start_imag, step]],
                         jnp.float32)
    out = _pallas_escape(params, height=spec.height, width=spec.width,
                         max_iter=max_iter, segment=segment, block_h=block_h,
                         clamp=clamp, interpret=interpret)
    return np.asarray(out).ravel()
