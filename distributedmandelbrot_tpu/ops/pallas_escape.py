"""Pallas TPU escape-time kernel.

Why a hand kernel when XLA already fuses the masked loop: *block-granular
early exit* and *zero HBM loop-carry traffic*.  The XLA path's segmented
``while_loop`` iterates until the slowest pixel of the whole tile finishes,
and on large tiles XLA materializes the loop carry in HBM between segment
bodies; this kernel walks the tile in ``(block_h, block_w)`` VMEM blocks —
the grid is sequential on a TPU core — and each block runs its own escape
loop entirely out of VMEM, exiting as soon as *its* pixels are done.  On
mixed tiles (fast-escaping sky + deep interior) that recovers most of the
CUDA reference's per-pixel early-return
(``DistributedMandelbrotWorkerCUDA.py:62-67``) without divergent control
flow: VPU-friendly masked math inside, coarse-grained exit outside.

Mosaic constraint that shapes the whole kernel: this TPU toolchain cannot
legalize ``scf.while`` whose yield carries *vectors* (every variant dies
with "failed to legalize operation 'scf.yield'" once the carry
disaggregates into per-vreg values — round 1 crashed on exactly this).
``scf.for`` (``lax.fori_loop``) vector carries *do* legalize, and
``lax.while_loop`` is fine when the carry is scalars only.  So the
data-dependent escape loop keeps its vector state (``zr, zi, active, n``)
in VMEM scratch refs and carries just two scalars through the while:
the iteration counter and the live-pixel count.  Each body iteration
loads the state, runs a small fixed unroll (:data:`DEFAULT_UNROLL`) of
the recurrence as straight-line vector code, stores the state back, and
reduces the mask to the scalar live count that drives the loop condition.

Everything stays on device: coordinates are generated in-kernel from three
scalars (SMEM), output is the uint8 tile block (VMEM), no HBM coordinate
traffic at all.  f32 only — this is the TPU throughput path; parity
anchors live elsewhere (see ops/escape_time.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.ops.escape_time import (
    CYCLE_STRIDE,  # noqa: F401 — re-export: the constant lived here in r5
    family_interior, family_step, probe_step, resolve_cycle_check)
from distributedmandelbrot_tpu.ops.mixed_precision import (scout_cast,
                                                           scout_const)
from distributedmandelbrot_tpu.ops.mxu_iteration import mxu_step

def _pallas():
    """Import pallas lazily: on some builds the import itself fails unless
    the TPU platform plugin registered (e.g. CPU-forced test processes)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl, pltpu


class PallasUnsupported(ValueError):
    """The *intentional* shape/budget rejections of the Pallas dispatch
    path (tile extent below the block granule, iteration cap needing
    int64, pixel pitch below f32 resolution) — the documented cue for
    callers to fall back to the XLA path.  A subclass of ValueError
    so pre-existing ``except ValueError``
    callers keep working, but fall-back sites should catch THIS type:
    a genuine kernel bug surfacing as a bare ValueError must propagate,
    not silently degrade to the XLA path (round-2 advisor finding)."""

def _check_f32_resolvable(spec: TileSpec) -> None:
    """Decline views whose pixel pitch aliases in f32: the kernel
    generates coordinates on device as ``start + i*step`` in f32, and
    below a few ulps per pixel adjacent columns/rows collapse to the
    same value — a banded render no block size can fix.  Such views
    need the f64 XLA path (or perturbation)."""
    from distributedmandelbrot_tpu.core.geometry import spec_f32_resolvable
    if not spec_f32_resolvable(spec):
        raise PallasUnsupported(
            f"pixel pitch of {spec!r} is below f32 resolution "
            "(adjacent pixels alias); use the f64 or perturbation path")


def _check_dispatch_mode(power: int, burning: bool, julia: bool) -> None:
    """Family/mode validation shared by every dispatch wrapper (plain
    ValueError: a user error on every path, not a fall-back cue)."""
    from distributedmandelbrot_tpu.ops.families import _check_family
    _check_family(power, burning)
    if julia and (power != 2 or burning):
        raise ValueError("julia mode supports the degree-2 recurrence only")


def _guard_budget(max_iter: int) -> None:
    """In-kernel scaling is int32; deeper budgets need the XLA path
    (fall-back sites catch PallasUnsupported specifically)."""
    from distributedmandelbrot_tpu.ops.escape_time import INT32_SCALE_LIMIT
    if max_iter - 1 >= INT32_SCALE_LIMIT:
        raise PallasUnsupported(
            f"max_iter {max_iter} too deep for the pallas path")


def _params_row(spec: TileSpec, julia_c: complex | None = None) -> list:
    """The kernel's SMEM params row for one tile — the single definition
    of the row layout (per-axis pitch; julia appends the constant), with
    the f32-resolvability guard applied."""
    _check_f32_resolvable(spec)
    row = [spec.start_real, spec.start_imag,
           spec.range_real / (spec.width - 1),
           spec.range_imag / (spec.height - 1)]
    if julia_c is not None:
        jc = complex(julia_c)
        row += [jc.real, jc.imag]
    return row


# Block shape: one early-exit domain.  Swept on a real v5e (2048^2 view,
# depth 1000, K=8 tiles per dispatch to amortize the tunnel latency):
# (64,128) and (32,128) tie at the top — ~395 Mpix/s on the full -2..2
# view and ~232 Mpix/s on the seahorse zoom — vs 282/145 at (256,256)
# and 291/115 at (8,128): small blocks separate sky from interior (finer
# early exit), until per-block loop overhead bites below 32 rows.
DEFAULT_BLOCK_H = 64
DEFAULT_BLOCK_W = 128

# The cycle-probe cadence constant (CYCLE_STRIDE) and the strided
# check-point predicate (probe_step) are canonical in escape_time.py —
# ONE copy of the policy for the XLA loops and all three Pallas loop
# bodies, with the round-5 hardware sweep numbers documented there.

# Escape-loop steps per while-iteration (between early-exit checks).
# Each step is ~12 straight-line vector ops; the unroll amortizes the
# scratch load/store and the live-count reduction.  Re-swept on live
# hardware 2026-07-31 (tools/sweep_results.jsonl): a real trade, not a
# uniform win — 64 gains +3-6% on boundary-dense views (seahorse
# headline 569->590, filament raw 186->196 Mpix/s at 1024^2; same
# pattern at 4096^2) but LOSES ~5-8% on sky-dominated full-domain
# views (4096^2 full ic=true 457->434), whose blocks exit after a few
# steps and pay the longer segment's overshoot.  64 ships because the
# views it helps are the slow ones — the worst-case floor and the
# conservative headline — while the views it costs are already the
# fastest (full view benches ~2.4x the seahorse rate).  16 loses ~10%
# on deep views.  Output-invariant regardless (overshoot cancels in
# the count classification — equality-tested across unrolls).
DEFAULT_UNROLL = 64


def _interior_init(c_real, c_imag, dyn_steps, shape, interior_check: bool,
                   power: int = 2, burning: bool = False):
    """Shared scratch-state seed for both block kernels: ``(act0, n_sat,
    live0)`` where proven-interior pixels (the single-sourced policy of
    ops.escape_time.family_interior) start inactive with their bounded
    count pre-saturated at ``dyn_steps`` — so they classify "never
    escaped" (0 / nu=0) with zero iterations — and ``live0`` seeds the
    while-loop's live count so a block of only interior + sky pixels
    exits before a single escape segment runs."""
    mask = (family_interior(c_real, c_imag, power, burning)
            if interior_check else None)
    if mask is not None:
        interior = mask.astype(jnp.int32)
        act0 = 1 - interior
        return act0, interior * dyn_steps, jnp.sum(act0, dtype=jnp.int32)
    return (jnp.ones(shape, jnp.int32), jnp.zeros(shape, jnp.int32),
            jnp.asarray(shape[0] * shape[1], jnp.int32))


def _escape_block_kernel(params_ref, mrd_ref, out_ref, zr_ref, zi_ref,
                         act_ref, n_ref, *snap_refs, max_iter: int,
                         unroll: int, block_h: int, block_w: int,
                         clamp: bool, interior_check: bool,
                         cycle_check: bool, julia: bool = False,
                         power: int = 2, burning: bool = False,
                         use_mxu: bool = False):
    """One (block_h, block_w) block: in-kernel grid -> escape loop -> uint8.

    Semantics pinned to the reference kernel
    (``DistributedMandelbrotWorkerCUDA.py:39-68,96-98``): z starts at c,
    counts 1..mrd-1, bailout |z|^2 >= 4 after the update, 0 = never
    escaped, uint8 scaling ceil(v*256/mrd) with wrap.

    ``max_iter`` is the *static* compile-time cap; the tile's actual
    budget ``mrd <= max_iter`` arrives as an SMEM scalar, so one compiled
    executable serves a mixed-budget batch (the sharded dispatch path)
    and the loop still exits at the tile's own budget.

    ``julia`` mode: params carries two extra SMEM scalars ``(c_re,
    c_im)``; z starts at the pixel grid and ``c`` is the constant.  Same
    count semantics; the closed-form interior shortcut does not apply
    (no closed form exists), the cycle probe does.
    """
    pl, _ = _pallas()
    _escape_tile_body(pl.program_id(0), pl.program_id(1), 0,
                      out_ref.shape, lambda v: out_ref.__setitem__(..., v),
                      params_ref, mrd_ref, zr_ref, zi_ref, act_ref, n_ref,
                      snap_refs, max_iter=max_iter, unroll=unroll,
                      block_h=block_h, block_w=block_w, clamp=clamp,
                      interior_check=interior_check, cycle_check=cycle_check,
                      julia=julia, power=power, burning=burning,
                      use_mxu=use_mxu)


def _load_block_coords(params_ref, mrd_ref, t, i, j, shape,
                       block_h: int, block_w: int, julia: bool):
    """Shared prologue of every grid-generating kernel: load tile ``t``'s
    SMEM params row, generate this block's pixel grid on device as
    ``start + index * step`` (f32 — the documented one-ulp-vs-host-grid
    convention), and select the recurrence constant.  Returns
    ``(g_real, g_imag, c_real, c_imag, mrd)``."""
    start_r = params_ref[t, 0]
    start_i = params_ref[t, 1]
    step_r = params_ref[t, 2]
    step_i = params_ref[t, 3]  # per-axis pitch: anisotropic TileSpecs differ
    mrd = mrd_ref[t, 0]
    dtype = params_ref.dtype

    col = lax.broadcasted_iota(jnp.int32, shape, 1) + j * block_w
    row = lax.broadcasted_iota(jnp.int32, shape, 0) + i * block_h
    g_real = start_r + col.astype(dtype) * step_r
    g_imag = start_i + row.astype(dtype) * step_i
    if julia:
        c_real = jnp.full(shape, params_ref[t, 4], dtype)
        c_imag = jnp.full(shape, params_ref[t, 5], dtype)
    else:
        c_real = g_real
        c_imag = g_imag
    return g_real, g_imag, c_real, c_imag, mrd


def _run_seg_loop(zr_ref, zi_ref, act_ref, n_ref, snap_refs, c_real, c_imag,
                  live0, *, cond_cap, sat_steps, unroll: int,
                  cycle_check: bool, power: int, burning: bool,
                  it0=None, dyn_ref=None, use_mxu: bool = False):
    """The ONE segmented escape while-loop, shared by the single-tile,
    batch-grid, phase-1 state, and compaction resume kernels — sharing
    this body is what makes every dispatch (and the two halves of a
    compacted run) bit-identical by construction.

    Escape recurrence with a sticky active mask; see
    ops/escape_time.py:escape_loop for why stickiness matters and how
    the count recovers the escape iteration.  Vector state lives in the
    scratch refs; the while carries scalars only (Mosaic constraint).
    The mask stays int32 end-to-end — i1 vectors can appear only as
    transient compare results, never in carries or stores.  Stickiness
    is a select (where(cond, act, 0) == act & cond for act in {0,1}):
    cmp+select+add per step, one op fewer than cmp+convert+and+add —
    this loop body times ~10 vector ops, so every op is ~10% of the
    raw throughput.

    ``cond_cap``: the loop runs segments while ``it <= cond_cap`` (and
    lanes are live).  ``sat_steps``: the budget the cycle probe
    saturates retired counts to.  ``it0``: the first segment's
    iteration number (default 1); segment boundaries land on
    ``it0 + k*unroll``, so a resumed loop executes the identical
    iteration grid as long as resume points are unroll-aligned.
    ``dyn_ref``: optional per-lane budget ref — lanes whose own budget
    is exhausted retire at segment granularity (their count has already
    reached >= budget, which classifies never-escaped regardless of any
    segment overshoot, so late retirement never changes output; see the
    compaction design note in ops/compact_escape.py)."""
    four = jnp.asarray(4.0, c_real.dtype)
    if it0 is None:
        it0 = jnp.asarray(1, jnp.int32)

    def seg_body(carry):
        it, _, next_snap = carry
        zr = zr_ref[:]
        zi = zi_ref[:]
        act = act_ref[:]
        n = n_ref[:]
        if dyn_ref is not None:
            # Mixed-budget compact buffers: retire lanes past their own
            # tile's budget (output-invariant — their n is already
            # saturated past it).
            act = jnp.where(it <= dyn_ref[:], act, 0)
        if cycle_check:
            # Brent-style snapshot refresh at doubling iteration gaps:
            # once the gap exceeds the orbit's (eventual, exact-f32)
            # period, the per-SEGMENT equality below fires (see note).
            # Scalar predicate -> vector select; refresh cost is
            # per-segment, not per-step.
            do_snap = it >= next_snap
            szr_ref, szi_ref = snap_refs
            szr = jnp.where(do_snap, zr, szr_ref[:])
            szi = jnp.where(do_snap, zi, szi_ref[:])
            next_snap = jnp.where(do_snap, it + it, next_snap)
        zr2 = zr * zr
        zi2 = zi * zi
        for step in range(unroll):
            if use_mxu and power == 2 and not burning:
                # MXU full mode (gate-admitted only where the parity
                # probe proved the matmul form rounds identically —
                # see ops/mxu_iteration.py): the square rides a
                # batched 2x2 matmul, the escape test stays VPU.
                zr, zi = mxu_step(zr, zi, c_real, c_imag)
            elif power == 2:
                # Cached-squares form.  The Burning Ship fold reduces to
                # ONE extra abs here: squares are abs-invariant, so the
                # zr update is unchanged and 2|zr||zi| = |2 zr zi|.
                cross = (zr + zr) * zi
                zi = (jnp.abs(cross) if burning else cross) + c_imag
                zr = zr2 - zi2 + c_real
            else:
                zr, zi = family_step(zr, zi, c_real, c_imag, power=power,
                                     burning=burning)
            zr2 = zr * zr
            zi2 = zi * zi
            act = jnp.where(zr2 + zi2 < four, act, 0)
            if cycle_check and probe_step(step, unroll):
                # The final-step check makes completeness unroll-proof:
                # clamped unrolls below/indivisible by the stride (tiny
                # budgets clamp unroll to max_iter-1) still probe at
                # every segment boundary, whose gaps walk k*unroll and
                # hit 0 mod p within p/gcd(p, unroll) segments.
                # Exact periodicity, checked every CYCLE_STRIDE steps
                # (round 5 — the original per-step check cost ~6 extra
                # vector ops on a ~10-op step body, a measured 16-29%
                # tax on escape-rich deep views where the probe saves
                # nothing; the stride is a STATIC Python condition in
                # the unrolled body, so skipped steps cost zero).
                # Detection still fires for every cycle: with the
                # snapshot fixed, z at a check point equals it iff the
                # elapsed gap is a multiple of the period p, and
                # consecutive check points walk the gap through
                # k*stride, which hits 0 mod p within p/gcd(p, stride)
                # checks.  Detection is merely (boundedly) later, and
                # timing is OUTPUT-INVARIANT: a cycling lane can never
                # escape, its count saturates past the budget whenever
                # it retires, and it classifies never-escaped (0)
                # either way — the invariant the identity tests and
                # hardware step 3c pin.  (inf/NaN lanes are already
                # inactive; NaN != NaN keeps them inert.)
                cyc = jnp.where((zr == szr) & (zi == szi), act, 0)
                act = act - cyc
                n = n + cyc * sat_steps
            n = n + act
        zr_ref[:] = zr
        zi_ref[:] = zi
        act_ref[:] = act
        n_ref[:] = n
        if cycle_check:
            szr_ref[:] = szr
            szi_ref[:] = szi
        # dtype pinned: under x64 a bare sum would widen to int64 and
        # break the while carry's type invariance.
        return (it + unroll, jnp.sum(act, dtype=jnp.int32), next_snap)

    def seg_cond(carry):
        it, live, _ = carry
        return (it <= cond_cap) & (live > 0)

    lax.while_loop(seg_cond, seg_body,
                   (it0, live0, jnp.asarray(2, jnp.int32)))


def _escape_tile_body(i, j, t, shape, store, params_ref, mrd_ref, zr_ref,
                      zi_ref, act_ref, n_ref, snap_refs, *, max_iter: int,
                      unroll: int, block_h: int, block_w: int, clamp: bool,
                      interior_check: bool, cycle_check: bool, julia: bool,
                      power: int, burning: bool, use_mxu: bool = False):
    """The one escape-loop body shared by the single-tile and batch-grid
    kernels (they differ only in which params/mrd row ``t`` feeds the
    block and where ``store`` lands the uint8 result).  Keeping this a
    single function is what keeps the two dispatches bit-identical by
    construction."""
    g_real, g_imag, c_real, c_imag, mrd = _load_block_coords(
        params_ref, mrd_ref, t, i, j, shape, block_h, block_w, julia)

    total_steps = max_iter - 1
    if total_steps <= 0:
        store(jnp.zeros(shape, jnp.uint8))
        return
    dyn_steps = mrd - 1  # this tile's own budget (traced, <= total_steps)

    zr_ref[:] = g_real  # z0: the pixel grid (Mandelbrot: equals c)
    zi_ref[:] = g_imag
    # Interior pixels otherwise dominate iteration work on set-crossing
    # views — this shortcut is where the block-granular exit really pays.
    act0, n_sat, live0 = _interior_init(
        c_real, c_imag, dyn_steps, shape, interior_check and not julia,
        power=power, burning=burning)
    act_ref[:] = act0
    n_ref[:] = n_sat
    if cycle_check:
        szr_ref, szi_ref = snap_refs  # allocated only in cycle mode
        szr_ref[:] = g_real  # snapshot of z_0
        szi_ref[:] = g_imag

    _run_seg_loop(zr_ref, zi_ref, act_ref, n_ref, snap_refs, c_real, c_imag,
                  live0, cond_cap=dyn_steps, sat_steps=dyn_steps,
                  unroll=unroll, cycle_check=cycle_check, power=power,
                  burning=burning, use_mxu=use_mxu)

    n = n_ref[:]
    counts = jnp.where(n >= dyn_steps, 0, n + 1)
    vals = (counts * 256 + (mrd - 1)) // mrd
    if clamp:
        vals = jnp.minimum(vals, 255)
    store(vals.astype(jnp.uint8))


@partial(jax.jit, static_argnames=("height", "width", "max_iter", "unroll",
                                   "block_h", "block_w", "clamp", "interpret",
                                   "interior_check", "cycle_check", "julia",
                                   "power", "burning", "use_mxu"))
def _pallas_escape(params, mrd=None, *, height: int, width: int,
                   max_iter: int, unroll: int = DEFAULT_UNROLL,
                   block_h: int = DEFAULT_BLOCK_H,
                   block_w: int = DEFAULT_BLOCK_W, clamp: bool = False,
                   interpret: bool = False, interior_check: bool = True,
                   cycle_check: bool | None = None, julia: bool = False,
                   power: int = 2, burning: bool = False,
                   use_mxu: bool = False):
    """``max_iter`` is the static compile cap; ``mrd`` (defaults to the
    cap) is this tile's traced budget — see ``_escape_block_kernel``.
    params shape (1, 4): ``(start_real, start_imag, step_real,
    step_imag)`` — two pitch scalars so anisotropic tiles render
    correctly; ``julia`` appends the constant for shape (1, 6).
    ``power``/``burning`` select the extended families; the interior
    shortcut follows escape_time.family_interior's policy (cardioid+bulb
    at degree 2, inscribed disk at higher degrees, none for the ship or
    julia mode)."""
    pl, pltpu = _pallas()
    if mrd is None:
        mrd = jnp.asarray([[max_iter]], jnp.int32)
    # None resolves against THIS call's static cap — the right default
    # for raw callers (bench chains); the public wrappers resolve from
    # the tile's requested budget before bucketing and pass a bool, so
    # bucket padding never turns the probe on for shallow tiles.
    cycle_check = resolve_cycle_check(cycle_check, max_iter)
    kernel = partial(_escape_block_kernel, max_iter=max_iter,
                     unroll=max(1, min(unroll, max(1, max_iter - 1))),
                     block_h=block_h, block_w=block_w, clamp=clamp,
                     interior_check=interior_check, cycle_check=cycle_check,
                     julia=julia, power=power, burning=burning,
                     use_mxu=use_mxu)
    n_params = 6 if julia else 4
    return pl.pallas_call(
        kernel,
        grid=(height // block_h, width // block_w),
        in_specs=[pl.BlockSpec((1, n_params), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((block_h, block_w), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((height, width), jnp.uint8),
        scratch_shapes=[pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.int32),
                        pltpu.VMEM((block_h, block_w), jnp.int32)]
        # Snapshot buffers exist only in cycle mode — shallow budgets
        # don't pay the extra VMEM.
        + ([pltpu.VMEM((block_h, block_w), jnp.float32)] * 2
           if cycle_check else []),
        interpret=interpret,
    )(params, mrd)


# --- Batch-grid kernel -------------------------------------------------------
#
# Dispatching a tile batch as ONE pallas_call with the tile index as a
# leading grid axis, instead of `lax.map` over per-tile calls.  Measured
# on the dev v5e (2026-07-31): the escape loop's steady-state rate more
# than doubles when long-running grid programs are consecutive — an
# all-deep 1024^2 tile runs ~95 Giter/s as a 128-program call but
# ~225 Giter/s inside a 2048-program call (the same kernel, the same
# per-program work; a Mosaic grid-pipelining effect).  The win therefore
# appears when MOST programs run deep: ~+17% on a depth-5000 seahorse
# batch (config 3), ~2.4x on fully-interior work — and nothing (to -6%)
# on shallow early-exit views where per-program overhead dominates.
# Dispatch policy: use the batch grid when the resolved budget is
# >= BATCH_GRID_MIN_ITER (the same depth class where the cycle probe
# arms), keep the per-tile chain below it.

BATCH_GRID_MIN_ITER = 4096

# Per-tile grids below this many programs can't amortize a launch on
# their own — batch them regardless of depth (measured +7% on the
# config-5 shape: 64 x 256^2 tiles = 8 programs each, mi=1000).
BATCH_GRID_MIN_PROGRAMS = 64


def prefer_batch_grid(budget: int, height: int, width: int,
                      block_h: int, block_w: int) -> bool:
    """The single copy of the batch-grid dispatch policy: one launch per
    batch when deep budgets dominate (+17% measured at depth 5000 —
    consecutive deep grid programs pipeline ~2x better) or when the
    per-tile grid is too small to amortize a launch by itself; per-tile
    chains otherwise (shallow early-exit views measure a few percent
    faster there).  ``budget`` is the TRUE deepest budget, not the
    padded compile cap (same principle as the cycle probe)."""
    programs = (height // block_h) * (width // block_w)
    return (budget >= BATCH_GRID_MIN_ITER
            or programs < BATCH_GRID_MIN_PROGRAMS)


def _escape_batch_kernel(params_ref, mrd_ref, out_ref, zr_ref, zi_ref,
                         act_ref, n_ref, *snap_refs, max_iter: int,
                         unroll: int, block_h: int, block_w: int,
                         clamp: bool, interior_check: bool,
                         cycle_check: bool, julia: bool = False,
                         power: int = 2, burning: bool = False):
    """One (block_h, block_w) block of tile ``t = program_id(0)``.

    Same body as :func:`_escape_block_kernel` — literally, via
    :func:`_escape_tile_body` — so the two dispatches are bit-identical
    by construction; only the params/mrd row selection (the leading grid
    axis) and the output plane differ."""
    pl, _ = _pallas()
    _escape_tile_body(pl.program_id(1), pl.program_id(2), pl.program_id(0),
                      out_ref.shape[1:],
                      lambda v: out_ref.__setitem__(0, v),
                      params_ref, mrd_ref, zr_ref, zi_ref, act_ref, n_ref,
                      snap_refs, max_iter=max_iter, unroll=unroll,
                      block_h=block_h, block_w=block_w, clamp=clamp,
                      interior_check=interior_check, cycle_check=cycle_check,
                      julia=julia, power=power, burning=burning)


@partial(jax.jit, static_argnames=("k", "height", "width", "max_iter",
                                   "unroll", "block_h", "block_w", "clamp",
                                   "interpret", "interior_check",
                                   "cycle_check", "julia", "power",
                                   "burning"))
def _pallas_escape_batch(params, mrds, *, k: int, height: int, width: int,
                         max_iter: int, unroll: int = DEFAULT_UNROLL,
                         block_h: int = DEFAULT_BLOCK_H,
                         block_w: int = DEFAULT_BLOCK_W, clamp: bool = False,
                         interpret: bool = False, interior_check: bool = True,
                         cycle_check: bool | None = None, julia: bool = False,
                         power: int = 2, burning: bool = False):
    """``k`` tiles in ONE kernel launch, tile index as the leading grid
    axis -> (k, height, width) uint8.  ``params``: (k, 4|6) rows as in
    :func:`_pallas_escape`; ``mrds``: (k, 1) per-tile budgets; the static
    ``max_iter`` is the bucketed cap of their max.  Outputs are
    bit-identical to k single-tile calls — use for deep budgets (see
    the batch-grid design note above)."""
    pl, pltpu = _pallas()
    cycle_check = resolve_cycle_check(cycle_check, max_iter)
    kernel = partial(_escape_batch_kernel, max_iter=max_iter,
                     unroll=max(1, min(unroll, max(1, max_iter - 1))),
                     block_h=block_h, block_w=block_w, clamp=clamp,
                     interior_check=interior_check, cycle_check=cycle_check,
                     julia=julia, power=power, burning=burning)
    return pl.pallas_call(
        kernel,
        grid=(k, height // block_h, width // block_w),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((1, block_h, block_w),
                               lambda t, i, j: (t, i, j)),
        out_shape=jax.ShapeDtypeStruct((k, height, width), jnp.uint8),
        scratch_shapes=[pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.int32),
                        pltpu.VMEM((block_h, block_w), jnp.int32)]
        + ([pltpu.VMEM((block_h, block_w), jnp.float32)] * 2
           if cycle_check else []),
        interpret=interpret,
    )(params, mrds)


# --- Megakernel (fused-launch default dispatch route) ------------------------
#
# The batch-grid kernel above already folds K tiles into one pallas call;
# the megakernel extends it into the DEFAULT dispatch route for fused
# worker batches (PallasBackend.dispatch_many) and the bench kernel leg:
#
#   * the ~64 ms per-call dispatch/sync constant (BENCH_r05: benched 610
#     vs device 1461 Mpix/s on 64x1024^2) is paid once per K-tile batch
#     instead of once per tile;
#   * the per-block prologue — coordinate generation feeding the
#     interior classification, live-count reduction, and the bf16 scout
#     — is software-pipelined across grid steps with double-buffered
#     (ping-pong) scratch slots: block g+1's prologue runs at the tail
#     of step g, after step g's uint8 store has been issued, so it
#     overlaps the output window's copy-out DMA (Mosaic already
#     double-buffers the out windows across grid steps — this extends
#     the overlap to our own prologue vector work).  Only the INTEGER
#     prologue products ride the slots; the float state re-seeds inline
#     so the escape loop's float graph stays structurally identical to
#     the single-tile kernel (see the kernel docstring for why that is
#     load-bearing for bit-identity);
#   * the uint8 plane is written straight from the VMEM iteration state
#     per block (no post-hoc int32 plane + XLA cast pass as on the
#     packed-kernel route);
#   * an optional bf16 scouting pass shadows the first segments of each
#     block in half precision and reports how many pixels it predicts
#     escape inside the scout window (the `worker_kernel_bf16_pruned`
#     census).  The scout is ADVISORY BY DESIGN: final counts come only
#     from the f32 loop run from z0, so scout-on vs scout-off output is
#     bit-identical by construction — see ops/mixed_precision.py for why
#     no sound count-carrying handoff across the precision boundary
#     exists, and test_pallas.py's guard test for the pinned contract.
#
# Bit-identity across dispatch routes is preserved the same way the
# batch kernel preserves it: the prologue is _load_block_coords +
# _interior_init, the loop is _run_seg_loop, the epilogue is the same
# count classification expression — the pipelining only *reorders*
# independent per-block computations, never changes them.

# bf16 scouting defaults: one unrolled segment of shadow iteration, armed
# only for budgets deep enough to amortize it (a sky block that escapes
# in its first f32 segment shouldn't pay half a segment of prediction).
SCOUT_SEGMENTS_DEFAULT = 1
SCOUT_MIN_ITER = 256


def _scout_census(g_real, g_imag, c_real, c_imag, act0, *, steps: int,
                  power: int, burning: bool):
    """bf16 scouting shadow: iterate a half-precision COPY of the orbit
    for ``steps`` straight-line steps and count how many initially-live
    pixels it predicts escape inside the window.  Returns the int32
    census scalar only — no shadow state ever reaches the f32 loop or
    the output (the parity-guard contract of ops/mixed_precision.py).
    Prediction quality is approximate by design (bf16 orbits diverge on
    boundary pixels; overflow-to-inf/NaN lanes read as escapes), which
    is fine for an occupancy census."""
    bzr = scout_cast(g_real)
    bzi = scout_cast(g_imag)
    bcr = scout_cast(c_real)
    bci = scout_cast(c_imag)
    four = scout_const(4.0)
    act = act0
    zr2 = bzr * bzr
    zi2 = bzi * bzi
    for _ in range(steps):
        if power == 2:
            cross = (bzr + bzr) * bzi
            bzi = (jnp.abs(cross) if burning else cross) + bci
            bzr = zr2 - zi2 + bcr
        else:
            bzr, bzi = family_step(bzr, bzi, bcr, bci, power=power,
                                   burning=burning)
        zr2 = bzr * bzr
        zi2 = bzi * bzi
        act = jnp.where(zr2 + zi2 < four, act, 0)
    return (jnp.sum(act0, dtype=jnp.int32)
            - jnp.sum(act, dtype=jnp.int32))


def _escape_mega_kernel(params_ref, mrd_ref, out_ref, scout_ref, zr_ref,
                        zi_ref, act_ref, n_ref, live_ref, census_ref,
                        *snap_refs, k: int, gh: int, gw: int, max_iter: int,
                        unroll: int, block_h: int, block_w: int, clamp: bool,
                        interior_check: bool, cycle_check: bool,
                        scout_steps: int, julia: bool = False,
                        power: int = 2, burning: bool = False,
                        use_mxu: bool = False):
    """One (block_h, block_w) block of tile ``t = program_id(0)``, with
    the INTEGER half of the prologue software-pipelined one grid step
    ahead.

    ``act``/``n`` scratch carry a leading ping-pong axis of 2; flat
    block index ``g`` selects slot ``g % 2``.  Step ``g`` consumes the
    slot its predecessor seeded — the interior classification, its live
    count, and the bf16 scouting census, i.e. the expensive prologue
    vector work — runs the shared escape loop and the uint8 epilogue,
    then seeds slot ``(g+1) % 2`` for its successor AFTER its own
    output store, so the successor's classification/scout overlaps the
    out-window copy-out.  ``live_ref``/``census_ref`` are (2,) SMEM
    slots carrying the scalar products the same way.

    The FLOAT dataflow is deliberately NOT pipelined: coordinates are
    regenerated inline (4 vector ops) and ``zr/zi`` (and the cycle
    snapshots) live in plain un-slotted scratch, so the escape loop's
    float graph is structurally identical to the single-tile kernel's.
    Routing floats through dynamically-indexed slots measurably shifts
    where the compiler contracts mul+add chains into FMAs, and 300
    iterations amplify that last-ulp difference into a moved count
    bucket on a chaotic pixel — the exact failure the bit-identity
    contract forbids.  Integer products can't contract, so slotting
    them is bit-safe, and they are the expensive part of the prologue
    anyway (the mask is ~20 vector ops plus a reduction; the armed
    scout is a full unrolled bf16 segment).
    """
    pl, _ = _pallas()
    t = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    shape = (block_h, block_w)
    per_tile = gh * gw
    g = (t * gh + i) * gw + j
    total = k * per_tile

    if max_iter - 1 <= 0:
        out_ref[...] = jnp.zeros((1,) + shape, jnp.uint8)

        @pl.when((i == 0) & (j == 0))
        def _():
            scout_ref[0, 0] = jnp.int32(0)
        return

    def prologue(t2, i2, j2, s):
        """Seed integer slot ``s`` for block (t2, i2, j2): the interior
        classification of the single-tile prologue (shared helpers) plus
        the bf16 scouting shadow, whose census rides the SMEM slot."""
        g_real2, g_imag2, c_real2, c_imag2, mrd2 = _load_block_coords(
            params_ref, mrd_ref, t2, i2, j2, shape, block_h, block_w, julia)
        act0, n_sat, live0 = _interior_init(
            c_real2, c_imag2, mrd2 - 1, shape, interior_check and not julia,
            power=power, burning=burning)
        act_ref[s] = act0
        n_ref[s] = n_sat
        live_ref[s] = live0
        if scout_steps:
            census_ref[s] = _scout_census(g_real2, g_imag2, c_real2,
                                          c_imag2, act0, steps=scout_steps,
                                          power=power, burning=burning)

    p = g % 2

    @pl.when(g == 0)
    def _():
        prologue(t, i, j, 0)  # warm-up: the first block seeds itself

    # Float prologue, inline — byte-identical dataflow to the
    # single-tile kernel's _escape_tile_body (see the docstring note).
    g_real, g_imag, c_real, c_imag, mrd = _load_block_coords(
        params_ref, mrd_ref, t, i, j, shape, block_h, block_w, julia)
    dyn_steps = mrd - 1  # this tile's own budget (traced, <= cap)
    zr_ref[:] = g_real  # z0: the pixel grid (Mandelbrot: equals c)
    zi_ref[:] = g_imag
    if cycle_check:
        szr_ref, szi_ref = snap_refs
        szr_ref[:] = g_real  # snapshot of z_0
        szi_ref[:] = g_imag

    _run_seg_loop(zr_ref, zi_ref, act_ref.at[p], n_ref.at[p], snap_refs,
                  c_real, c_imag, live_ref[p], cond_cap=dyn_steps,
                  sat_steps=dyn_steps, unroll=unroll,
                  cycle_check=cycle_check, power=power, burning=burning,
                  use_mxu=use_mxu)

    n = n_ref[p]
    counts = jnp.where(n >= dyn_steps, 0, n + 1)
    vals = (counts * 256 + (mrd - 1)) // mrd
    if clamp:
        vals = jnp.minimum(vals, 255)
    out_ref[0] = vals.astype(jnp.uint8)

    # Per-tile scout census: the (t, 0) SMEM window stays resident across
    # this tile's 64 consecutive blocks, so init-on-first + accumulate.
    @pl.when((i == 0) & (j == 0))
    def _():
        scout_ref[0, 0] = jnp.int32(0)
    if scout_steps:
        scout_ref[0, 0] = scout_ref[0, 0] + census_ref[p]

    @pl.when(g + 1 < total)
    def _():
        # Pipelined prologue: seed the successor's slot AFTER this
        # block's store, overlapping the out-window copy-out.
        nf = g + 1
        t2 = nf // per_tile
        r2 = nf % per_tile
        prologue(t2, r2 // gw, r2 % gw, 1 - p)


@partial(jax.jit, static_argnames=("k", "height", "width", "max_iter",
                                   "unroll", "block_h", "block_w", "clamp",
                                   "interpret", "interior_check",
                                   "cycle_check", "scout_segments", "julia",
                                   "power", "burning", "use_mxu"))
def _pallas_escape_mega(params, mrds, *, k: int, height: int, width: int,
                        max_iter: int, unroll: int = DEFAULT_UNROLL,
                        block_h: int = DEFAULT_BLOCK_H,
                        block_w: int = DEFAULT_BLOCK_W, clamp: bool = False,
                        interpret: bool = False, interior_check: bool = True,
                        cycle_check: bool | None = None,
                        scout_segments: int = 0, julia: bool = False,
                        power: int = 2, burning: bool = False,
                        use_mxu: bool = False):
    """``k`` tiles in ONE launch with pipelined prologues and the bf16
    scouting census -> ``((k, height, width) uint8, (k, 1) int32)``.
    Same params/mrds layout as :func:`_pallas_escape_batch`; outputs are
    bit-identical to it (and so to k single-tile calls) for every
    ``scout_segments`` — the scout is advisory only."""
    pl, pltpu = _pallas()
    cycle_check = resolve_cycle_check(cycle_check, max_iter)
    gh = height // block_h
    gw = width // block_w
    unroll_eff = max(1, min(unroll, max(1, max_iter - 1)))
    kernel = partial(_escape_mega_kernel, k=k, gh=gh, gw=gw,
                     max_iter=max_iter, unroll=unroll_eff, block_h=block_h,
                     block_w=block_w, clamp=clamp,
                     interior_check=interior_check, cycle_check=cycle_check,
                     scout_steps=int(scout_segments) * unroll_eff,
                     julia=julia, power=power, burning=burning,
                     use_mxu=use_mxu)
    return pl.pallas_call(
        kernel,
        grid=(k, gh, gw),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec((1, block_h, block_w),
                                lambda t, i, j: (t, i, j)),
                   pl.BlockSpec((1, 1), lambda t, i, j: (t, 0),
                                memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, height, width), jnp.uint8),
                   jax.ShapeDtypeStruct((k, 1), jnp.int32)],
        # zr/zi (and snapshots) stay un-slotted — the float dataflow must
        # match the single-tile kernel exactly (see the kernel's note);
        # the leading axis 2 on act/n/live/census is the ping-pong of
        # the pipelined integer prologue.
        scratch_shapes=[pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((2, block_h, block_w), jnp.int32),
                        pltpu.VMEM((2, block_h, block_w), jnp.int32),
                        pltpu.SMEM((2,), jnp.int32),
                        pltpu.SMEM((2,), jnp.int32)]
        + ([pltpu.VMEM((block_h, block_w), jnp.float32)] * 2
           if cycle_check else []),
        interpret=interpret,
    )(params, mrds)


def compute_tiles_mega_pallas(specs, max_iters, *,
                              unroll: int = DEFAULT_UNROLL,
                              block_h: int = DEFAULT_BLOCK_H,
                              block_w: int | None = None,
                              clamp: bool = False,
                              interpret: bool | None = None,
                              interior_check: bool = True,
                              cycle_check: bool | None = None,
                              scout_segments: int | None = None,
                              power: int = 2, burning: bool = False,
                              julia_cs=None,
                              device: jax.Device | None = None,
                              use_mxu: bool | None = None):
    """Fuse ``k`` same-shaped tiles into ONE megakernel launch; returns
    ``(tiles, scout)`` still on device — ``tiles`` is (k, height, width)
    uint8 (slice per-tile handles off it), ``scout`` is (k, 1) int32
    with the bf16 scouting census per tile (0 when the scout is off).

    This is the default dispatch route for fused worker batches
    (PallasBackend.dispatch_many) and the bench kernel leg: the per-call
    dispatch constant is paid once per batch, not per tile.  All specs
    must share (height, width); budgets are per-tile under one bucketed
    cap, exactly like the batch-grid path.  ``scout_segments=None``
    arms :data:`SCOUT_SEGMENTS_DEFAULT` when the deepest budget reaches
    :data:`SCOUT_MIN_ITER`; pass 0 to disable.  ``device`` pins the
    launch (and its output buffers) to a specific chip, as in
    :func:`compute_tile_pallas_device`.  Raises
    :class:`PallasUnsupported` on the usual shape/pitch/budget limits —
    fall-back sites dispatch per-tile instead.

    ``use_mxu``: ``None`` (default) resolves the ops/mxu_iteration gate —
    the recurrence rides the 2x2-matmul form only when ``DMTPU_MXU=1``
    AND the parity probe proved bit-identical rounding on this platform
    (the census-only fallback never reaches this kernel; the backend
    runs it as a separate advisory shadow).  An explicit ``True``
    (tests, benches) skips the gate but still requires the degree-2
    non-burning recurrence.
    """
    rows, mrd_rows, kw = mega_dispatch_plan(
        specs, max_iters, unroll=unroll, block_h=block_h, block_w=block_w,
        clamp=clamp, interpret=interpret, interior_check=interior_check,
        cycle_check=cycle_check, scout_segments=scout_segments, power=power,
        burning=burning, julia_cs=julia_cs, use_mxu=use_mxu)
    params = jnp.asarray(rows, jnp.float32)
    mrds = jnp.asarray(mrd_rows, jnp.int32)
    if device is not None:
        params = jax.device_put(params, device)
        mrds = jax.device_put(mrds, device)
    return _pallas_escape_mega(params, mrds, k=len(specs), **kw)


def mega_dispatch_plan(specs, max_iters, *, unroll: int = DEFAULT_UNROLL,
                       block_h: int = DEFAULT_BLOCK_H,
                       block_w: int | None = None, clamp: bool = False,
                       interpret: bool | None = None,
                       interior_check: bool = True,
                       cycle_check: bool | None = None,
                       scout_segments: int | None = None,
                       power: int = 2, burning: bool = False,
                       julia_cs=None, use_mxu: bool | None = None):
    """Validate a fused tile batch and resolve every static dispatch
    decision of the megakernel — the ONE copy of the policy shared by
    the single-device route (:func:`compute_tiles_mega_pallas`) and the
    mesh route (parallel/sharding.compute_tiles_mega_sharded), so the
    two can never drift (the pallas_batch_config precedent).  Returns
    ``(rows, mrd_rows, kwargs)``: host-side params rows, ``(k, 1)``
    budget rows, and the static keyword set for
    :func:`_pallas_escape_mega` (everything but ``k``, which the mesh
    path rewrites to its per-device shard size)."""
    k = len(specs)
    julia = julia_cs is not None
    _check_dispatch_mode(power, burning, julia)
    if use_mxu is None:
        from distributedmandelbrot_tpu.ops.mxu_iteration import mxu_mode
        use_mxu = mxu_mode() == "full"
    if use_mxu and (power != 2 or burning):
        raise PallasUnsupported(
            "mxu iteration form supports the degree-2 non-burning "
            "recurrence only")
    if k < 1:
        raise ValueError("empty tile batch")
    if len(max_iters) != k:
        raise ValueError("specs and max_iters length mismatch")
    if julia and (len(julia_cs) != k or any(c is None for c in julia_cs)):
        raise ValueError("julia_cs must give a constant per tile")
    h, w = specs[0].height, specs[0].width
    for spec in specs:
        if (spec.height, spec.width) != (h, w):
            raise PallasUnsupported("fused tiles must share height/width")
    cap_req = max(int(m) for m in max_iters)
    _guard_budget(cap_req)
    block_h, block_w = fit_blocks(h, w, block_h=block_h, block_w=block_w)
    if interpret is None:
        interpret = not pallas_available()
    rows = [_params_row(spec, julia_cs[idx] if julia else None)
            for idx, spec in enumerate(specs)]
    mrd_rows = [[int(m)] for m in max_iters]
    if scout_segments is None:
        scout_segments = (SCOUT_SEGMENTS_DEFAULT
                          if cap_req >= SCOUT_MIN_ITER else 0)
    kwargs = dict(
        height=h, width=w, max_iter=bucket_cap(cap_req), unroll=unroll,
        block_h=block_h, block_w=block_w, clamp=clamp, interpret=interpret,
        interior_check=interior_check and not julia,
        cycle_check=resolve_cycle_check(cycle_check, cap_req),
        scout_segments=int(scout_segments), julia=julia, power=power,
        burning=burning, use_mxu=bool(use_mxu))
    return rows, mrd_rows, kwargs


# --- Packed multi-tile kernel ------------------------------------------------
#
# Measured on the dev v5e (2026-07-31, chained-checksum timing): the
# single-state escape loop is LATENCY-bound, not issue-bound — stripping
# all bookkeeping ops (cmp/select/count/live-sum, 5 of 12 nominal vector
# ops) gains only ~15%, and block shape from (32,128) to (256,256) moves
# throughput by <±3%.  Interleaving the recurrences of SEVERAL
# independent tiles as straight-line code in one kernel fills the VPU's
# latency shadows: 2 tiles run 1.7x, 4 tiles ~2.6x the per-tile rate on
# deep boundary views (45 -> 13 ms/tile on the filament bench window).
#
# One empirical constraint shapes the design: the speedup appears ONLY
# when the states' results combine into a single output store.  Writing
# the states to separate outputs, or to disjoint slices of one block,
# loses the entire gain (measured repeatedly: ~1.17 vs ~2.0 vreg-ops/
# cycle; a Mosaic scheduling effect we can exploit but not control).  So
# the kernel packs each state's final uint8-scaled value into one byte
# lane of a single int32 output plane — the ``& 255`` in the pack IS the
# uint8 wrap of the scaling contract (``ceil(v*256/mrd)`` cast to byte,
# DistributedMandelbrotWorkerCUDA.py:96-98) — and the XLA caller unpacks
# with a shift-and-mask per state.  Packed uint8 planes also keep the
# HBM write and device->host traffic at 1 byte/pixel/tile, same as the
# single-tile kernel.

PACK_MAX = 4  # int32 holds four byte lanes


def _escape_pack_kernel(params_ref, mrd_ref, out_ref, *refs, n_states: int,
                        max_iter: int, unroll: int, block_h: int,
                        block_w: int, clamp: bool, interior_check: bool,
                        cycle_check: bool, julia: bool = False,
                        power: int = 2, burning: bool = False):
    """One block of ``n_states`` tiles, recurrences interleaved.

    Same per-pixel semantics as :func:`_escape_block_kernel` (z from c,
    counts 1..mrd-1, bailout after update, 0 = never escaped, ceil
    scaling with wrap) — the outputs are bit-identical per state; only
    the scheduling differs.  Each state has its own window (params row),
    budget (mrd row), interior-shortcut mask and cycle snapshots.  The
    while carries scalars only (same Mosaic constraint); its live count
    sums all states, so a block exits when EVERY state's block is done —
    states ride in each other's latency shadows, so a finished state
    costs (nearly) nothing while a deep one still runs.

    Per-state budgets: the loop bound is the deepest state's budget; a
    shallower state retires at segment granularity (``it > dyn_s`` zeroes
    its mask), and lanes of that state still live past their budget have
    ``n >= dyn_s``, which the epilogue classifies as never-escaped — the
    exact overshoot argument of the single-state kernel.
    """
    pl, _ = _pallas()
    i = pl.program_id(0)
    j = pl.program_id(1)
    shape = out_ref.shape
    dtype = params_ref.dtype
    NS = range(n_states)
    per = 6 if cycle_check else 4
    zr_refs = [refs[s * per + 0] for s in NS]
    zi_refs = [refs[s * per + 1] for s in NS]
    act_refs = [refs[s * per + 2] for s in NS]
    n_refs = [refs[s * per + 3] for s in NS]
    if cycle_check:
        szr_refs = [refs[s * per + 4] for s in NS]
        szi_refs = [refs[s * per + 5] for s in NS]

    col = lax.broadcasted_iota(jnp.int32, shape, 1) + j * block_w
    row = lax.broadcasted_iota(jnp.int32, shape, 0) + i * block_h
    colf = col.astype(dtype)
    rowf = row.astype(dtype)
    g_real = [params_ref[s, 0] + colf * params_ref[s, 2] for s in NS]
    g_imag = [params_ref[s, 1] + rowf * params_ref[s, 3] for s in NS]
    if julia:
        c_real = [jnp.full(shape, params_ref[s, 4], dtype) for s in NS]
        c_imag = [jnp.full(shape, params_ref[s, 5], dtype) for s in NS]
    else:
        c_real = g_real
        c_imag = g_imag

    total_steps = max_iter - 1
    if total_steps <= 0:
        out_ref[:] = jnp.zeros(shape, jnp.int32)
        return
    dyn = [mrd_ref[s, 0] - 1 for s in NS]
    dyn_max = dyn[0]
    for s in range(1, n_states):
        dyn_max = jnp.maximum(dyn_max, dyn[s])

    four = jnp.asarray(4.0, dtype)
    live0 = jnp.asarray(0, jnp.int32)
    for s in NS:
        zr_refs[s][:] = g_real[s]
        zi_refs[s][:] = g_imag[s]
        act0, n_sat, live_s = _interior_init(
            c_real[s], c_imag[s], dyn[s], shape,
            interior_check and not julia, power=power, burning=burning)
        act_refs[s][:] = act0
        n_refs[s][:] = n_sat
        live0 = live0 + live_s
        if cycle_check:
            szr_refs[s][:] = g_real[s]
            szi_refs[s][:] = g_imag[s]

    def seg_body(carry):
        it, _, next_snap = carry
        zr = [r[:] for r in zr_refs]
        zi = [r[:] for r in zi_refs]
        # Segment-granular retirement of states past their own budget
        # (scalar predicate -> broadcast select, once per segment).
        act = [jnp.where(it <= dyn[s], act_refs[s][:], 0) for s in NS]
        n = [r[:] for r in n_refs]
        if cycle_check:
            do_snap = it >= next_snap
            szr = [jnp.where(do_snap, zr[s], szr_refs[s][:]) for s in NS]
            szi = [jnp.where(do_snap, zi[s], szi_refs[s][:]) for s in NS]
            next_snap = jnp.where(do_snap, it + it, next_snap)
        zr2 = [z * z for z in zr]
        zi2 = [z * z for z in zi]
        for step in range(unroll):
            if power == 2:
                cross = [(zr[s] + zr[s]) * zi[s] for s in NS]
                if burning:
                    cross = [jnp.abs(c) for c in cross]
                zi = [cross[s] + c_imag[s] for s in NS]
                zr = [zr2[s] - zi2[s] + c_real[s] for s in NS]
            else:
                stepped = [family_step(zr[s], zi[s], c_real[s], c_imag[s],
                                       power=power, burning=burning)
                           for s in NS]
                zr = [t[0] for t in stepped]
                zi = [t[1] for t in stepped]
            zr2 = [zr[s] * zr[s] for s in NS]
            zi2 = [zi[s] * zi[s] for s in NS]
            act = [jnp.where(zr2[s] + zi2[s] < four, act[s], 0) for s in NS]
            if cycle_check and probe_step(step, unroll):
                # Strided probe cadence + unroll-proof boundary check —
                # same trade and same output-invariance argument as
                # _run_seg_loop (the measured 16-29% per-step tax).
                cyc = [jnp.where((zr[s] == szr[s]) & (zi[s] == szi[s]),
                                 act[s], 0) for s in NS]
                act = [act[s] - cyc[s] for s in NS]
                n = [n[s] + cyc[s] * dyn[s] for s in NS]
            n = [n[s] + act[s] for s in NS]
        live = jnp.sum(act[0], dtype=jnp.int32)
        for s in range(1, n_states):
            live = live + jnp.sum(act[s], dtype=jnp.int32)
        for s in NS:
            zr_refs[s][:] = zr[s]
            zi_refs[s][:] = zi[s]
            act_refs[s][:] = act[s]
            n_refs[s][:] = n[s]
            if cycle_check:
                szr_refs[s][:] = szr[s]
                szi_refs[s][:] = szi[s]
        return (it + unroll, live, next_snap)

    def seg_cond(carry):
        it, live, _ = carry
        return (it <= dyn_max) & (live > 0)

    lax.while_loop(seg_cond, seg_body,
                   (jnp.asarray(1, jnp.int32), live0,
                    jnp.asarray(2, jnp.int32)))

    acc = jnp.zeros(shape, jnp.int32)
    for s in NS:
        n = n_refs[s][:]
        counts = jnp.where(n >= dyn[s], 0, n + 1)
        mrd_s = mrd_ref[s, 0]
        vals = (counts * 256 + (mrd_s - 1)) // mrd_s
        if clamp:
            vals = jnp.minimum(vals, 255)
        acc = acc | ((vals & 255) << (8 * s))
    out_ref[:] = acc


@partial(jax.jit, static_argnames=("n_states", "height", "width", "max_iter",
                                   "unroll", "block_h", "block_w", "clamp",
                                   "interpret", "interior_check",
                                   "cycle_check", "julia", "power",
                                   "burning"))
def _pallas_escape_pack(params, mrds, *, n_states: int, height: int,
                        width: int, max_iter: int,
                        unroll: int = DEFAULT_UNROLL,
                        block_h: int = DEFAULT_BLOCK_H,
                        block_w: int = DEFAULT_BLOCK_W, clamp: bool = False,
                        interpret: bool = False, interior_check: bool = True,
                        cycle_check: bool | None = None, julia: bool = False,
                        power: int = 2, burning: bool = False):
    """``n_states`` tiles per kernel pass -> (height, width) int32 with
    state ``s``'s uint8 plane in byte lane ``s``.  ``params``: (n_states,
    4|6) as in :func:`_pallas_escape` per row; ``mrds``: (n_states, 1)
    per-state budgets (the static ``max_iter`` is the bucketed cap of
    their max).  Unpack with :func:`unpack_planes`."""
    pl, pltpu = _pallas()
    if not 1 <= n_states <= PACK_MAX:
        raise PallasUnsupported(f"pack of {n_states} states unsupported")
    cycle_check = resolve_cycle_check(cycle_check, max_iter)
    kernel = partial(_escape_pack_kernel, n_states=n_states,
                     max_iter=max_iter,
                     unroll=max(1, min(unroll, max(1, max_iter - 1))),
                     block_h=block_h, block_w=block_w, clamp=clamp,
                     interior_check=interior_check, cycle_check=cycle_check,
                     julia=julia, power=power, burning=burning)
    n_params = 6 if julia else 4
    per_state = ([pltpu.VMEM((block_h, block_w), jnp.float32),
                  pltpu.VMEM((block_h, block_w), jnp.float32),
                  pltpu.VMEM((block_h, block_w), jnp.int32),
                  pltpu.VMEM((block_h, block_w), jnp.int32)]
                 + ([pltpu.VMEM((block_h, block_w), jnp.float32)] * 2
                    if cycle_check else []))
    return pl.pallas_call(
        kernel,
        grid=(height // block_h, width // block_w),
        in_specs=[pl.BlockSpec((n_states, n_params), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((n_states, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((block_h, block_w), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((height, width), jnp.int32),
        scratch_shapes=per_state * n_states,
        interpret=interpret,
    )(params, mrds)


@partial(jax.jit, static_argnames=("n_states",))
def unpack_planes(packed, n_states: int):
    """(h, w) int32 packed planes -> (n_states, h, w) uint8."""
    return jnp.stack([((packed >> (8 * s)) & 255).astype(jnp.uint8)
                      for s in range(n_states)])


def compute_tiles_packed_pallas(specs, max_iters, *,
                                unroll: int = DEFAULT_UNROLL,
                                block_h: int = DEFAULT_BLOCK_H,
                                block_w: int | None = None,
                                clamp: bool = False,
                                interpret: bool | None = None,
                                interior_check: bool = True,
                                cycle_check: bool | None = None,
                                power: int = 2, burning: bool = False,
                                julia_cs=None) -> list[jax.Array]:
    """Compute up to :data:`PACK_MAX` same-shaped tiles in ONE interleaved
    kernel pass; returns per-tile (height, width) uint8 arrays still on
    device.  ~1.7x (2 tiles) to ~2.6x (4) the per-tile rate of
    :func:`compute_tile_pallas_device` — the escape loop is latency-bound
    and the extra states fill the VPU pipeline (see the packed-kernel
    design note above).

    All specs must share (height, width); family flags are per-call (one
    family per pack — group before calling).  ``julia_cs``: per-tile Julia
    constants (all non-None) or None for the Mandelbrot-family modes.
    Raises :class:`PallasUnsupported` exactly like the single-tile path
    (shape granule, int32 budget cap, f32-resolvable pitch, pack size).
    """
    n = len(specs)
    julia = julia_cs is not None
    _check_dispatch_mode(power, burning, julia)
    if not 1 <= n <= PACK_MAX:
        raise PallasUnsupported(f"pack of {n} tiles unsupported (1..4)")
    if len(max_iters) != n:
        raise ValueError("specs and max_iters length mismatch")
    if julia and (len(julia_cs) != n or any(c is None for c in julia_cs)):
        raise ValueError("julia_cs must give a constant per tile")
    h, w = specs[0].height, specs[0].width
    for spec in specs:
        if (spec.height, spec.width) != (h, w):
            raise PallasUnsupported("packed tiles must share height/width")
    cap_req = max(int(m) for m in max_iters)
    _guard_budget(cap_req)
    block_h, block_w = fit_blocks(h, w, block_h=block_h, block_w=block_w)
    if interpret is None:
        interpret = not pallas_available()
    rows = [_params_row(spec, julia_cs[idx] if julia else None)
            for idx, spec in enumerate(specs)]
    params = jnp.asarray(rows, jnp.float32)
    mrds = jnp.asarray([[int(m)] for m in max_iters], jnp.int32)
    packed = _pallas_escape_pack(
        params, mrds, n_states=n, height=h, width=w,
        max_iter=bucket_cap(cap_req), unroll=unroll, block_h=block_h,
        block_w=block_w, clamp=clamp, interpret=interpret,
        interior_check=interior_check and not julia,
        cycle_check=resolve_cycle_check(cycle_check, cap_req),
        julia=julia, power=power, burning=burning)
    planes = unpack_planes(packed, n_states=n)
    return [planes[s] for s in range(n)]


def _smooth_block_kernel(params_ref, mrd_ref, out_ref, zr_ref, zi_ref,
                         actb_ref, n_ref, act2_ref, n2_ref, *snap_refs,
                         max_iter: int, unroll: int, block_h: int,
                         block_w: int, bailout: float, extra: int,
                         interior_check: bool, cycle_check: bool,
                         julia: bool = False, power: int = 2,
                         burning: bool = False):
    """Smooth-coloring twin of :func:`_escape_block_kernel`: freezes the
    full value at the first radius-``bailout`` crossing while a sticky
    radius-2 count keeps in-set classification identical to the integer
    kernel (semantics of ``ops.escape_time.escape_smooth``).  State lives
    in VMEM scratch; the while carries scalars only (same Mosaic
    constraint, same early exit — here on the radius-``bailout`` mask,
    run ``extra`` steps past the budget so late escapees reach the
    smoothing radius).  ``julia`` as in the integer kernel: params (1, 6),
    z starts at the grid, constant ``c`` from SMEM.  ``power``/``burning``
    select the extended families, with the degree-``power``
    renormalization of ``ops.escape_time._escape_smooth_jit``."""
    pl, _ = _pallas()
    i = pl.program_id(0)
    j = pl.program_id(1)
    start_r = params_ref[0, 0]
    start_i = params_ref[0, 1]
    step_r = params_ref[0, 2]
    step_i = params_ref[0, 3]  # per-axis pitch: anisotropic TileSpecs differ
    mrd = mrd_ref[0, 0]
    shape = out_ref.shape
    dtype = params_ref.dtype

    col = lax.broadcasted_iota(jnp.int32, shape, 1) + j * block_w
    row = lax.broadcasted_iota(jnp.int32, shape, 0) + i * block_h
    g_real = start_r + col.astype(dtype) * step_r
    g_imag = start_i + row.astype(dtype) * step_i
    if julia:
        c_real = jnp.full(shape, params_ref[0, 4], dtype)
        c_imag = jnp.full(shape, params_ref[0, 5], dtype)
    else:
        c_real = g_real
        c_imag = g_imag

    if max_iter <= 1:
        out_ref[:] = jnp.zeros(shape, dtype)
        return
    dyn_steps = mrd - 1
    four = jnp.asarray(4.0, dtype)
    b2 = jnp.asarray(bailout * bailout, dtype)

    zr_ref[:] = g_real  # z0: the pixel grid (Mandelbrot: equals c)
    zi_ref[:] = g_imag
    # Same interior shortcut as the integer kernel (radius-2 count is the
    # one pre-saturated: it owns in-set classification, nu = 0).
    act0, n2_sat, live0 = _interior_init(c_real, c_imag, dyn_steps, shape,
                                         interior_check and not julia,
                                         power=power, burning=burning)
    actb_ref[:] = act0
    n_ref[:] = jnp.zeros(shape, jnp.int32)
    act2_ref[:] = act0
    n2_ref[:] = n2_sat
    if cycle_check:
        szr_ref, szi_ref = snap_refs  # allocated only in cycle mode
        szr_ref[:] = g_real  # snapshot of z_0
        szi_ref[:] = g_imag

    def seg_body(carry):
        it, _, next_snap = carry
        zr = zr_ref[:]
        zi = zi_ref[:]
        act_b = actb_ref[:]
        n = n_ref[:]
        act2 = act2_ref[:]
        n2 = n2_ref[:]
        if cycle_check:
            do_snap = it >= next_snap
            szr = jnp.where(do_snap, zr, szr_ref[:])
            szi = jnp.where(do_snap, zi, szi_ref[:])
            next_snap = jnp.where(do_snap, it + it, next_snap)
        for step in range(unroll):
            nzr, nzi = family_step(zr, zi, c_real, c_imag, power=power,
                                   burning=burning)
            # Escaped-from-bailout lanes freeze — their z at the first
            # crossing IS the smoothing payload, so no separate snapshot
            # state is needed.
            sel = act_b != 0
            zr = jnp.where(sel, nzr, zr)
            zi = jnp.where(sel, nzi, zi)
            m2 = zr * zr + zi * zi
            act_b = jnp.where(m2 < b2, act_b, 0)
            n = n + act_b
            act2 = jnp.where(m2 < four, act2, 0)
            if cycle_check and probe_step(step, unroll):
                # act2 implies act_b (radius 2 clears before bailout), so
                # the probe fires only on live orbits; saturating the
                # radius-2 count classifies the lane in-set and retires
                # it (see escape_loop for the exactness argument).
                # Strided cadence + boundary check as in _run_seg_loop
                # (output-invariant: a cycling lane's n2 saturates past
                # the budget whenever it retires, and the nu=0 select
                # discards its n/z entirely).
                cyc = jnp.where((zr == szr) & (zi == szi), act2, 0)
                act2 = act2 - cyc
                act_b = act_b - cyc
                n2 = n2 + cyc * dyn_steps
            n2 = n2 + act2
        zr_ref[:] = zr
        zi_ref[:] = zi
        actb_ref[:] = act_b
        n_ref[:] = n
        act2_ref[:] = act2
        n2_ref[:] = n2
        if cycle_check:
            szr_ref[:] = szr
            szi_ref[:] = szi
        return (it + unroll, jnp.sum(act_b, dtype=jnp.int32), next_snap)

    def seg_cond(carry):
        it, live, _ = carry
        return (it <= dyn_steps + extra) & (live > 0)

    lax.while_loop(seg_cond, seg_body,
                   (jnp.asarray(1, jnp.int32), live0,
                    jnp.asarray(2, jnp.int32)))

    n = n_ref[:]
    n2 = n2_ref[:]
    # Frozen z for escaped lanes; never-escaped lanes clamp to b2 (the
    # same laggard handling as the XLA kernel).
    fzr = zr_ref[:]
    fzi = zi_ref[:]
    # Same two-sided sanitization as the XLA smooth kernel: high degrees
    # freeze past bailout with |z|^2 (or its inf - inf) beyond f32 range.
    big = float(jnp.finfo(dtype).max)
    mag2 = jnp.clip(jnp.nan_to_num(fzr * fzr + fzi * fzi, nan=big,
                                   posinf=big), b2, big)
    log_ratio = jnp.log(mag2) / jnp.asarray(2.0 * np.log(bailout), dtype)
    corr = jnp.log2(log_ratio)
    if power != 2:
        corr = corr / jnp.asarray(np.log2(power), dtype)
    nu = (n + 2).astype(dtype) - corr
    out_ref[:] = jnp.where(n2 >= dyn_steps, jnp.zeros((), dtype), nu)


@partial(jax.jit, static_argnames=("height", "width", "max_iter", "unroll",
                                   "block_h", "block_w", "bailout",
                                   "interpret", "interior_check",
                                   "cycle_check", "julia", "power",
                                   "burning"))
def _pallas_smooth(params, mrd=None, *, height: int, width: int,
                   max_iter: int, unroll: int = DEFAULT_UNROLL,
                   block_h: int = DEFAULT_BLOCK_H,
                   block_w: int = DEFAULT_BLOCK_W, bailout: float = 256.0,
                   interpret: bool = False, interior_check: bool = True,
                   cycle_check: bool | None = None, julia: bool = False,
                   power: int = 2, burning: bool = False):
    pl, pltpu = _pallas()
    if mrd is None:
        mrd = jnp.asarray([[max_iter]], jnp.int32)
    cycle_check = resolve_cycle_check(cycle_check, max_iter)
    extra = 8 + int(np.ceil(np.log2(np.log2(max(bailout, 4.0)))))
    kernel = partial(_smooth_block_kernel, max_iter=max_iter,
                     unroll=max(1, min(unroll, max(1, max_iter - 1))),
                     block_h=block_h, block_w=block_w,
                     # dmtpu: ignore[jax-host-sync] — bailout is a static_argnames python float
                     bailout=float(bailout), extra=extra,
                     interior_check=interior_check,
                     cycle_check=cycle_check, julia=julia, power=power,
                     burning=burning)
    n_params = 6 if julia else 4
    return pl.pallas_call(
        kernel,
        grid=(height // block_h, width // block_w),
        in_specs=[pl.BlockSpec((1, n_params), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((block_h, block_w), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((height, width), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.float32),
                        pltpu.VMEM((block_h, block_w), jnp.int32),
                        pltpu.VMEM((block_h, block_w), jnp.int32),
                        pltpu.VMEM((block_h, block_w), jnp.int32),
                        pltpu.VMEM((block_h, block_w), jnp.int32)]
        + ([pltpu.VMEM((block_h, block_w), jnp.float32)] * 2
           if cycle_check else []),
        interpret=interpret,
    )(params, mrd)


def compute_tile_smooth_pallas(spec: TileSpec, max_iter: int, *,
                               unroll: int = DEFAULT_UNROLL,
                               block_h: int = DEFAULT_BLOCK_H,
                               block_w: int | None = None,
                               bailout: float = 256.0,
                               interpret: bool | None = None,
                               interior_check: bool = True,
                               cycle_check: bool | None = None,
                               julia_c: complex | None = None,
                               power: int = 2,
                               burning: bool = False) -> np.ndarray:
    """Smooth (band-free) tile via the Pallas kernel -> (h, w) float32 nu.

    The f32 TPU throughput path for smooth rendering (animations, live
    views); the f64 quality path stays on the XLA kernel.  ``julia_c``
    renders the Julia set for that constant (rides SMEM — sweeping it
    reuses one executable); ``power``/``burning`` the extended families.
    Same :class:`PallasUnsupported` contract as
    :func:`compute_tile_pallas_device` for unsupported shapes/budgets —
    fall-back sites catch that type (not bare ValueError) and use XLA.
    """
    _check_dispatch_mode(power, burning, julia_c is not None)
    _guard_budget(max_iter)
    block_h, block_w = fit_blocks(spec.height, spec.width,
                                  block_h=block_h, block_w=block_w)
    if interpret is None:
        interpret = not pallas_available()
    params = jnp.asarray([_params_row(spec, julia_c)], jnp.float32)
    cap = bucket_cap(max_iter)
    mrd = jnp.asarray([[max_iter]], jnp.int32)
    out = _pallas_smooth(params, mrd, height=spec.height, width=spec.width,
                         max_iter=cap, unroll=unroll, block_h=block_h,
                         block_w=block_w, bailout=bailout,
                         interpret=interpret, interior_check=interior_check,
                         cycle_check=resolve_cycle_check(cycle_check,
                                                         max_iter),
                         julia=julia_c is not None,
                         power=power, burning=burning)
    return np.asarray(out)


def bucket_cap(max_iter: int) -> int:
    """The static compile cap for a budget: rounded up to a power of two
    (floor 256), so farms and animations mixing budgets (256, 1000,
    1024, ...) share executables instead of compiling one per distinct
    max_iter.  The kernel's while loop exits at the traced per-tile
    budget, so the padded cap costs nothing.  Used by both dispatch
    paths (single-tile and shard_map batch) — the caps must agree or
    they stop sharing executables."""
    return 1 << max(8, (max_iter - 1).bit_length()) if max_iter > 1 else 1


def pallas_available() -> bool:
    """True when pallas imports and a TPU backend is live (interpret mode
    covers functional testing elsewhere)."""
    try:
        _pallas()
    except Exception:
        return False
    return jax.default_backend() == "tpu"


def pallas_importable() -> bool:
    try:
        _pallas()
        return True
    except Exception:
        return False


def _fit_block(extent: int, block: int, floor: int) -> int:
    """``block`` if it divides ``extent``, else the largest power-of-two
    divisor of ``extent`` below it — subject to the hardware granule
    ``floor`` (32 sublanes x 128 lanes for a uint8 output block): blocks
    below the granule force Mosaic padding on the store path, so such
    extents are rejected and callers fall back to the XLA path."""
    if extent % block == 0 and block % floor == 0:
        return block
    # Power-of-two floor of the candidate, so halving walks every
    # power-of-two divisor candidate down to the granule.
    fit = 1 << (min(block, extent).bit_length() - 1)
    while fit >= floor and extent % fit:
        fit //= 2
    if fit < floor or fit % floor:
        raise PallasUnsupported(
            f"tile extent {extent} unsupported by pallas path")
    return fit


def fit_blocks(height: int, width: int, *,
               block_h: int = DEFAULT_BLOCK_H,
               block_w: int | None = None) -> tuple[int, int]:
    """The (block_h, block_w) the kernel will actually use for a tile, with
    granule validation — raises PallasUnsupported for bad extents.  Every
    caller of :func:`_pallas_escape` must size blocks through here, or a
    non-divisible tile silently computes only ``extent // block`` blocks."""
    if block_w is None:
        block_w = min(DEFAULT_BLOCK_W, width)
    return (_fit_block(height, min(block_h, height), floor=32),
            _fit_block(width, block_w, floor=128))


def compute_tile_pallas_device(spec: TileSpec, max_iter: int, *,
                               unroll: int = DEFAULT_UNROLL,
                               block_h: int = DEFAULT_BLOCK_H,
                               block_w: int | None = None,
                               clamp: bool = False,
                               interpret: bool | None = None,
                               interior_check: bool = True,
                               cycle_check: bool | None = None,
                               power: int = 2, burning: bool = False,
                               julia_c: complex | None = None,
                               device: jax.Device | None = None) -> jax.Array:
    """Dispatch one tile's kernel; returns the (height, width) uint8 tile
    still on device.  Callers that pipeline (dispatch batch, then
    materialize) overlap compute with device->host transfers.

    ``device`` targets the dispatch at a specific local chip: the scalar
    inputs are committed there, so the kernel (and its output buffer)
    land on that device — how the pipelined executor round-robins tiles
    over every local device instead of queueing all of them on
    ``jax.devices()[0]``.  ``None`` keeps the default placement.

    The single dispatch body for every integer-kernel variant —
    Mandelbrot, Julia (``julia_c``), Multibrot/Burning Ship
    (``power``/``burning``) — so the budget guard, block sizing, and
    params layout exist exactly once.
    """
    _check_dispatch_mode(power, burning, julia_c is not None)
    _guard_budget(max_iter)
    block_h, block_w = fit_blocks(spec.height, spec.width,
                                  block_h=block_h, block_w=block_w)
    if interpret is None:
        interpret = not pallas_available()
    params = jnp.asarray([_params_row(spec, julia_c)], jnp.float32)
    cap = bucket_cap(max_iter)
    mrd = jnp.asarray([[max_iter]], jnp.int32)
    if device is not None:
        # Committed inputs pin the whole dispatch (and the output tile)
        # to this chip; the transfer is two tiny SMEM rows.
        params = jax.device_put(params, device)
        mrd = jax.device_put(mrd, device)
    # Probe policy follows the tile's ACTUAL budget, not the padded
    # compile cap: a shallow tile whose bucket rounds up past the probe
    # threshold must not pay the probe's per-step compares and snapshot
    # VMEM (round-2 advisor finding).
    return _pallas_escape(params, mrd, height=spec.height, width=spec.width,
                          max_iter=cap, unroll=unroll, block_h=block_h,
                          block_w=block_w, clamp=clamp, interpret=interpret,
                          interior_check=interior_check
                          and julia_c is None,
                          cycle_check=resolve_cycle_check(cycle_check,
                                                          max_iter),
                          julia=julia_c is not None, power=power,
                          burning=burning)


def compute_tile_family_pallas(spec: TileSpec, max_iter: int, *,
                               power: int = 2, burning: bool = False,
                               unroll: int = DEFAULT_UNROLL,
                               block_h: int = DEFAULT_BLOCK_H,
                               block_w: int | None = None,
                               clamp: bool = False,
                               interpret: bool | None = None,
                               cycle_check: bool | None = None) -> np.ndarray:
    """Multibrot / Burning-Ship tile via the Pallas kernel -> flat uint8.

    Same block-granular early exit and cycle probe as the Mandelbrot
    kernel; the degree-2 ship costs one extra abs per step (squares are
    abs-invariant, so the cached-squares form survives the fold).
    Unsupported shapes/budgets raise :class:`PallasUnsupported`; invalid
    family parameters raise the XLA path's plain ValueError (a user
    error on every path, not a fall-back cue).
    """
    out = compute_tile_pallas_device(spec, max_iter, unroll=unroll,
                                     block_h=block_h, block_w=block_w,
                                     clamp=clamp, interpret=interpret,
                                     cycle_check=cycle_check, power=power,
                                     burning=burning)
    return np.asarray(out).ravel()


def compute_tile_julia_pallas(spec: TileSpec, c: complex, max_iter: int, *,
                              unroll: int = DEFAULT_UNROLL,
                              block_h: int = DEFAULT_BLOCK_H,
                              block_w: int | None = None,
                              clamp: bool = False,
                              interpret: bool | None = None,
                              cycle_check: bool | None = None) -> np.ndarray:
    """Julia tile via the Pallas kernel -> flat uint8 (f32 TPU fast path).

    The constant rides SMEM as traced scalars, so sweeping ``c`` — a
    Julia animation — reuses one compiled executable, matching the XLA
    path's behavior (escape_time.escape_counts_julia).  Same
    :class:`PallasUnsupported` contract for unsupported shapes/budgets
    as the Mandelbrot wrapper.
    """
    out = compute_tile_pallas_device(spec, max_iter, unroll=unroll,
                                     block_h=block_h, block_w=block_w,
                                     clamp=clamp, interpret=interpret,
                                     cycle_check=cycle_check, julia_c=c)
    return np.asarray(out).ravel()


def compute_tile_pallas(spec: TileSpec, max_iter: int, *,
                        unroll: int = DEFAULT_UNROLL,
                        block_h: int = DEFAULT_BLOCK_H,
                        block_w: int | None = None,
                        clamp: bool = False,
                        interpret: bool | None = None,
                        interior_check: bool = True,
                        cycle_check: bool | None = None) -> np.ndarray:
    """Compute one tile with the Pallas kernel; flat uint8, real-fastest.

    ``interpret=None`` auto-selects interpreter mode off-TPU (slow; for
    functional testing only).  ``interior_check`` toggles the closed-form
    interior shortcut (output-identical; off only for timing the raw loop);
    ``cycle_check`` the Brent periodicity probe (output-identical; None =
    on for deep budgets, see escape_time.CYCLE_CHECK_MIN_ITER).
    """
    out = compute_tile_pallas_device(spec, max_iter, unroll=unroll,
                                     block_h=block_h, block_w=block_w,
                                     clamp=clamp, interpret=interpret,
                                     interior_check=interior_check,
                                     cycle_check=cycle_check)
    return np.asarray(out).ravel()
