"""JAX escape-time kernels, TPU-first.

The reference's CUDA kernel (``DistributedMandelbrotWorkerCUDA.py:39-68``)
returns per-pixel at the escape iteration.  SIMD/vector hardware has no
per-element early return, so the TPU-native form is *masked iteration* in
the select-free shape of :func:`escape_loop`: every pixel keeps iterating
unconditionally (escaped orbits diverge to inf, possibly NaN via inf-inf
— harmless, since a sticky ``active`` mask stops their count from
advancing and the final count is recovered arithmetically).  Because
inf/NaN in escaped lanes is *by design*, ``jax_debug_nans`` /
``jax_debug_infs`` will abort on perfectly valid renders — leave them off
around these kernels.  Early exit is recovered at tile granularity with a
segmented ``lax.while_loop`` — run ``segment`` unconditional iterations
at a time (an unrolled body XLA fuses into one elementwise loop nest),
then stop when the whole tile has escaped or the iteration budget is
spent.  For typical views most of the tile escapes early, so segments
capture most of the CUDA early-exit win without data-dependent control
flow inside the hot loop.

Two precision paths:

- ``float64`` path — near-exact vs the numpy golden
  (:mod:`distributedmandelbrot_tpu.ops.reference`).  *Near*, not bit-exact:
  XLA's backends contract ``mul+add/sub`` chains into FMA/FMS (single
  rounding), and the contraction survives ``optimization_barrier`` because
  fusions recompute producers; no supported flag disables it
  (``--xla_allow_excess_precision=false`` does not).  The effect is a
  last-ulp trajectory difference that changes the escape count of O(1)
  chaotic-boundary pixels per tile (measured ~0.02% at depth 1000).  The
  framework's *bit-exact* parity anchors are therefore the host paths —
  the numpy golden and the native C++ kernel built with
  ``-ffp-contract=off`` — and the JAX paths are validated against them
  statistically.
- ``float32`` fast path — the TPU throughput path; boundary pixels may
  land in adjacent iteration buckets, acceptable for rendering and
  benchmarked separately.

All functions are pure and jit-compiled with static ``max_iter`` and
``segment`` (a handful of distinct depths per run, so recompiles are rare
and each specialization unrolls its segment body).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.utils.precision import ensure_x64

DEFAULT_SEGMENT = 32

# Highest escape count whose exact uint8 scaling stays within int32:
# the scaled value is counts*256 + (mrd-1) with mrd <= counts+1, so the
# worst case is counts*257, and counts <= (2^31-1)//257 is safe.  Budgets
# with max_iter - 1 >= this widen to int64 (and x64 mode).
INT32_SCALE_LIMIT = (2**31 - 1) // 257 + 1  # 8,355,968

# Cap on how many escape iterations are ever unrolled into a flat op chain.
# Segments larger than this run as an inner fori_loop of MAX_UNROLL-step
# unrolled bodies: identical semantics, but compile time stays bounded —
# XLA:CPU's backend goes superlinear past a few hundred unrolled steps
# (seg=299 f64: >9 min flat vs 0.9 s capped) and Mosaic blows up similarly.
MAX_UNROLL = 64

# Safety margins for the closed-form interior test (:func:`mandelbrot_interior`),
# per dtype.  The test polynomials are evaluated in at most ~4 rounding steps
# on operands of magnitude <= ~1 near the curves, so the evaluation error is
# a few ulps (~5e-7 f32 / ~1e-15 f64); the margin is ~20x that, guaranteeing
# a pixel that passes the strict-by-margin test is *mathematically* interior.
# Pixels inside the true curve but within the margin strip simply iterate
# normally — the margin costs coverage (a boundary strip of width ~1e-5 in
# test-value terms, negligible area), never correctness.
INTERIOR_MARGIN = {np.dtype(np.float32): 1e-5, np.dtype(np.float64): 1e-12}


def _validated_margin(dtype) -> float:
    """The one margin policy shared by every interior test.  Only dtypes in
    :data:`INTERIOR_MARGIN` are validated; anything narrower (f16/bf16) gets
    a loud error rather than a margin below one ulp of the test polynomial
    that could silently misclassify an exterior point as interior."""
    try:
        return INTERIOR_MARGIN[np.dtype(dtype)]
    except KeyError:
        raise ValueError(
            f"no validated interior margin for dtype {np.dtype(dtype)}; "
            "pass margin= explicitly (f32/f64 are supported by default)"
        ) from None

# Budgets at/above this enable the Brent cycle probe by default (see
# escape_loop): deep budgets are where in-set pixels missed by the closed
# forms dominate.  Lowered 4096 -> 1024 in round 5: the threshold was
# set when the probe compared every step (a measured 16-29% Pallas /
# up-to-55% XLA tax on escape-rich views); with the strided cadence
# (CYCLE_STRIDE below, shared by the XLA and Pallas loops) the tax is
# 0-5% at mid budgets while bounded-dynamics views gain ~9x (minibrot
# 8x1024^2 device Mpix/s at mi=2000: 239 probe-off -> 2071 on the
# default policy; ROUND5_NOTES.md) — and farm grids at the reference's
# canonical mi=1024 contain exactly such minibrot tiles as their
# stragglers.  The Pallas wrappers resolve the same policy from the
# tile's REQUESTED budget (before bucket_cap padding), so a shallow
# tile whose bucket rounds past this threshold never pays the probe.
CYCLE_CHECK_MIN_ITER = 1024


def resolve_cycle_check(cycle_check: bool | None, max_iter: int) -> bool:
    return (max_iter >= CYCLE_CHECK_MIN_ITER if cycle_check is None
            else cycle_check)


# Cycle-probe check cadence (steps between snapshot-equality checks),
# shared by the XLA loops here and the Pallas kernels.  Swept on live
# hardware in round 5 (ROUND5_NOTES.md): the per-step check cost ~4-6
# extra vector ops on an ~8-10-op step body — a measured 16-29% tax on
# the Pallas path and up to 55% on the XLA path (seahorse mi=2000 XLA:
# 42.6 probe-on vs 93.6 off benched; it even LOST on the
# minibrot-interior view, 54.6 vs 63.1, because the cost runs from step
# 1 while detection waits for convergence).  Stride 8 on the Pallas
# sweep dominated both view classes (minibrot 2485 device Mpix/s = the
# per-step value; seahorse 320 vs per-step 251 and probe-off 298).
# Detection stays complete: check-point gaps walk k*stride (and
# k*chunk at chunk boundaries), hitting 0 mod p within p/gcd checks —
# merely boundedly later, which is output-invariant (a cycling lane's
# count saturates past the budget whenever it retires).
CYCLE_STRIDE = 8


def probe_step(k: int, chunk_len: int) -> bool:
    """STATIC predicate: does the cycle probe fire after unrolled step
    ``k`` of a ``chunk_len``-step chunk?  Every CYCLE_STRIDE steps, plus
    the chunk's last step so clamped/indivisible chunks keep the
    completeness guarantee.  One copy for the XLA and Pallas loops."""
    return (k + 1) % CYCLE_STRIDE == 0 or k == chunk_len - 1


def unrolled_steps(step_fn, state, segment: int, max_unroll: int = MAX_UNROLL,
                   indexed: bool = False):
    """Apply ``step_fn`` ``segment`` times: fori_loop over full
    ``max_unroll``-step unrolled chunks, remainder unrolled flat.

    ``indexed=True`` calls ``step_fn(state, k, chunk_len)`` with the
    STATIC position inside the current unrolled chunk, so strided
    per-step work (the cycle probe) keys off it at zero dynamic cost."""
    call = step_fn if indexed else (lambda s, k, ln: step_fn(s))
    full, rem = divmod(segment, max_unroll)
    if full:
        def chunk(_, s):
            for k in range(max_unroll):
                s = call(s, k, max_unroll)
            return s
        state = lax.fori_loop(0, full, chunk, state) if full > 1 else \
            chunk(0, state)
    for k in range(rem):
        state = call(state, k, rem)
    return state


def mandelbrot_interior(c_real, c_imag, margin: float | None = None):
    """Pixels *provably* inside the Mandelbrot set, by closed form.

    Main cardioid: with ``q = (x - 1/4)^2 + y^2``, interior iff
    ``q (q + x - 1/4) < y^2 / 4``.  Period-2 bulb: ``(x+1)^2 + y^2 < 1/16``.
    Both tests are strict-by-``margin`` (see :data:`INTERIOR_MARGIN`), so
    floating-point evaluation can never classify an exterior point as
    interior — a True here means the exact orbit never escapes, hence the
    escape kernels may skip such pixels and report "never escaped" (0)
    with *identical* output to full iteration.  This is the SIMD-friendly
    recovery of the work the reference's CUDA kernel burns: interior
    pixels run to the full budget there
    (``DistributedMandelbrotWorkerCUDA.py:49-68`` has no interior test)
    and dominate total iteration count on set-crossing views (measured
    94% of all iteration work on the seahorse bench window).

    O(1) per pixel, ~10 elementwise ops — amortized against budgets of
    hundreds to tens of thousands of iterations saved per covered pixel.
    """
    dtype = jnp.result_type(c_real)
    if margin is None:
        margin = _validated_margin(dtype)
    m = jnp.asarray(margin, dtype)
    y2 = c_imag * c_imag
    xm = c_real - jnp.asarray(0.25, dtype)
    q = xm * xm + y2
    cardioid = q * (q + xm) < jnp.asarray(0.25, dtype) * y2 - m
    xp = c_real + jnp.asarray(1.0, dtype)
    bulb = xp * xp + y2 < jnp.asarray(0.0625, dtype) - m
    return cardioid | bulb


def multibrot_interior_radius(power: int) -> float:
    """Radius of the inscribed disk (centered at 0) of the degree-``power``
    Multibrot's period-1 hyperbolic component.

    The component is ``c = w - w^d`` over ``|w| < d^(-1/(d-1))`` (where the
    fixed point's multiplier ``d w^(d-1)`` is attracting) and contains 0;
    on its boundary ``|c| = |w - w^d| >= |w|(1 - |w|^(d-1)) =
    (d-1) d^(-d/(d-1))``, so the disk of that radius lies strictly inside
    — every c in it has an attracting fixed point and provably never
    escapes.  For d=2 this is the |c| < 1/4 disk inside the cardioid
    (the cardioid test is strictly stronger there; this exists for d > 2,
    where no simple closed boundary form is available)."""
    d = float(power)
    return (d - 1.0) * d ** (-d / (d - 1.0))


def multibrot_interior(c_real, c_imag, power: int,
                       margin: float | None = None):
    """Conservative interior mask for the degree-``power`` Multibrot: the
    inscribed disk of :func:`multibrot_interior_radius`, strict by the
    same per-dtype margin policy as :func:`mandelbrot_interior` (the test
    is two multiplies and an add — rounding is a couple of ulps)."""
    dtype = jnp.result_type(c_real)
    if margin is None:
        margin = _validated_margin(dtype)
    r = multibrot_interior_radius(power)
    lim = jnp.asarray(r * r - margin, dtype)
    return c_real * c_real + c_imag * c_imag < lim


def family_interior(c_real, c_imag, power: int = 2, burning: bool = False):
    """The proven-interior mask for a recurrence family, or ``None`` when
    no closed form is known (the Burning Ship): cardioid + period-2 bulb
    at degree 2, the inscribed period-1 disk at higher multibrot degrees.
    The single source of the family -> interior-test policy — used by the
    XLA count loop, the smooth kernel, and the Pallas block kernels, so
    the dispatch can never diverge between them."""
    if burning:
        return None
    if power == 2:
        return mandelbrot_interior(c_real, c_imag)
    return multibrot_interior(c_real, c_imag, power)


def cycle_probe_update(zr, zi, szr, szi, live, n, total_steps: int):
    """Shared per-step Brent probe bookkeeping: retire exactly-repeating
    live orbits and saturate their count so they classify never-escaped
    (see :func:`escape_loop` for the exactness argument).  Returns the
    updated ``(live, n)`` plus the fired mask ``cyc`` for callers that
    maintain additional masks (the smooth kernels also clear their
    bailout mask)."""
    cyc = live & (zr == szr) & (zi == szi)
    live = live & ~cyc
    n = n + cyc.astype(jnp.int32) * total_steps
    return live, n, cyc


def counts_from_survival(n, total_steps: int):
    """Escape counts from the survived-iterations count ``n``: a pixel
    escaping at ``e`` survived ``e - 1`` updates, and ``n >= total_steps``
    means never escaped within budget -> 0 (which also cancels escapes
    recorded during the last segment's overrun and absorbs the interior/
    cycle saturation)."""
    return jnp.where(n >= total_steps, 0, n + 1)


def brent_snap_hook(state, it):
    """Shared cycle-probe snapshot refresh (see :func:`escape_loop`): the
    trailing three state fields are, by convention, ``(szr, szi,
    next_snap)``; snapshots refresh at doubling iteration gaps."""
    *rest, szr, szi, next_snap = state
    do = it >= next_snap
    szr = jnp.where(do, state[0], szr)
    szi = jnp.where(do, state[1], szi)
    next_snap = jnp.where(do, it + it, next_snap)
    return (*rest, szr, szi, next_snap)


def segmented_while(one_step, state, *, total_steps: int, segment: int,
                    active_of, seg_hook=None, indexed: bool = False):
    """Run ``one_step`` in fixed-trip unrolled segments under a
    ``lax.while_loop`` until the iteration budget is spent or
    ``active_of(state)`` is all-False (tile-granular early exit).  The last
    segment may overrun past ``total_steps``; callers cancel overrun
    effects arithmetically (see :func:`escape_loop`).  Shared scaffolding
    for the parity and smooth kernels.

    ``seg_hook(state, it) -> state``, if given, runs once at the top of
    each segment (used for the cycle-probe snapshot refresh — per-segment
    cost instead of per-step).  ``indexed`` forwards to
    :func:`unrolled_steps` (static step positions for the strided
    probe)."""
    segment = max(1, min(segment, total_steps))

    def segment_body(carry):
        s, it = carry
        if seg_hook is not None:
            s = seg_hook(s, it)
        # Fixed-trip segment; unroll capped so compile time stays bounded.
        return (unrolled_steps(one_step, s, segment, indexed=indexed),
                it + segment)

    def segment_cond(carry):
        s, it = carry
        # Keep going while budget remains and any pixel is still bounded.
        return (it <= total_steps) & jnp.any(active_of(s))

    state, _ = lax.while_loop(segment_cond, segment_body,
                              (state, jnp.asarray(1, jnp.int32)))
    return state


def escape_loop(zr0, zi0, c_real, c_imag, *, total_steps: int, segment: int,
                interior=None, cycle_check: bool = False):
    """The shared segmented escape recurrence (single source of truth for
    the XLA, sharded, and Pallas kernels).

    Select-free form: escaped pixels are never frozen — they keep iterating
    (diverging to inf, then possibly NaN via inf-inf) while a sticky
    ``active`` mask, cleared at the first ``|z|^2 >= 4`` test, stops their
    count from advancing.  The stickiness matters: exact arithmetic
    guarantees ``|z|`` can never re-enter the bailout disk once outside
    (for ``|c| <= 2``, ``|z_new| >= |z|^2 - |c| >= 2``; the square's
    corners ``|c| in (2, 2*sqrt(2)]`` escape at iteration 1 and grow as
    ``|z_{k+1}| >= |z_k|(|z_k|-1)``), but the inequality is tight at the
    boundary and floating-point rounding could momentarily dip a
    barely-escaped orbit back under 4 — the mask makes the recorded count
    immune to that (and to any downstream NaN comparison semantics).

    The escape iteration is recovered arithmetically: ``n`` counts the
    updates a pixel stayed bounded through, so a pixel escaping at ``e``
    has ``n = e - 1``, and ``n >= total_steps`` means "never escaped
    within budget" -> 0 (this also cancels escapes recorded during the
    last segment's overrun past ``total_steps``).  Per pixel per iteration
    the loop costs 5 mul/add, 1 compare, 1 and, 1 count add.

    ``zr0``/``zi0`` are the initial ``z`` (normally equal to ``c``; passed
    explicitly so shard_map callers can derive them with the union of both
    inputs' varying manual axes).  Returns int32 escape counts.

    ``interior`` (optional bool mask): pixels *proven* in-set by closed
    form (:func:`mandelbrot_interior`) start inactive with their count
    pre-saturated at ``total_steps``, so they come out 0 ("never
    escaped") without iterating — and a tile of only interior + escaped
    pixels takes the tile-granular early exit.  Output is identical to
    full iteration; only the work changes.  Callers must pass it only
    when ``z0 == c`` (the Mandelbrot family — the test is meaningless
    for Julia orbits).

    ``cycle_check``: Brent-style periodicity probe.  A ``z`` bitwise
    equal to a snapshot from an earlier iteration means the orbit
    repeats forever under the deterministic map and can never escape, so
    the count saturates and the lane retires — again output-identical,
    valid for any ``z0``/``c`` (Julia included).  Snapshots refresh at
    doubling iteration gaps (per segment, via ``seg_hook``), so any
    eventual exact-float cycle is caught once the gap exceeds its
    period.  Worth its ~4 extra ops/step only at deep budgets where
    closed forms leave in-set pixels running (higher-period bulbs,
    minibrots, Julia interiors) — see CYCLE_CHECK_MIN_ITER.
    """
    four = jnp.asarray(4.0, jnp.result_type(zr0))

    def one_step(state, k=0, chunk_len=1):
        if cycle_check:
            zr, zi, zr2, zi2, active, n, szr, szi, next_snap = state
        else:
            zr, zi, zr2, zi2, active, n = state
        zi = (zr + zr) * zi + c_imag
        zr = zr2 - zi2 + c_real
        zr2 = zr * zr
        zi2 = zi * zi
        active = active & (zr2 + zi2 < four)
        if cycle_check:
            if probe_step(k, chunk_len):  # strided cadence (CYCLE_STRIDE)
                active, n, _ = cycle_probe_update(zr, zi, szr, szi, active,
                                                  n, total_steps)
            n = n + active.astype(jnp.int32)
            return (zr, zi, zr2, zi2, active, n, szr, szi, next_snap)
        n = n + active.astype(jnp.int32)
        return (zr, zi, zr2, zi2, active, n)

    mix = zr0 * 0 + zi0 * 0  # union of varying axes under shard_map
    active0 = mix == 0
    n0 = mix.astype(jnp.int32)
    if interior is not None:
        active0 = active0 & ~interior
        n0 = n0 + interior.astype(jnp.int32) * total_steps
    init = (zr0, zi0, zr0 * zr0, zi0 * zi0, active0, n0)
    if cycle_check:
        init = init + (zr0, zi0, jnp.asarray(2, jnp.int32))
    state = segmented_while(
        one_step, init, total_steps=total_steps, segment=segment,
        active_of=lambda s: s[4],
        seg_hook=brent_snap_hook if cycle_check else None,
        indexed=True)
    return counts_from_survival(state[5], total_steps)


def family_step(zr, zi, c_real, c_imag, *, power: int, burning: bool):
    """One update of the generalized recurrence ``z <- z^power + c``
    (Multibrot), optionally through the Burning Ship's ``|Re z| +
    i|Im z|`` fold first.  The numpy golden
    (reference.escape_counts_family) mirrors the general formula and
    operation order exactly, so parity differences are FMA-contraction-
    only, as for the core kernels.

    Degree-2 non-burning takes the specialized form — ``(zr+zr)*zi`` is
    one op cheaper than ``zr*zi + zi*zr`` and IEEE-identical (both are
    exact doublings) — so this step also serves the plain Mandelbrot
    recurrence at zero cost (the smooth kernel uses it that way).
    """
    if burning:
        zr = jnp.abs(zr)
        zi = jnp.abs(zi)
    if power == 2:
        return zr * zr - zi * zi + c_real, (zr + zr) * zi + c_imag
    wr, wi = zr, zi
    for _ in range(power - 1):
        wr, wi = wr * zr - wi * zi, wr * zi + wi * zr
    return wr + c_real, wi + c_imag


def escape_loop_generic(step_fn, zr0, zi0, *, total_steps: int, segment: int,
                        cycle_check: bool = False, interior=None):
    """Segmented select-free escape loop for an arbitrary one-step map
    ``step_fn(zr, zi) -> (zr, zi)`` (the Multibrot / Burning Ship
    families, ops.families).

    Same protocol as :func:`escape_loop` — sticky mask, survived-count
    recovery, Brent probe, overrun cancellation, optional proven-interior
    pre-saturation — sharing its helpers (:func:`cycle_probe_update`,
    :func:`brent_snap_hook`, :func:`counts_from_survival`); any protocol
    change must land in both (the z^2+c loop stays specialized so it can
    reuse its cached squares for the next update; this variant recomputes
    ``|z|^2``).
    """
    four = jnp.asarray(4.0, jnp.result_type(zr0))

    def one_step(state, k=0, chunk_len=1):
        if cycle_check:
            zr, zi, active, n, szr, szi, next_snap = state
        else:
            zr, zi, active, n = state
        zr, zi = step_fn(zr, zi)
        active = active & (zr * zr + zi * zi < four)
        if cycle_check:
            if probe_step(k, chunk_len):  # strided cadence (CYCLE_STRIDE)
                active, n, _ = cycle_probe_update(zr, zi, szr, szi, active,
                                                  n, total_steps)
            n = n + active.astype(jnp.int32)
            return (zr, zi, active, n, szr, szi, next_snap)
        n = n + active.astype(jnp.int32)
        return (zr, zi, active, n)

    active0 = zr0 * 0 == 0
    n0 = jnp.zeros(zr0.shape, jnp.int32)
    if interior is not None:
        active0 = active0 & ~interior
        n0 = n0 + interior.astype(jnp.int32) * total_steps
    init = (zr0, zi0, active0, n0)
    if cycle_check:
        init = init + (zr0, zi0, jnp.asarray(2, jnp.int32))
    state = segmented_while(
        one_step, init, total_steps=total_steps, segment=segment,
        active_of=lambda s: s[2],
        seg_hook=brent_snap_hook if cycle_check else None,
        indexed=True)
    return counts_from_survival(state[3], total_steps)


def escape_counts(c_real: jax.Array, c_imag: jax.Array, *, max_iter: int,
                  segment: int = DEFAULT_SEGMENT,
                  interior_check: bool = True,
                  cycle_check: bool | None = None) -> jax.Array:
    """Escape iteration (int32) per element; 0 if never escaped.

    Semantics pinned to the golden reference: z starts at c, iterations
    count 1..max_iter-1, bailout test |z|^2 >= 4 after the update.
    ``interior_check`` applies the closed-form interior shortcut
    (:func:`mandelbrot_interior`) and ``cycle_check`` the Brent
    periodicity probe (None = on for deep budgets) — both
    output-identical, work-saving; disable to time the raw loop.

    Thin dispatch wrapper: float64 inputs enable x64 first — otherwise JAX
    would silently truncate them to float32 and run the fast path while the
    caller believes they got the f64 path.
    """
    dt = getattr(c_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    return _escape_counts_jit(c_real, c_imag, max_iter=max_iter,
                              segment=segment, interior_check=interior_check,
                              cycle_check=resolve_cycle_check(cycle_check,
                                                              max_iter))


@partial(jax.jit, static_argnames=("max_iter", "segment", "interior_check",
                                   "cycle_check"))
def _escape_counts_jit(c_real: jax.Array, c_imag: jax.Array, *, max_iter: int,
                       segment: int = DEFAULT_SEGMENT,
                       interior_check: bool = True,
                       cycle_check: bool = False) -> jax.Array:
    dtype = jnp.result_type(c_real)
    c_real = c_real.astype(dtype)
    c_imag = c_imag.astype(dtype)

    total_steps = max_iter - 1  # iterations 1 .. max_iter-1
    if total_steps <= 0:
        return jnp.zeros(c_real.shape, jnp.int32)
    interior = mandelbrot_interior(c_real, c_imag) if interior_check else None
    return escape_loop(c_real, c_imag, c_real, c_imag,
                       total_steps=total_steps, segment=segment,
                       interior=interior, cycle_check=cycle_check)


def escape_counts_julia(z_real: jax.Array, z_imag: jax.Array,
                        c: complex, *, max_iter: int,
                        segment: int = DEFAULT_SEGMENT,
                        cycle_check: bool | None = None) -> jax.Array:
    """Julia-set escape counts: z starts at the pixel, ``c`` is a constant.

    A capability extension past the reference (which renders only the
    Mandelbrot set) that falls out of the kernel design: the shared
    recurrence (:func:`escape_loop`) already takes the initial ``z``
    independently of ``c``, so the Julia family reuses the identical
    segmented select-free loop, uint8 scaling, and tile plumbing.  Same
    count semantics as :func:`escape_counts` (iterations 1..max_iter-1,
    first test after the first update, 0 = never escaped).

    No closed-form interior exists for arbitrary Julia sets, but the
    Brent cycle probe (``cycle_check``, None = on for deep budgets) is
    z0-agnostic, so connected Julia interiors — attracting-orbit basins
    — still get an in-set shortcut.
    """
    dt = getattr(z_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    c = complex(c)
    dtype = jnp.result_type(z_real)
    # c is traced (not static) so sweeping constants — a Julia animation —
    # reuses one compiled executable.
    return _escape_counts_julia_jit(z_real, z_imag,
                                    jnp.asarray(c.real, dtype),
                                    jnp.asarray(c.imag, dtype),
                                    max_iter=max_iter, segment=segment,
                                    cycle_check=resolve_cycle_check(
                                        cycle_check, max_iter))


@partial(jax.jit, static_argnames=("max_iter", "segment", "cycle_check"))
def _escape_counts_julia_jit(z_real: jax.Array, z_imag: jax.Array,
                             cr: jax.Array, ci: jax.Array,
                             *, max_iter: int, segment: int,
                             cycle_check: bool = False) -> jax.Array:
    dtype = jnp.result_type(z_real)
    total_steps = max_iter - 1
    if total_steps <= 0:
        return jnp.zeros(z_real.shape, jnp.int32)
    return escape_loop(z_real.astype(dtype), z_imag.astype(dtype), cr, ci,
                       total_steps=total_steps, segment=segment,
                       cycle_check=cycle_check)


def compute_tile_julia(spec: TileSpec, c: complex, max_iter: int, *,
                       dtype: np.dtype = np.float32,
                       segment: int = DEFAULT_SEGMENT,
                       clamp: bool = False) -> np.ndarray:
    """One Julia tile end-to-end -> flat uint8 pixels (canonical order)."""
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    z_real, z_imag = spec.grid_2d()
    counts = escape_counts_julia(jnp.asarray(z_real, dtype=dtype),
                                 jnp.asarray(z_imag, dtype=dtype), c,
                                 max_iter=max_iter, segment=segment)
    pixels = scale_counts_to_uint8(counts, max_iter=max_iter, clamp=clamp)
    return np.asarray(pixels).ravel()


def scale_counts_to_uint8(counts: jax.Array, *, max_iter: int,
                          clamp: bool = False) -> jax.Array:
    """See :func:`_scale_counts_jit`; widens beyond int32 when needed."""
    if max_iter - 1 >= INT32_SCALE_LIMIT:  # scaling would wrap int32
        ensure_x64()
    return _scale_counts_jit(counts, max_iter=max_iter, clamp=clamp)


@partial(jax.jit, static_argnames=("max_iter", "clamp"))
def _scale_counts_jit(counts: jax.Array, *, max_iter: int,
                      clamp: bool = False) -> jax.Array:
    """uint8 pixel encoding of escape counts (device-side, exact).

    Parity mode reproduces ``ceil(v*256/max_iter)`` with uint8 *wrap* at 256
    (``DistributedMandelbrotWorkerCUDA.py:96-98``).  Computed as exact
    integer ceil-division ``(v*256 + m - 1) // m`` instead of emulated
    float64 on TPU: for ``v*256 <= 2^24`` and integer ratios bounded by 256,
    the fractional gap above any integer is >= 2^-40 relative, far above
    float64's 2^-52 ulp, so the float64 ``ceil`` the reference computes can
    never disagree with true integer ceil — the paths are bit-identical.

    For ``max_iter - 1 > 2^23`` the product ``counts*256`` would overflow
    int32, so the wrapper enables x64 and the math widens to int64 (still
    exact; the same gap argument holds through the uint32 wire range).
    """
    wide = jnp.int64 if max_iter - 1 >= INT32_SCALE_LIMIT else jnp.int32
    vals = (counts.astype(wide) * 256 + (max_iter - 1)) // max_iter
    if clamp:
        vals = jnp.minimum(vals, 255)
    return vals.astype(jnp.uint8)  # int->uint8 wraps mod 256 deterministically


def escape_smooth(c_real: jax.Array, c_imag: jax.Array, *, max_iter: int,
                  segment: int = DEFAULT_SEGMENT, bailout: float = 256.0,
                  interior_check: bool = True,
                  cycle_check: bool | None = None) -> jax.Array:
    """Continuous (smooth-colored) escape value per element; 0 if never
    escaped.

    The quality-mode companion to :func:`escape_counts` (the reference has
    no smooth coloring — this is the deep-zoom rendering extension of
    BASELINE.md config 4): returns the renormalized iteration count
    ``nu = e + 1 - log2(ln|z_e| / ln(bailout))`` where ``e`` is the escape
    iteration against radius ``bailout``.  A large bailout (default 256)
    makes the log-log correction accurate, eliminating the color banding of
    integer counts.

    In-set classification follows :func:`escape_counts` semantics: the
    kernel tracks the radius-2 bounded count alongside the radius-
    ``bailout`` orbit, so ``nu == 0`` iff the radius-2 budget was
    exhausted, even for pixels whose radius-2 escape lands in the last
    iterations of the budget (the loop runs a few extra segments so their
    orbit can reach the smoothing radius).  As with every JAX path here,
    agreement with the numpy golden is statistical, not bit-exact — FMA
    contraction can shift O(1) chaotic-boundary pixels (module docstring).

    Unlike the select-free parity loop, escaped pixels freeze here — their
    ``z`` at escape is the payload.  Values are returned in the kernel
    dtype (float32 fast path / float64 deep zoom).
    """
    dt = getattr(c_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    return _escape_smooth_jit(c_real, c_imag, c_real, c_imag,
                              max_iter=max_iter, segment=segment,
                              bailout=float(bailout),
                              interior_check=interior_check,
                              cycle_check=resolve_cycle_check(cycle_check,
                                                              max_iter))


def escape_smooth_julia(z_real: jax.Array, z_imag: jax.Array, c: complex, *,
                        max_iter: int, segment: int = DEFAULT_SEGMENT,
                        bailout: float = 256.0,
                        cycle_check: bool | None = None) -> jax.Array:
    """Smooth coloring for the Julia family (z starts at the pixel, ``c``
    constant and traced — constant sweeps reuse one executable).  Same
    semantics as :func:`escape_smooth`."""
    dt = getattr(z_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    c = complex(c)
    dtype = jnp.result_type(z_real)
    return _escape_smooth_jit(z_real, z_imag,
                              jnp.asarray(c.real, dtype),
                              jnp.asarray(c.imag, dtype),
                              max_iter=max_iter, segment=segment,
                              bailout=float(bailout), interior_check=False,
                              cycle_check=resolve_cycle_check(cycle_check,
                                                              max_iter))


@partial(jax.jit, static_argnames=("max_iter", "segment", "bailout",
                                   "interior_check", "cycle_check", "power",
                                   "burning"))
def _escape_smooth_jit(zr0: jax.Array, zi0: jax.Array,
                       c_real: jax.Array, c_imag: jax.Array, *,
                       max_iter: int, segment: int, bailout: float,
                       interior_check: bool = False,
                       cycle_check: bool = False, power: int = 2,
                       burning: bool = False) -> jax.Array:
    dtype = jnp.result_type(zr0)
    zr0 = zr0.astype(dtype)
    zi0 = zi0.astype(dtype)
    c_real = c_real.astype(dtype)
    c_imag = c_imag.astype(dtype)
    total_steps = max_iter - 1
    if total_steps <= 0:
        return jnp.zeros(zr0.shape, dtype)
    four = jnp.asarray(4.0, dtype)
    b2 = jnp.asarray(bailout * bailout, dtype)

    def one_step(state, k=0, chunk_len=1):
        if cycle_check:
            zr, zi, active, n, bounded2, n2, szr, szi, next_snap = state
        else:
            zr, zi, active, n, bounded2, n2 = state
        # family_step's power-2 path IS the plain recurrence (exact same
        # op mix); other degrees/burning serve the extended families.
        nzr, nzi = family_step(zr, zi, c_real, c_imag, power=power,
                               burning=burning)
        zr = jnp.where(active, nzr, zr)
        zi = jnp.where(active, nzi, zi)
        m2 = zr * zr + zi * zi
        active = active & (m2 < b2)
        n = n + active.astype(jnp.int32)
        # Radius-2 count runs alongside (sticky, like the parity loop) so
        # in-set classification matches escape_counts exactly.
        bounded2 = bounded2 & (m2 < four)
        if cycle_check:
            if probe_step(k, chunk_len):  # strided cadence (CYCLE_STRIDE)
                # bounded2 implies still-active (radius 2 clears before
                # the bailout radius), so the probe only ever fires on
                # live, still-iterating orbits.  Saturating n2
                # classifies the lane in-set; the frozen z it leaves
                # behind is discarded by the output branch.
                bounded2, n2, cyc = cycle_probe_update(
                    zr, zi, szr, szi, bounded2, n2, total_steps)
                active = active & ~cyc
            n2 = n2 + bounded2.astype(jnp.int32)
            return (zr, zi, active, n, bounded2, n2, szr, szi, next_snap)
        n2 = n2 + bounded2.astype(jnp.int32)
        return (zr, zi, active, n, bounded2, n2)

    # Extra budget lets orbits that cross radius 2 in the last iterations
    # still reach the smoothing radius; the radius-2 count is what's
    # compared against total_steps, so the extra steps never change
    # classification.  From |z| > 2 the orbit at least squares-minus-|c|
    # each step, so bailout is reached within a handful of steps except
    # for orbits hovering at 2+eps (which get nu = n+2 via the clamp).
    extra = 8 + int(np.ceil(np.log2(np.log2(max(bailout, 4.0)))))
    mix = zr0 * 0 + zi0 * 0
    active0 = mix == 0
    n2_0 = mix.astype(jnp.int32)
    if interior_check:  # valid only for z0 == c (Mandelbrot-family callers)
        interior = family_interior(c_real + mix, c_imag + mix, power,
                                   burning)
        if interior is not None:
            # Proven-interior pixels: inactive from the start (their z
            # stays frozen at c — harmless, the output branch discards
            # it), radius-2 count pre-saturated so they classify in-set
            # (nu = 0) exactly as if they had iterated the full budget.
            active0 = active0 & ~interior
            n2_0 = n2_0 + interior.astype(jnp.int32) * total_steps
    init = (zr0 + mix, zi0 + mix, active0, mix.astype(jnp.int32),
            active0, n2_0)
    if cycle_check:
        init = init + (zr0 + mix, zi0 + mix, jnp.asarray(2, jnp.int32))
    state = segmented_while(
        one_step, init, total_steps=total_steps + extra, segment=segment,
        active_of=lambda s: s[2],
        seg_hook=brent_snap_hook if cycle_check else None,
        indexed=True)
    zr, zi, active, n, bounded2, n2 = state[:6]

    # Frozen |z_e| is in [bailout, ~bailout^2 + |c|) — one squaring past
    # the test — so mag2 is in [bailout^2, ~bailout^4) and log_ratio in
    # [1, ~2); nu = n + 2 - log2(log_ratio) can therefore never go
    # negative.  The clamp guards lanes that never reached the smoothing
    # radius within the extra budget (hovering just outside radius 2):
    # they get log_ratio 1 -> nu = n + 2.
    # Sanitized on BOTH sides: the lower bound is the laggard clamp (see
    # below); the upper bound keeps high multibrot degrees finite in f32
    # — a lane freezes one step past bailout, where |z|^2 ~ bailout^(2d)
    # overflows float32 to inf for d >= 8 (and the step's inf - inf
    # leaves NaN components in the frozen z for d >= 17), either of
    # which would corrupt nu (to -inf/NaN, rendered as in-set).  Pinning
    # both to the dtype max costs a bounded correction error on exactly
    # those saturated lanes.
    # dmtpu: ignore[jax-host-sync] — finfo(dtype).max is static metadata, not a tracer
    big = float(jnp.finfo(dtype).max)
    mag2 = jnp.clip(jnp.nan_to_num(zr * zr + zi * zi, nan=big, posinf=big),
                    b2, big)
    log_ratio = jnp.log(mag2) / jnp.asarray(2.0 * np.log(bailout), dtype)
    corr = jnp.log2(log_ratio)
    if power != 2:
        # Degree-d renormalization: |z| grows like |z|^d per step, so the
        # fractional correction is log_d of the log-ratio.
        corr = corr / jnp.asarray(np.log2(power), dtype)
    nu = (n + 2).astype(dtype) - corr
    # In-set iff the radius-2 count exhausted the reference budget (n2
    # counts only iterations 1..total_steps thanks to the sticky mask and
    # the fact that an overrun past total_steps implies n2 already
    # saturated or the pixel escaped radius 2 earlier).
    return jnp.where(n2 >= total_steps, jnp.zeros((), dtype), nu)


def compute_tile_smooth(spec: TileSpec, max_iter: int, *,
                        dtype: np.dtype = np.float64,
                        segment: int = DEFAULT_SEGMENT,
                        bailout: float = 256.0,
                        julia_c: complex | None = None,
                        cycle_check: bool | None = None) -> np.ndarray:
    """One tile through the smooth-coloring path -> 2-D float array.

    With ``julia_c`` set, renders the Julia set for that constant instead
    of the Mandelbrot set.
    """
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    g_real, g_imag = spec.grid_2d()
    g_real = jnp.asarray(g_real, dtype=dtype)
    g_imag = jnp.asarray(g_imag, dtype=dtype)
    if julia_c is None:
        nu = escape_smooth(g_real, g_imag, max_iter=max_iter,
                           segment=segment, bailout=bailout,
                           cycle_check=cycle_check)
    else:
        nu = escape_smooth_julia(g_real, g_imag, julia_c, max_iter=max_iter,
                                 segment=segment, bailout=bailout,
                                 cycle_check=cycle_check)
    return np.asarray(nu)


def compute_tile(spec: TileSpec, max_iter: int, *,
                 dtype: np.dtype = np.float32,
                 segment: int = DEFAULT_SEGMENT,
                 clamp: bool = False,
                 device: jax.Device | None = None,
                 interior_check: bool = True,
                 cycle_check: bool | None = None) -> np.ndarray:
    """Compute one tile end-to-end: grid -> device kernel -> uint8 pixels.

    Returns the flat uint8 array in the canonical real-fastest order.  The
    sample grid is always generated in float64 on the host (bit-identical to
    the reference's ``np.linspace``) and cast to ``dtype`` for the kernel, so
    the float64 path is the exact parity path.  The shortcut toggles pass
    through to :func:`escape_counts` (output-identical either way; off for
    timing the raw loop).
    """
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    c_real, c_imag = spec.grid_2d()
    c_real = jnp.asarray(c_real, dtype=dtype)
    c_imag = jnp.asarray(c_imag, dtype=dtype)
    if device is not None:
        c_real = jax.device_put(c_real, device)
        c_imag = jax.device_put(c_imag, device)
    counts = escape_counts(c_real, c_imag, max_iter=max_iter, segment=segment,
                           interior_check=interior_check,
                           cycle_check=cycle_check)
    pixels = scale_counts_to_uint8(counts, max_iter=max_iter, clamp=clamp)
    return np.asarray(pixels).ravel()
