"""JAX escape-time kernels, TPU-first.

The reference's CUDA kernel (``DistributedMandelbrotWorkerCUDA.py:39-68``)
returns per-pixel at the escape iteration.  SIMD/vector hardware has no
per-element early return, so the TPU-native form is *masked iteration*:
every pixel advances under a mask that freezes it once escaped (freezing
also prevents inf/nan pollution from continued squaring).  Early exit is
recovered at tile granularity with a segmented ``lax.while_loop`` — run
``segment`` masked iterations at a time (an unrolled ``fori_loop`` body XLA
fuses into one elementwise loop nest), then stop when the whole tile has
escaped or the iteration budget is spent.  For typical views most of the
tile escapes early, so segments capture most of the CUDA early-exit win
without data-dependent control flow inside the hot loop.

Two precision paths:

- ``float64`` path — near-exact vs the numpy golden
  (:mod:`distributedmandelbrot_tpu.ops.reference`).  *Near*, not bit-exact:
  XLA's backends contract ``mul+add/sub`` chains into FMA/FMS (single
  rounding), and the contraction survives ``optimization_barrier`` because
  fusions recompute producers; no supported flag disables it
  (``--xla_allow_excess_precision=false`` does not).  The effect is a
  last-ulp trajectory difference that changes the escape count of O(1)
  chaotic-boundary pixels per tile (measured ~0.02% at depth 1000).  The
  framework's *bit-exact* parity anchors are therefore the host paths —
  the numpy golden and the native C++ kernel built with
  ``-ffp-contract=off`` — and the JAX paths are validated against them
  statistically.
- ``float32`` fast path — the TPU throughput path; boundary pixels may
  land in adjacent iteration buckets, acceptable for rendering and
  benchmarked separately.

All functions are pure and jit-compiled with static ``max_iter`` and
``segment`` (a handful of distinct depths per run, so recompiles are rare
and each specialization unrolls its segment body).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.utils.precision import ensure_x64

DEFAULT_SEGMENT = 32


def escape_counts(c_real: jax.Array, c_imag: jax.Array, *, max_iter: int,
                  segment: int = DEFAULT_SEGMENT) -> jax.Array:
    """Escape iteration (int32) per element; 0 if never escaped.

    Semantics pinned to the golden reference: z starts at c, iterations
    count 1..max_iter-1, bailout test |z|^2 >= 4 after the update.

    Thin dispatch wrapper: float64 inputs enable x64 first — otherwise JAX
    would silently truncate them to float32 and run the fast path while the
    caller believes they got the f64 path.
    """
    dt = getattr(c_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    return _escape_counts_jit(c_real, c_imag, max_iter=max_iter,
                              segment=segment)


@partial(jax.jit, static_argnames=("max_iter", "segment"))
def _escape_counts_jit(c_real: jax.Array, c_imag: jax.Array, *, max_iter: int,
                       segment: int = DEFAULT_SEGMENT) -> jax.Array:
    dtype = jnp.result_type(c_real)
    c_real = c_real.astype(dtype)
    c_imag = c_imag.astype(dtype)
    four = jnp.asarray(4.0, dtype)
    two = jnp.asarray(2.0, dtype)

    total_steps = max_iter - 1  # iterations 1 .. max_iter-1
    if total_steps <= 0:
        return jnp.zeros(c_real.shape, jnp.int32)
    segment = max(1, min(segment, total_steps))

    def one_step(state, it):
        zr, zi, counts = state
        active = counts == 0
        new_zr = zr * zr - zi * zi + c_real
        new_zi = two * zr * zi + c_imag
        zr = jnp.where(active, new_zr, zr)
        zi = jnp.where(active, new_zi, zi)
        escaped = active & (zr * zr + zi * zi >= four)
        counts = jnp.where(escaped, it, counts)
        return (zr, zi, counts)

    def segment_body(carry):
        zr, zi, counts, it = carry
        state = (zr, zi, counts)
        # Unrolled fixed-trip segment; `it + k` stays a traced scalar.
        for k in range(segment):
            state = one_step(state, it + k)
        zr, zi, counts = state
        return (zr, zi, counts, it + segment)

    def segment_cond(carry):
        zr, zi, counts, it = carry
        # Keep going while budget remains and any pixel is still active.
        # Pixels that never escape stay active to the end, exactly like the
        # reference's full-depth loop.
        return (it <= total_steps) & jnp.any(counts == 0)

    init = (c_real, c_imag, jnp.zeros(c_real.shape, jnp.int32),
            jnp.asarray(1, jnp.int32))
    zr, zi, counts, it = lax.while_loop(segment_cond, segment_body, init)
    # The last segment may overrun past total_steps; cancel counts recorded
    # beyond the budget (they belong to iterations the reference never runs).
    counts = jnp.where(counts > total_steps, 0, counts)
    return counts


def scale_counts_to_uint8(counts: jax.Array, *, max_iter: int,
                          clamp: bool = False) -> jax.Array:
    """See :func:`_scale_counts_jit`; widens beyond int32 when needed."""
    if max_iter - 1 > (1 << 23):  # counts*256 would overflow int32's 2^31
        ensure_x64()
    return _scale_counts_jit(counts, max_iter=max_iter, clamp=clamp)


@partial(jax.jit, static_argnames=("max_iter", "clamp"))
def _scale_counts_jit(counts: jax.Array, *, max_iter: int,
                      clamp: bool = False) -> jax.Array:
    """uint8 pixel encoding of escape counts (device-side, exact).

    Parity mode reproduces ``ceil(v*256/max_iter)`` with uint8 *wrap* at 256
    (``DistributedMandelbrotWorkerCUDA.py:96-98``).  Computed as exact
    integer ceil-division ``(v*256 + m - 1) // m`` instead of emulated
    float64 on TPU: for ``v*256 <= 2^24`` and integer ratios bounded by 256,
    the fractional gap above any integer is >= 2^-40 relative, far above
    float64's 2^-52 ulp, so the float64 ``ceil`` the reference computes can
    never disagree with true integer ceil — the paths are bit-identical.

    For ``max_iter - 1 > 2^23`` the product ``counts*256`` would overflow
    int32, so the wrapper enables x64 and the math widens to int64 (still
    exact; the same gap argument holds through the uint32 wire range).
    """
    wide = jnp.int64 if max_iter - 1 > (1 << 23) else jnp.int32
    vals = (counts.astype(wide) * 256 + (max_iter - 1)) // max_iter
    if clamp:
        vals = jnp.minimum(vals, 255)
    return vals.astype(jnp.uint8)  # int->uint8 wraps mod 256 deterministically


def compute_tile(spec: TileSpec, max_iter: int, *,
                 dtype: np.dtype = np.float32,
                 segment: int = DEFAULT_SEGMENT,
                 clamp: bool = False,
                 device: jax.Device | None = None) -> np.ndarray:
    """Compute one tile end-to-end: grid -> device kernel -> uint8 pixels.

    Returns the flat uint8 array in the canonical real-fastest order.  The
    sample grid is always generated in float64 on the host (bit-identical to
    the reference's ``np.linspace``) and cast to ``dtype`` for the kernel, so
    the float64 path is the exact parity path.
    """
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    c_real, c_imag = spec.grid_2d()
    c_real = jnp.asarray(c_real, dtype=dtype)
    c_imag = jnp.asarray(c_imag, dtype=dtype)
    if device is not None:
        c_real = jax.device_put(c_real, device)
        c_imag = jax.device_put(c_imag, device)
    counts = escape_counts(c_real, c_imag, max_iter=max_iter, segment=segment)
    pixels = scale_counts_to_uint8(counts, max_iter=max_iter, clamp=clamp)
    return np.asarray(pixels).ravel()
