"""BLA (bilinear approximation) acceleration for perturbation deep zoom.

State of the art for deep-zoom renderers (Kalles Fraktaler 2.15+,
fractalshades): over an orbit segment where the quadratic term of the
delta recurrence is negligible, ``L`` perturbation steps collapse to ONE
bilinear map

    dz_{n+L} = A dz_n + B dc

with ``(A, B)`` composed from the reference orbit and a conservative
validity radius ``r`` bounding ``|dz_n|`` so the dropped ``dz^2`` terms
stay below ``eps`` of the linear term.  Tables for skip lengths 1, 2, 4,
... are built host-side in float64 by pairwise merging (O(orbit) work,
a few MB) and the device loop applies the longest valid skip each
iteration.

TPU-native twist — **tile-granular skipping**: per-lane skip lengths
diverge (the classical CPU implementations branch per pixel), which is
poison for SIMD.  Here ONE skip decision is made per chunk per
iteration from the maximum live ``|dz|``, so the whole chunk advances in
lockstep: far-from-escape lanes (tiny deltas — the overwhelming
majority of a deep view) ride long skips, and as soon as any live lane
grows, the chunk degrades to exact single steps — which is precisely
when accuracy matters.  Callers chunk tiles (see
``perturbation._compute_perturb``) so a stalled region doesn't gate the
whole tile.

Accuracy contract (why this is an OPT-IN fast path, not the default):
- the escape test runs at skip boundaries, not inside skipped segments,
  so a pixel escaping mid-segment is detected late — its count lands at
  the segment end (error < the skip length).  In practice lanes near
  escape have large ``|dz|`` and fail the radius checks, forcing exact
  steps, so measured count errors are confined to scattered boundary
  pixels;
- the same holds for Pauldelbrot glitch detection — a glitch inside a
  skipped segment is flagged at the boundary (still flagged: glitched
  deltas COLLAPSE toward ``-Z``, i.e. grow to ``|Z|`` scale, which
  blows the radius check and forces exact stepping into the glitch);
- skipped steps drop the quadratic term: deltas differ from the exact
  scan at relative ``eps`` per skip (default 2^-16, ~256 ulps of f32
  noise across a whole render).

Reference files for the semantics being accelerated:
``_perturb_scan`` (ops/perturbation.py) — counts, glitch flags and the
in-set convention are identical by construction for pixels that never
ride an invalid skip.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.ops.perturbation import GLITCH_TOL

logger = logging.getLogger("dmtpu.bla")

# Relative size of the dropped quadratic term at the base level:
# |dz| < eps * |Z| keeps |dz^2| below eps of the linear |2 Z dz|.
DEFAULT_BLA_EPS = 2.0 ** -16

# Deepest skip = 2^LMAX steps; the loop pays (LMAX - min level) scalar
# level checks per iteration.
BLA_LEVELS_MAX = 14

# The period-6 bond point of the main cardioid, c = 3/8 + i sqrt(3)/8
# (boundary angle pi/3) — exactly representable as decimal strings
# (imag = isqrt(3 * 10^80) * 125, digit-shifted), and the canonical
# slow-dynamics benchmark view for this module: parabolic (multiplier
# 1) dynamics keep every pixel of a deep window iterating to the full
# budget, the case BLA accelerates ~10x.  Shared by bench.py's
# deep-slow config and the test suite so they can never drift.
BOND_POINT_RE = "0.375"
BOND_POINT_IM = "0.2165063509461096616909307926882340458678500"

# Shortest STORED (and selectable) skip: skips below this aren't worth
# an iteration's overhead (level checks + gathers + the live-max
# reduction) versus just bursting exact steps, so levels under it are
# merge intermediates only — never stored, uploaded, or selected.
# Storage therefore costs ~5 * 2 * N / BLA_MIN_SKIP entries (row width
# N / min_skip, halving per level), not the dense levels * N / 2.
BLA_MIN_SKIP = 64


def build_bla_table(z_re: np.ndarray, z_im: np.ndarray, dc_max: float,
                    *, eps: float = DEFAULT_BLA_EPS,
                    levels: int | None = None,
                    z_cap: float = 4.0):
    """Pairwise-merged BLA tables over a reference orbit (host, f64).

    Returns ``(A_re, A_im, B_re, B_im, r2)`` each shaped
    ``(rows, ceil(N / BLA_MIN_SKIP))`` — row ``i`` holds the entries for
    skip length ``BLA_MIN_SKIP * 2^i`` (from orbit positions aligned to
    it), right-padded with zeros (r2 = 0 => never valid).  ``dc_max`` is
    the largest ``|dc|`` any lane will use — the merge's cross term is
    bounded with it, so one table serves a whole tile.

    Merge rule for segment1 (A1,B1,r1) followed by segment2 (A2,B2,r2):
    valid iff the input delta fits segment1 AND the output of segment1
    fits segment2 — conservatively ``|dz| < min(r1, (r2 - |B1| dc_max)
    / |A1|)``; the composed map is ``A = A2 A1, B = A2 B1 + B2``.

    ``z_cap`` zeroes base radii at orbit positions with ``|Z| >=
    z_cap``.  The default (4.0) invalidates every segment containing a
    post-escape entry beyond the first one or two steps (a bounded
    reference stays |Z| <= 2; after escape |Z| squares past 4 within a
    couple of steps toward ~1e100): segments straddling the escape
    would otherwise merge huge-but-positive-radius entries whose
    coefficients saturate to inf in f32, and a zero-delta lane skipped
    through one NaN-poisons into a false in-set (found in review;
    regression-tested).  The earliest straddling positions that slip
    the cap keep FINITE coefficients (late detection there is the
    ordinary skip-boundary contract); additionally, stored radii are
    zeroed wherever the merged coefficients exceed f32 range.  The
    smooth factory passes ``min(4, bailout/2)`` so skips also never
    cross the smoothing radius.
    """
    n = len(z_re)
    min_level = max(1, BLA_MIN_SKIP.bit_length() - 1)
    if levels is None:
        levels = min(BLA_LEVELS_MAX, max(min_level,
                                         int(np.log2(max(2, n)))))
    z = z_re.astype(np.float64) + 1j * z_im.astype(np.float64)
    # Single-step linearization at position k: dz' = 2 Z_k dz + dc.
    a = 2.0 * z
    b = np.ones_like(z)
    with np.errstate(over="ignore", invalid="ignore"):
        r = eps * np.abs(z)
        r = np.where(np.abs(z) < z_cap, r, 0.0)
    rows = max(1, levels - min_level + 1)
    width = max(1, (n + BLA_MIN_SKIP - 1) // BLA_MIN_SKIP)
    A_re = np.zeros((rows, width))
    A_im = np.zeros((rows, width))
    B_re = np.zeros((rows, width))
    B_im = np.zeros((rows, width))
    R2 = np.zeros((rows, width))

    f32_max = float(np.finfo(np.float32).max)

    def store(row, a_l, b_l, r_l):
        k = len(a_l)
        A_re[row, :k] = a_l.real
        A_im[row, :k] = a_l.imag
        B_re[row, :k] = b_l.real
        B_im[row, :k] = b_l.imag
        # A coefficient the f32 upload would saturate must never be
        # selectable (inf * 0 = NaN poisons zero-delta lanes).
        fits = (np.isfinite(a_l) & np.isfinite(b_l)
                & (np.abs(a_l.real) < f32_max)
                & (np.abs(a_l.imag) < f32_max)
                & (np.abs(b_l.real) < f32_max)
                & (np.abs(b_l.imag) < f32_max))
        R2[row, :k] = np.where(fits,
                               np.square(np.maximum(r_l, 0.0)), 0.0)

    # a/b/r start as the per-position single-step maps (skip 1 — the
    # exact path handles single steps, quadratic term included); each
    # merge pass halves the count and doubles the skip.  Levels below
    # min_level are intermediates only.
    for level in range(1, levels + 1):
        m = len(a) // 2
        if m == 0:
            break
        a1, a2 = a[0:2 * m:2], a[1:2 * m:2]
        b1, b2 = b[0:2 * m:2], b[1:2 * m:2]
        r1, r2_ = r[0:2 * m:2], r[1:2 * m:2]
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            abs_a1 = np.abs(a1)
            abs_b1 = np.abs(b1)
            r_out = np.minimum(
                r1, np.where(abs_a1 > 0,
                             (r2_ - abs_b1 * dc_max) / np.maximum(
                                 abs_a1, 1e-300), 0.0))
            a_m = a2 * a1
            b_m = a2 * b1 + b2
        r_out = np.where(np.isfinite(r_out), r_out, 0.0)
        a_m = np.where(np.isfinite(a_m), a_m, 0.0)
        b_m = np.where(np.isfinite(b_m), b_m, 0.0)
        if level >= min_level:
            store(level - min_level, a_m, b_m, r_out)
        a, b, r = a_m, b_m, np.maximum(r_out, 0.0)
    return A_re, A_im, B_re, B_im, R2


_TABLE_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_TABLE_CACHE_MAX = 4
# Byte bound, same rationale as perturbation's device-orbit cache:
# giant-budget tables must not pin HBM when upstream caches thrash.
_TABLE_CACHE_MAX_BYTES = 256 * 1024 * 1024


def _device_table(z_re: np.ndarray, z_im: np.ndarray, dc_max: float,
                  eps: float, dtype, z_cap: float = 4.0):
    """Device-resident BLA table, LRU-cached like the orbit itself
    (perturbation._device_orbit): animation frames and repeat renders
    share the host orbit arrays, so identity + fingerprint keys work;
    dc_max is quantized a power of two up so nearby frames share."""
    q = float(2.0 ** np.ceil(np.log2(max(dc_max, 1e-300))))
    key = (id(z_re), id(z_im), len(z_re), q, eps, np.dtype(dtype).str,
           z_cap)
    # Fingerprint matches _device_orbit's guard strength and adds a
    # mid-orbit sample: an id()-reuse collision after upstream lru
    # eviction must not serve a stale table for a different orbit that
    # happens to share length and endpoints (round-3 advisor).
    mid = len(z_re) // 2
    fp = (float(z_re[0]), float(z_im[0]), float(z_re[-1]),
          float(z_im[-1]), float(z_re[mid]), float(z_im[mid]))
    hit = _TABLE_CACHE.get(key)
    if hit is not None and hit[0] == fp:
        _TABLE_CACHE.move_to_end(key)
        return hit[1]
    host = build_bla_table(z_re, z_im, q, eps=eps, z_cap=z_cap)
    # The cast may saturate extension-segment coefficients to inf; the
    # builder zeroes those entries' radii (z_cap + f32-range gates), so
    # they are never selected — the warning is noise.
    with np.errstate(over="ignore"):
        dev = tuple(jnp.asarray(t, dtype) for t in host)
    _TABLE_CACHE[key] = (fp, dev)

    def total_bytes():
        return sum(sum(t.nbytes for t in e[1])
                   for e in _TABLE_CACHE.values())

    while (len(_TABLE_CACHE) > _TABLE_CACHE_MAX
           or (len(_TABLE_CACHE) > 1
               and total_bytes() > _TABLE_CACHE_MAX_BYTES)):
        _TABLE_CACHE.popitem(last=False)
    return dev


# Exact steps advanced per iteration when no skip validates: amortizes
# the level checks / gathers / live-max reduction that otherwise triple
# the cost of regions stuck on single steps.  256 matches the plain
# scan's slice length (perturbation.PERTURB_SEGMENT grade), measured
# necessary to keep burst-only regions near plain-scan speed.
BLA_EXACT_BURST = 256


def _select_skip(n, max_dz2, R2, levels: int, orbit_len: int):
    """Largest valid aligned skip LEVEL for the whole chunk, or 0.
    Table row i covers skip length 2^(min_level + i); levels below
    min_level are not stored (see BLA_MIN_SKIP) — a region that can
    only manage tiny skips runs exact bursts at plain-scan speed.  The
    single copy of the validity condition for BOTH scan variants."""
    min_level = max(1, BLA_MIN_SKIP.bit_length() - 1)
    l_sel = jnp.asarray(0, jnp.int32)
    for lv in range(min_level + levels - 1, min_level - 1, -1):
        span = 1 << lv
        idx = n >> lv
        ok = ((n & (span - 1)) == 0) & (n + span <= orbit_len) \
            & (max_dz2 < R2[lv - min_level, idx])
        l_sel = jnp.where((l_sel == 0) & ok, lv, l_sel)
    return l_sel


def _apply_skip_map(l_sel, n, tabs, dzr, dzi, dc_re, dc_im,
                    add_dc: bool):
    """Apply the selected level's bilinear map: ``dz' = A dz + B dc``,
    advancing ``n`` by the skip length.  The single copy of the gather
    and complex arithmetic for both scan variants."""
    min_level = max(1, BLA_MIN_SKIP.bit_length() - 1)
    A_re, A_im, B_re, B_im, _ = tabs
    li = jnp.maximum(l_sel - min_level, 0)
    ti = n >> jnp.maximum(l_sel, 1)
    ar = A_re[li, ti]
    ai = A_im[li, ti]
    br = B_re[li, ti]
    bi = B_im[li, ti]
    bla_r = ar * dzr - ai * dzi
    bla_i = ar * dzi + ai * dzr
    if add_dc:
        bla_r = bla_r + (br * dc_re - bi * dc_im)
        bla_i = bla_i + (br * dc_im + bi * dc_re)
    return n + (jnp.int32(1) << l_sel), bla_r, bla_i


def _padded_orbit(z_re, z_im, dtype):
    """Orbit cast to the delta dtype (it arrives f64 under x64 — same
    cast as _segmented_orbit_scan's callers) with tail padding so the
    bursts' fixed-size dynamic slices always fit; the per-step validity
    gate keeps padded values inert."""
    return (jnp.concatenate([z_re.astype(dtype),
                             jnp.zeros(BLA_EXACT_BURST, dtype)]),
            jnp.concatenate([z_im.astype(dtype),
                             jnp.zeros(BLA_EXACT_BURST, dtype)]))


@partial(jax.jit, static_argnames=("orbit_len", "max_iter", "levels",
                                   "add_dc"))
def _bla_scan(z_re, z_im, tabs, dc_re, dc_im, *, orbit_len: int,
              max_iter: int, levels: int, add_dc: bool = True):
    """Delta advance with tile-granular BLA skips.

    Same output conventions as ``_perturb_scan`` (counts, glitched,
    active) for pixels that never ride a skip; see the module accuracy
    contract for the rest.  The while carry holds the chunk's vector
    state plus the scalar orbit position ``n``.  Iterations either apply
    ONE bilinear skip or, when no level validates, a
    :data:`BLA_EXACT_BURST`-step run of the exact per-step recurrence
    (tests included — semantically the plain scan for those steps).
    """
    dtype = jnp.result_type(dc_re)
    shape = dc_re.shape
    four = jnp.asarray(4.0, dtype)
    tol = jnp.asarray(GLITCH_TOL, dtype)
    R2 = tabs[4]
    z_re, z_im = _padded_orbit(z_re, z_im, dtype)

    def _burst_step(s, xs):
        """One exact step of the burst scan: the plain _perturb_scan
        step plus a scalar validity guard for bursts straddling the
        orbit end (one guard variant only — a cond choosing between an
        ungated and a gated scan was observed on XLA:TPU costing as if
        BOTH branches execute).  Retirement positions come from per-lane
        pass counting: a lane failing the test at in-burst offset j has
        accumulated j passes, so cnt = n0 + passes — identical to the
        positional convention."""
        dzr, dzi, act, npass, glitched = s
        zr, zi, i = xs
        valid = i < orbit_len
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (act & valid & (mag2 < tol * zmag2))
        act2 = act & ((mag2 < four) | ~valid)
        npass = npass + act2.astype(jnp.int32)
        ndzr = ((zr + zr) * dzr - (zi + zi) * dzi
                + (dzr * dzr - dzi * dzi))
        ndzi = (zr + zr) * dzi + (zi + zi) * dzr + 2 * dzr * dzi
        if add_dc:
            ndzr = ndzr + dc_re
            ndzi = ndzi + dc_im
        ndzr = jnp.where(valid, ndzr, dzr)
        ndzi = jnp.where(valid, ndzi, dzi)
        return (ndzr, ndzi, act2, npass, glitched), None

    def exact_burst(state):
        n0, dzr, dzi, act, cnt, glitched, skipped = state
        zseg_r = lax.dynamic_slice_in_dim(z_re, n0, BLA_EXACT_BURST)
        zseg_i = lax.dynamic_slice_in_dim(z_im, n0, BLA_EXACT_BURST)
        idx = n0 + jnp.arange(BLA_EXACT_BURST, dtype=jnp.int32)
        (dzr, dzi, act2, npass, glitched), _ = lax.scan(
            _burst_step,
            (dzr, dzi, act, jnp.zeros(shape, jnp.int32), glitched),
            (zseg_r, zseg_i, idx))
        newly = act & ~act2
        cnt = jnp.where(newly, n0 + npass, cnt)
        return (n0 + BLA_EXACT_BURST, dzr, dzi, act2, cnt, glitched,
                skipped)

    def body(state):
        n, dzr, dzi, act, cnt, glitched, skipped = state
        zr = z_re[n]
        zi = z_im[n]
        # Escape/glitch test of z_{n+1} = Z[n] + dz_{n+1} (re-testing a
        # position after a skip is harmless: positional counts).
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (act & (mag2 < tol * zmag2))
        newly_out = act & (mag2 >= four)
        cnt = jnp.where(newly_out, n, cnt)
        act = act & ~newly_out
        max_dz2 = jnp.max(jnp.where(act, dzr * dzr + dzi * dzi,
                                    jnp.zeros((), dtype)))
        l_sel = _select_skip(n, max_dz2, R2, levels, orbit_len)

        def apply_skip(s):
            n, dzr, dzi, act, cnt, glitched, skipped = s
            n2, bla_r, bla_i = _apply_skip_map(l_sel, n, tabs, dzr, dzi,
                                               dc_re, dc_im, add_dc)
            # n2 - n: the advance _apply_skip_map actually made — the
            # single source of truth for the skip length.
            return (n2, bla_r, bla_i, act, cnt, glitched,
                    skipped + (n2 - n))

        return lax.cond(l_sel > 0, apply_skip, exact_burst,
                        (n, dzr, dzi, act, cnt, glitched, skipped))

    def cond(state):
        n, _, _, act = state[:4]
        return (n < orbit_len) & jnp.any(act)

    init = (jnp.asarray(0, jnp.int32), dc_re.astype(dtype),
            dc_im.astype(dtype), jnp.ones(shape, jnp.bool_),
            jnp.full(shape, orbit_len, jnp.int32),
            jnp.zeros(shape, jnp.bool_), jnp.asarray(0, jnp.int32))
    (n, dzr, dzi, act, cnt, glitched, skipped) = \
        lax.while_loop(cond, body, init)
    # Lanes still active when the loop left: position n tests passed —
    # n == orbit_len normally; an early exit (all inactive) leaves their
    # cnt at the orbit_len sentinel, same thing.
    if orbit_len < max_iter:
        glitched = glitched | act
    counts = jnp.where(cnt >= max_iter, 0, jnp.maximum(cnt, 1))
    return counts, glitched, act, skipped


def bla_scan_factory(z_re: np.ndarray, z_im: np.ndarray, dc_max: float, *,
                     max_iter: int, dtype, add_dc: bool = True,
                     eps: float = DEFAULT_BLA_EPS):
    """A ``scan_fn(zr, zi, dre, dim) -> (counts, glitched)``-shaped
    callable for ``perturbation._compute_perturb``, with the BLA table
    built (and device-cached) from the HOST orbit arrays.  ``zr/zi``
    passed at call time must be the device copies of the same orbit."""
    tabs = _device_table(z_re, z_im, dc_max, eps, dtype)
    levels = tabs[0].shape[0]
    orbit_len = len(z_re)

    def scan_fn(zr, zi, dre, dim):
        counts, packed, skipped = _bla_scan_fetch(
            zr, zi, tabs, dre, dim, orbit_len=orbit_len,
            max_iter=max_iter, levels=levels, add_dc=add_dc)
        if logger.isEnabledFor(logging.DEBUG):  # one sync fetch/chunk
            logger.debug("BLA skipped %d of %d orbit steps on this chunk",
                         int(skipped), orbit_len)
        return counts, packed

    return scan_fn


@partial(jax.jit, static_argnames=("orbit_len", "max_iter", "levels",
                                   "add_dc"))
def _bla_scan_fetch(z_re, z_im, tabs, dc_re, dc_im, *, orbit_len: int,
                    max_iter: int, levels: int, add_dc: bool):
    """:func:`_bla_scan` shaped for the device->host fetch — same
    lossless trimming as perturbation._perturb_scan_fetch (uint16
    counts when the budget fits, bit-packed glitch mask), one jit so
    the trim costs no extra dispatch."""
    from distributedmandelbrot_tpu.ops.perturbation import _pack_mask
    counts, glitched, _, skipped = _bla_scan(
        z_re, z_im, tabs, dc_re, dc_im, orbit_len=orbit_len,
        max_iter=max_iter, levels=levels, add_dc=add_dc)
    if max_iter < (1 << 16):
        counts = counts.astype(jnp.uint16)
    return counts, _pack_mask(glitched), skipped


@partial(jax.jit, static_argnames=("orbit_len", "max_iter", "levels",
                                   "bailout", "add_dc"))
def _bla_scan_smooth(z_re, z_im, tabs, dc_re, dc_im, *, orbit_len: int,
                     max_iter: int, levels: int, bailout: float,
                     add_dc: bool = True):
    """Smooth twin of :func:`_bla_scan`: mirrors
    ``perturbation._perturb_scan_smooth``'s conventions (frozen full
    value at the first radius-``bailout`` crossing, radius-2 count for
    in-set classification) with tile-granular skips.

    The table must be built with ``z_cap <= bailout / 2`` (the factory
    uses ``min(4, bailout/2)`` — the 4.0 escape-segment guard is already
    tighter for every standard bailout): skips then never cross the
    smoothing radius, so every frozen value is produced by exact steps —
    the nu payload keeps exact-scan quality wherever a lane freezes.
    Escape/glitch timing carries the same boundary-detection contract
    as the integer scan.
    """
    dtype = jnp.result_type(dc_re)
    shape = dc_re.shape
    four = jnp.asarray(4.0, dtype)
    b2 = jnp.asarray(bailout * bailout, dtype)
    tol = jnp.asarray(GLITCH_TOL, dtype)
    R2 = tabs[4]
    z_re, z_im = _padded_orbit(z_re, z_im, dtype)

    def _burst_step(s, xs):
        dzr, dzi, act_b, nb, act2, n2, fzr, fzi, glitched = s
        zr, zi, i = xs
        valid = i < orbit_len
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (act2 & valid & (mag2 < tol * zmag2))
        newly = act_b & valid & (mag2 >= b2)
        fzr = jnp.where(newly, fr, fzr)
        fzi = jnp.where(newly, fi, fzi)
        act_b = act_b & ((mag2 < b2) | ~valid)
        nb = nb + act_b.astype(jnp.int32)
        act2 = act2 & ((mag2 < four) | ~valid)
        n2 = n2 + act2.astype(jnp.int32)
        ndzr = (zr + zr) * dzr - (zi + zi) * dzi + (dzr * dzr - dzi * dzi)
        ndzi = (zr + zr) * dzi + (zi + zi) * dzr + 2 * dzr * dzi
        if add_dc:
            ndzr = ndzr + dc_re
            ndzi = ndzi + dc_im
        ndzr = jnp.where(valid, ndzr, dzr)
        ndzi = jnp.where(valid, ndzi, dzi)
        return (ndzr, ndzi, act_b, nb, act2, n2, fzr, fzi, glitched), None

    def exact_burst(state):
        (n0, dzr, dzi, act_b, cnt_b, act2, cnt2, fzr, fzi, glitched,
         skipped) = state
        zseg_r = lax.dynamic_slice_in_dim(z_re, n0, BLA_EXACT_BURST)
        zseg_i = lax.dynamic_slice_in_dim(z_im, n0, BLA_EXACT_BURST)
        idx = n0 + jnp.arange(BLA_EXACT_BURST, dtype=jnp.int32)
        zeros_i = jnp.zeros(shape, jnp.int32)
        (dzr, dzi, nact_b, nb, nact2, n2, fzr, fzi, glitched), _ = \
            lax.scan(_burst_step,
                     (dzr, dzi, act_b, zeros_i, act2, zeros_i, fzr, fzi,
                      glitched),
                     (zseg_r, zseg_i, idx))
        cnt_b = jnp.where(act_b & ~nact_b, n0 + nb, cnt_b)
        cnt2 = jnp.where(act2 & ~nact2, n0 + n2, cnt2)
        return (n0 + BLA_EXACT_BURST, dzr, dzi, nact_b, cnt_b, nact2,
                cnt2, fzr, fzi, glitched, skipped)

    def body(state):
        (n, dzr, dzi, act_b, cnt_b, act2, cnt2, fzr, fzi, glitched,
         skipped) = state
        zr = z_re[n]
        zi = z_im[n]
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (act2 & (mag2 < tol * zmag2))
        newly = act_b & (mag2 >= b2)
        fzr = jnp.where(newly, fr, fzr)
        fzi = jnp.where(newly, fi, fzi)
        cnt_b = jnp.where(newly, n, cnt_b)
        act_b = act_b & ~newly
        out2 = act2 & (mag2 >= four)
        cnt2 = jnp.where(out2, n, cnt2)
        act2 = act2 & ~out2
        live = act_b | act2
        max_dz2 = jnp.max(jnp.where(live, dzr * dzr + dzi * dzi,
                                    jnp.zeros((), dtype)))
        l_sel = _select_skip(n, max_dz2, R2, levels, orbit_len)

        def apply_skip(s):
            (n, dzr, dzi, act_b, cnt_b, act2, cnt2, fzr, fzi, glitched,
             skipped) = s
            n2_, bla_r, bla_i = _apply_skip_map(l_sel, n, tabs, dzr, dzi,
                                                dc_re, dc_im, add_dc)
            return (n2_, bla_r, bla_i, act_b, cnt_b, act2, cnt2, fzr,
                    fzi, glitched, skipped + (n2_ - n))

        return lax.cond(l_sel > 0, apply_skip, exact_burst, state)

    def cond(state):
        n, _, _, act_b, _, act2 = state[:6]
        return (n < orbit_len) & jnp.any(act_b | act2)

    ones = jnp.ones(shape, jnp.bool_)
    sent = jnp.full(shape, orbit_len, jnp.int32)
    init = (jnp.asarray(0, jnp.int32), dc_re.astype(dtype),
            dc_im.astype(dtype), ones, sent, ones, sent,
            jnp.full(shape, bailout, dtype), jnp.zeros(shape, dtype),
            jnp.zeros(shape, jnp.bool_), jnp.asarray(0, jnp.int32))
    (n, dzr, dzi, act_b, cnt_b, act2, cnt2, fzr, fzi, glitched,
     skipped) = lax.while_loop(cond, body, init)
    if orbit_len < max_iter:
        glitched = glitched | act2
    # Identical epilogue to _perturb_scan_smooth, with the positional
    # counts standing in for the accumulated ones.
    mag2 = jnp.maximum(fzr * fzr + fzi * fzi, b2)
    log_ratio = jnp.log(mag2) / jnp.asarray(2.0 * np.log(bailout), dtype)
    nu = (cnt_b + 1).astype(dtype) - jnp.log2(log_ratio)
    nu = jnp.where(cnt2 >= max_iter, jnp.zeros((), dtype), nu)
    return nu, glitched, skipped


# Smooth-path skip guard: only orbit segments with |Z| below this may be
# skipped.  Measured on hardware (2026-07-31, 256^2): at the integer
# path's 4.0 cap the smooth plane differed from the exact scan on 17.7%
# of the config-4 boundary view's pixels (p99 |dnu| 0.005 but MAX 72
# bands — visible dots in animations); at 1.0 the two are bit-identical
# there at unchanged throughput, while the bond-point showcase keeps
# 11.5x (vs 12.4x) bit-identical.  0.5 forfeits the bond speedup
# (0.7x).  Mid-magnitude segments (1 <= |Z| < 4) amplify the dropped
# quadratic term right where smooth values are most visible, so the
# smooth path trades those segments' skips for exactness; the integer
# path keeps 4.0 under its documented approximate contract.
SMOOTH_Z_CAP = 1.0


def bla_smooth_scan_factory(z_re: np.ndarray, z_im: np.ndarray,
                            dc_max: float, *, max_iter: int, bailout: float,
                            dtype, add_dc: bool = True,
                            eps: float = DEFAULT_BLA_EPS):
    """Smooth counterpart of :func:`bla_scan_factory` — returns a
    ``scan_fn(zr, zi, dre, dim) -> (nu, glitched)``.  The table's
    ``z_cap`` guard (min of :data:`SMOOTH_Z_CAP` and bailout/2) keeps
    every freeze inside exact steps and every skip away from the
    mid-magnitude segments that bend smooth values."""
    tabs = _device_table(z_re, z_im, dc_max, eps, dtype,
                         z_cap=min(SMOOTH_Z_CAP, bailout / 2.0))
    levels = tabs[0].shape[0]
    orbit_len = len(z_re)

    def scan_fn(zr, zi, dre, dim):
        nu, packed, skipped = _bla_scan_smooth_fetch(
            zr, zi, tabs, dre, dim, orbit_len=orbit_len,
            max_iter=max_iter, levels=levels, bailout=float(bailout),
            add_dc=add_dc)
        if logger.isEnabledFor(logging.DEBUG):  # one sync fetch/chunk
            logger.debug("BLA skipped %d of %d orbit steps on this chunk",
                         int(skipped), orbit_len)
        return nu, packed

    return scan_fn


@partial(jax.jit, static_argnames=("orbit_len", "max_iter", "levels",
                                   "bailout", "add_dc"))
def _bla_scan_smooth_fetch(z_re, z_im, tabs, dc_re, dc_im, *,
                           orbit_len: int, max_iter: int, levels: int,
                           bailout: float, add_dc: bool):
    """Smooth twin of :func:`_bla_scan_fetch` (nu stays f32; only the
    glitch mask packs)."""
    from distributedmandelbrot_tpu.ops.perturbation import _pack_mask
    nu, glitched, skipped = _bla_scan_smooth(
        z_re, z_im, tabs, dc_re, dc_im, orbit_len=orbit_len,
        max_iter=max_iter, levels=levels, bailout=bailout, add_dc=add_dc)
    return nu, _pack_mask(glitched), skipped
