"""Extended escape-time families: Multibrot (z^d + c) and Burning Ship.

Capability extensions past the reference (which renders only the degree-2
Mandelbrot set, ``DistributedMandelbrotWorkerCUDA.py:39-68``) that fall out
of the TPU-first kernel architecture: the segmented select-free loop, the
tile-granular early exit, and the Brent cycle probe
(:mod:`distributedmandelbrot_tpu.ops.escape_time`) are all recurrence-
agnostic, so a new family only supplies its one-step map:

- **Multibrot** degree ``d``: ``z <- z^d + c`` (complex power by ``d-1``
  repeated multiplications — exact formula sharing with the golden).
- **Burning Ship**: ``z <- (|Re z| + i|Im z|)^2 + c``.

Count semantics mirror :func:`escape_time.escape_counts`: ``z`` starts at
``c``, iterations count 1..max_iter-1, bailout ``|z|^2 >= 4`` tested after
the update, 0 = never escaped.  (Radius 2 remains a valid escape bound for
every degree >= 2: once ``|z| > 2`` and ``|c| <= |z|``,
``|z^d + c| >= |z|^d - |c| >= |z|(|z|^{d-1} - 1) > |z|``.)

In-set shortcuts: the Multibrot gets the exact inscribed disk of its
period-1 component (:func:`escape_time.multibrot_interior_radius`; the
full cardioid+bulb closed forms at degree 2), the Burning Ship has no
known interior form, and the Brent cycle probe covers what the closed
forms miss on both (same policy: on at budgets >=
:data:`escape_time.CYCLE_CHECK_MIN_ITER`).  Goldens live beside the
other pins in :mod:`distributedmandelbrot_tpu.ops.reference`.

Parity note: the select-free protocol is exact (a pure-numpy mirror of
this loop matches the frozen golden bit-for-bit), but XLA's FMA
contraction shifts trajectories at the last ulp as in the core kernels —
and the Burning Ship's |.| folds amplify that (an orbit landing a ulp
across a fold diverges outright), so its statistical validation band is
wider (~1-2% of pixels at depth 300 vs ~0.02% for smooth maps).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.ops.escape_time import (
    DEFAULT_SEGMENT, _escape_smooth_jit, escape_loop_generic, family_interior,
    family_step, resolve_cycle_check, scale_counts_to_uint8)
from distributedmandelbrot_tpu.utils.precision import ensure_x64

__all__ = ["family_step", "escape_counts_family", "escape_smooth_family",
           "compute_tile_family", "compute_tile_smooth_family"]


def _check_family(power: int, burning: bool) -> None:
    if power < 2:
        raise ValueError(f"multibrot degree must be >= 2, got {power}")
    if burning and power != 2:
        raise ValueError("burning ship is degree 2 only")


@partial(jax.jit, static_argnames=("max_iter", "segment", "power", "burning",
                                   "cycle_check"))
def _family_counts_jit(c_real, c_imag, *, max_iter: int, segment: int,
                       power: int, burning: bool,
                       cycle_check: bool) -> jax.Array:
    dtype = jnp.result_type(c_real)
    c_real = c_real.astype(dtype)
    c_imag = c_imag.astype(dtype)
    total_steps = max_iter - 1
    if total_steps <= 0:
        return jnp.zeros(c_real.shape, jnp.int32)
    step = partial(family_step, c_real=c_real, c_imag=c_imag, power=power,
                   burning=burning)
    # Exact interior shortcut where a closed form exists (single-sourced
    # policy: escape_time.family_interior — cardioid+bulb at degree 2,
    # the inscribed period-1 disk above, None for the ship).
    return escape_loop_generic(step, c_real, c_imag,
                               total_steps=total_steps, segment=segment,
                               cycle_check=cycle_check,
                               interior=family_interior(c_real, c_imag,
                                                        power, burning))


def escape_counts_family(c_real: jax.Array, c_imag: jax.Array, *,
                         max_iter: int, power: int = 2,
                         burning: bool = False,
                         segment: int = DEFAULT_SEGMENT,
                         cycle_check: bool | None = None) -> jax.Array:
    """Escape counts for the Multibrot / Burning Ship families."""
    _check_family(power, burning)
    dt = getattr(c_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    return _family_counts_jit(c_real, c_imag, max_iter=max_iter,
                              segment=segment, power=power, burning=burning,
                              cycle_check=resolve_cycle_check(cycle_check,
                                                              max_iter))


def escape_smooth_family(c_real: jax.Array, c_imag: jax.Array, *,
                         max_iter: int, power: int = 2,
                         burning: bool = False,
                         segment: int = DEFAULT_SEGMENT,
                         bailout: float = 256.0,
                         cycle_check: bool | None = None) -> jax.Array:
    """Smooth (band-free) values for the extended families: the shared
    smooth kernel (escape_time._escape_smooth_jit) with the family's
    recurrence and degree-``power`` renormalization; 0 = in-set.  The
    closed-form interior shortcut does not apply; the cycle probe does."""
    _check_family(power, burning)
    dt = getattr(c_real, "dtype", None)
    if dt is not None and np.dtype(dt) == np.float64:
        ensure_x64()
    # interior_check on: the smooth kernel routes through the same
    # family_interior policy (cardioid+bulb / inscribed disk / None).
    return _escape_smooth_jit(c_real, c_imag, c_real, c_imag,
                              max_iter=max_iter, segment=segment,
                              bailout=float(bailout), interior_check=True,
                              cycle_check=resolve_cycle_check(cycle_check,
                                                              max_iter),
                              power=power, burning=burning)


def compute_tile_smooth_family(spec: TileSpec, max_iter: int, *,
                               power: int = 2, burning: bool = False,
                               dtype: np.dtype = np.float64,
                               segment: int = DEFAULT_SEGMENT,
                               bailout: float = 256.0) -> np.ndarray:
    """One smooth Multibrot/Burning-Ship tile -> 2-D float array."""
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    g_real, g_imag = spec.grid_2d()
    nu = escape_smooth_family(jnp.asarray(g_real, dtype=dtype),
                              jnp.asarray(g_imag, dtype=dtype),
                              max_iter=max_iter, power=power,
                              burning=burning, segment=segment,
                              bailout=bailout)
    return np.asarray(nu)


def compute_tile_family(spec: TileSpec, max_iter: int, *, power: int = 2,
                        burning: bool = False,
                        dtype: np.dtype = np.float32,
                        segment: int = DEFAULT_SEGMENT,
                        clamp: bool = False) -> np.ndarray:
    """One Multibrot/Burning-Ship tile end-to-end -> flat uint8 pixels."""
    if np.dtype(dtype) == np.float64:
        ensure_x64()
    c_real, c_imag = spec.grid_2d()
    counts = escape_counts_family(jnp.asarray(c_real, dtype=dtype),
                                  jnp.asarray(c_imag, dtype=dtype),
                                  max_iter=max_iter, power=power,
                                  burning=burning, segment=segment)
    return np.asarray(scale_counts_to_uint8(counts, max_iter=max_iter,
                                            clamp=clamp)).ravel()
