"""Live-lane compaction for the Pallas escape kernel.

The round-3 hardware audit measured the escape loop at ~95 Giter/s in
small or mixed early-exit calls but 225-250 Giter/s in big uniformly
deep calls, and recorded two negative results (depth-sorting a mixed
call's program order, probe-stride tuning) that localize the gap to the
*shape of the work*, not its schedule: on boundary views the block-
granular early exit leaves each surviving block running a full
(block_h, block_w) vector for a handful of live lanes — measured 6.9x
the ideal per-pixel iteration work on the worst-case filament view.

This module implements the structural fix, in two phases — and on the
current bench stack it is a MEASURED NEGATIVE, shipped opt-in only: the
resume kernel hits 520 Giter/s (2.3x the plain kernel's best big-call
rate, chained-delta timing), but XLA:TPU's element-granular lowering of
the compaction glue (gather/scatter/sort at 0.6-2.7 GB/s) costs more
than the compute it saves.  See the ``_COMPACT_OPTED_IN`` note and
ROUND4_NOTES.md "Live-lane compaction" for the full measurement table.
The design:

1. **Phase 1** (``_state_batch_kernel``): the normal block kernel, capped
   at ``phase_budget`` iterations (the shallow majority of a mixed view
   escapes here), which instead of the uint8 plane emits the raw
   per-pixel machine state — ``(c, z, n, act)``.
2. **Compaction + resume rounds**: surviving lanes from ALL blocks and
   tiles of the batch are gathered into one dense buffer (XLA cumsum +
   gather — no host sync, shapes static), and ``_resume_block_kernel``
   continues them in ``seg_steps``-iteration rounds, re-compacting
   between rounds so the buffer's live prefix shrinks as stragglers
   retire.  Every block of every round is fully live — exactly the
   uniform-deep big-call regime the audit measured at 225-250 Giter/s —
   and the executed iteration count approaches the per-pixel ideal the
   CUDA reference gets from per-pixel early return
   (``DistributedMandelbrotWorkerCUDA.py:62-67``).

**Bit-identity argument** (tested, not just argued): phase 1, the resume
rounds, and the plain kernel share ONE loop body
(``pallas_escape._run_seg_loop``) whose segment boundaries land on
``1 + k*unroll`` regardless of which call executes them — ``phase_budget``
and ``seg_steps`` are unroll-aligned, so a resumed lane executes the
identical arithmetic sequence, and the final count classification
(``n >= budget -> 0``) is insensitive to the segment-granular overshoot
and retirement the split introduces (an unescaped lane's count is
already past its budget; an escaping lane's mask froze at the exact
step).  The uint8 scaling is the same integer expression, applied
per-lane at the end.

**Static shapes, no host sync**: the compact buffer's capacity is a
static fraction of the batch (``COMPACT_CAPACITY_FRAC``).  If a view
leaves more survivors than that — deep near-uniform views, which are
exactly the ones already in the fast big-call regime — the overflow
lanes resume IN PLACE over the original grid under a ``lax.cond`` that
costs nothing when it doesn't fire.  Output is correct in both regimes;
the capacity only bounds how much gets accelerated.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributedmandelbrot_tpu.ops.escape_time import resolve_cycle_check
from distributedmandelbrot_tpu.ops.pallas_escape import (
    DEFAULT_BLOCK_H, DEFAULT_BLOCK_W, DEFAULT_UNROLL, PallasUnsupported,
    _interior_init, _load_block_coords, _pallas, _run_seg_loop, fit_blocks)

# Phase-1 budget: how deep the full grid runs before survivors compact.
# From the measured escape-depth distributions (ROUND4_NOTES.md): at 256
# iterations the filament worst-case view retains 4.7% of lanes, the
# hard seahorse-head view 17.9% — past the knee of the depth CDF, while
# costing only ~13% of a 2000-budget view's ideal work.  Must be a
# multiple of the kernel unroll (segment alignment, see module doc).
PHASE1_BUDGET = 256

# Resume-round length.  Shorter rounds re-compact more often (tighter
# straggler control) but pay the per-round XLA glue more often; 256
# matches the phase-1 knee spacing of the measured CDFs.
RESUME_SEG = 256

# Compact-buffer capacity as a fraction of the batch's pixels, aligned
# up to a whole (32, 128) block grid.  1/4 covers every measured
# boundary view's survivor fraction at PHASE1_BUDGET with 40% headroom;
# overflowing views resume in place (see module doc).
COMPACT_CAPACITY_FRAC = 4  # denominator

_LANE = 128          # compact buffer row width (f32 vreg lane count)
_RESUME_BLOCK_H = 32 # compact buffer block rows (VMEM-friendly, divides
                     # every capacity because capacity aligns to 4096)


def _state_batch_kernel(params_ref, mrd_ref, cr_ref, ci_ref, zr_out, zi_out,
                        n_out, act_out, zr_s, zi_s, act_s, n_s, *,
                        phase_budget: int, unroll: int, block_h: int,
                        block_w: int, interior_check: bool, julia: bool,
                        power: int, burning: bool):
    """Phase 1: the batch-grid escape kernel, capped at ``phase_budget``
    iterations, emitting raw state planes instead of uint8.

    The c planes are emitted from the kernel's OWN grid values (not
    regenerated on the XLA side) so the resume arithmetic consumes
    bit-identical coordinates by construction.  ``act`` is zeroed for
    tiles whose entire budget fits in phase 1 — they are complete, and
    their unescaped lanes already hold ``n >= budget``."""
    pl, _ = _pallas()
    t, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    shape = zr_s.shape
    g_real, g_imag, c_real, c_imag, mrd = _load_block_coords(
        params_ref, mrd_ref, t, i, j, shape, block_h, block_w, julia)
    dyn_steps = mrd - 1

    zr_s[:] = g_real
    zi_s[:] = g_imag
    act0, n_sat, live0 = _interior_init(
        c_real, c_imag, dyn_steps, shape, interior_check and not julia,
        power=power, burning=burning)
    act_s[:] = act0
    n_s[:] = n_sat

    _run_seg_loop(zr_s, zi_s, act_s, n_s, (), c_real, c_imag, live0,
                  cond_cap=jnp.minimum(dyn_steps, phase_budget),
                  sat_steps=dyn_steps, unroll=unroll, cycle_check=False,
                  power=power, burning=burning)

    cr_ref[0] = c_real
    ci_ref[0] = c_imag
    zr_out[0] = zr_s[:]
    zi_out[0] = zi_s[:]
    n_out[0] = n_s[:]
    # Tiles completed inside phase 1 contribute no survivors.
    act_out[0] = act_s[:] * (dyn_steps > phase_budget).astype(jnp.int32)


def _pallas_escape_state(params, mrds, *, k: int, height: int, width: int,
                         phase_budget: int, unroll: int, block_h: int,
                         block_w: int, interior_check: bool, julia: bool,
                         power: int, burning: bool, interpret: bool):
    """Dispatch phase 1 over a k-tile batch -> six (k, H, W) state planes
    ``(c_re, c_im, z_re, z_im, n, act)``."""
    pl, pltpu = _pallas()
    kernel = partial(_state_batch_kernel, phase_budget=phase_budget,
                     unroll=unroll, block_h=block_h, block_w=block_w,
                     interior_check=interior_check, julia=julia,
                     power=power, burning=burning)
    f32 = jnp.float32
    i32 = jnp.int32
    out_block = pl.BlockSpec((1, block_h, block_w), lambda t, i, j: (t, i, j))
    return pl.pallas_call(
        kernel,
        grid=(k, height // block_h, width // block_w),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[out_block] * 6,
        out_shape=[jax.ShapeDtypeStruct((k, height, width), f32)] * 4
        + [jax.ShapeDtypeStruct((k, height, width), i32)] * 2,
        scratch_shapes=[pltpu.VMEM((block_h, block_w), f32),
                        pltpu.VMEM((block_h, block_w), f32),
                        pltpu.VMEM((block_h, block_w), i32),
                        pltpu.VMEM((block_h, block_w), i32)],
        interpret=interpret,
    )(params, mrds)


def _resume_block_kernel(it0_ref, dyn_ref, cr_ref, ci_ref, zr_in, zi_in,
                         n_in, act_in, zr_out, zi_out, n_out, act_out,
                         zr_s, zi_s, act_s, n_s, *, seg_steps: int,
                         unroll: int, power: int, burning: bool):
    """One resume round over one block of lane-state planes: continue the
    shared loop body from iteration ``it0`` for at most ``seg_steps``
    more iterations (both unroll-aligned).  Geometry-free — lanes carry
    their own ``c`` and per-lane budget, so one kernel serves the dense
    compact buffer, mixed-budget batches, and the overflow in-place
    resume."""
    it0 = it0_ref[0, 0]
    act0 = act_in[...]
    zr_s[:] = zr_in[...]
    zi_s[:] = zi_in[...]
    act_s[:] = act0
    n_s[:] = n_in[...]
    c_real = cr_ref[...]
    c_imag = ci_ref[...]

    _run_seg_loop(zr_s, zi_s, act_s, n_s, (), c_real, c_imag,
                  jnp.sum(act0, dtype=jnp.int32),
                  cond_cap=it0 + (seg_steps - 1), sat_steps=it0,
                  unroll=unroll, cycle_check=False, power=power,
                  burning=burning, it0=it0, dyn_ref=dyn_ref)

    zr_out[...] = zr_s[:]
    zi_out[...] = zi_s[:]
    n_out[...] = n_s[:]
    act_out[...] = act_s[:]


def _pallas_resume(it0, dyn, cr, ci, zr, zi, n, act, *, seg_steps: int,
                   unroll: int, block_h: int, power: int, burning: bool,
                   interpret: bool):
    """One resume round over (R, 128) lane-state planes -> updated
    ``(z_re, z_im, n, act)``."""
    pl, pltpu = _pallas()
    rows, width = zr.shape
    kernel = partial(_resume_block_kernel, seg_steps=seg_steps,
                     unroll=unroll, power=power, burning=burning)
    f32 = jnp.float32
    i32 = jnp.int32
    block = pl.BlockSpec((block_h, width), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=(rows // block_h,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)] + [block] * 7,
        out_specs=[block] * 4,
        out_shape=[jax.ShapeDtypeStruct((rows, width), f32)] * 2
        + [jax.ShapeDtypeStruct((rows, width), i32)] * 2,
        scratch_shapes=[pltpu.VMEM((block_h, width), f32),
                        pltpu.VMEM((block_h, width), f32),
                        pltpu.VMEM((block_h, width), i32),
                        pltpu.VMEM((block_h, width), i32)],
        interpret=interpret,
    )(it0, dyn, cr, ci, zr, zi, n, act)


def _gather_lanes(valid, take, fills, *arrays):
    """Gather ``arrays`` at lane indices ``take`` where ``valid``, else
    the per-array fill — the one copy of the compact/re-compact gather."""
    return [jnp.where(valid, a.reshape(-1)[take], f)
            for a, f in zip(arrays, fills)]


@partial(jax.jit, static_argnames=(
    "k", "height", "width", "max_iter", "cap_lanes", "phase_budget",
    "seg_steps", "block_h", "block_w", "unroll", "clamp", "interior_check",
    "julia", "power", "burning", "interpret"))
def _compact_escape(params, mrds, *, k: int, height: int, width: int,
                    max_iter: int, cap_lanes: int, phase_budget: int,
                    seg_steps: int, block_h: int, block_w: int, unroll: int,
                    clamp: bool, interior_check: bool, julia: bool,
                    power: int, burning: bool, interpret: bool):
    """The full compacted pipeline: phase 1 -> gather survivors -> resume
    rounds with re-compaction -> scatter back -> uint8 scaling.  One jit,
    no host syncs; see the module doc for the design and the identity
    argument."""
    total = max_iter - 1
    N = k * height * width
    C = cap_lanes
    i32 = jnp.int32

    cr, ci, zr, zi, n, act = _pallas_escape_state(
        params, mrds, k=k, height=height, width=width,
        phase_budget=phase_budget, unroll=unroll, block_h=block_h,
        block_w=block_w, interior_check=interior_check, julia=julia,
        power=power, burning=burning, interpret=interpret)

    dyn_lane = jnp.broadcast_to((mrds[:, 0] - 1)[:, None, None],
                                (k, height, width)).reshape(N)
    act_f = act.reshape(N)
    n_f = n.reshape(N)
    live = act_f != 0
    pos = jnp.cumsum(live.astype(i32)) - 1
    keep = live & (pos < C)
    kept_ct = jnp.sum(keep, dtype=i32)

    idx = jnp.nonzero(keep, size=C, fill_value=N)[0].astype(i32)
    valid = jnp.arange(C, dtype=i32) < kept_ct
    take = jnp.minimum(idx, N - 1)
    czr, czi, ccr, cci = _gather_lanes(
        valid, take, (0.0, 0.0, 0.0, 0.0),
        zr.reshape(N), zi.reshape(N), cr.reshape(N), ci.reshape(N))
    cn, cact, cdyn = _gather_lanes(valid, take, (0, 0, 0),
                                   n_f, act_f, dyn_lane)
    orig = jnp.where(valid, idx, N)

    it0 = jnp.asarray(phase_budget + 1, i32)
    shape2 = (C // _LANE, _LANE)
    seg = jnp.asarray(seg_steps, i32)

    def round_cond(carry):
        it0, live_ct = carry[0], carry[1]
        return (live_ct > 0) & (it0 <= total)

    def round_body(carry):
        (it0, _, czr, czi, cn, cact, ccr, cci, cdyn, orig, n_out) = carry
        zr2, zi2, n2, act2 = _pallas_resume(
            it0.reshape(1, 1), cdyn.reshape(shape2), ccr.reshape(shape2),
            cci.reshape(shape2), czr.reshape(shape2), czi.reshape(shape2),
            cn.reshape(shape2), cact.reshape(shape2), seg_steps=seg_steps,
            unroll=unroll, block_h=_RESUME_BLOCK_H, power=power,
            burning=burning, interpret=interpret)
        # Every lane's count lands in the output each round (scatter by
        # original pixel index, OOB-dropped padding): lanes that retired
        # this round are final; lanes still live get overwritten by a
        # later round's scatter.
        n_out = n_out.at[orig].set(n2.reshape(C), mode="drop")
        # Re-compact: live lanes to the buffer front, so straggler-free
        # tail blocks of later rounds exit before their first segment.
        lv = act2.reshape(C) != 0
        cnt = jnp.sum(lv, dtype=i32)
        idx2 = jnp.nonzero(lv, size=C, fill_value=C)[0].astype(i32)
        val2 = jnp.arange(C, dtype=i32) < cnt
        take2 = jnp.minimum(idx2, C - 1)
        czr, czi, ccr2, cci2 = _gather_lanes(val2, take2,
                                             (0.0, 0.0, 0.0, 0.0),
                                             zr2, zi2, ccr, cci)
        cn, cdyn2 = _gather_lanes(val2, take2, (0, 0), n2, cdyn)
        # Live lanes are exactly the valid prefix — no gather needed
        # (dtype pinned: a weak-typed where would widen under x64 and
        # break the while carry's type invariance).
        cact = val2.astype(i32)
        orig = jnp.where(val2, orig[take2], N)
        return (it0 + seg, cnt, czr, czi, cn, cact, ccr2, cci2, cdyn2,
                orig, n_out)

    carry = (it0, kept_ct, czr, czi, cn, cact, ccr, cci, cdyn, orig, n_f)
    n_f = lax.while_loop(round_cond, round_body, carry)[-1]

    # Overflow: survivors past capacity resume IN PLACE over the original
    # layout (their own act plane, everything else dead) — the original
    # grid's block structure is exactly the fast regime for the
    # near-uniform deep views that overflow.  The cond skips the whole
    # branch (compile-time shapes equal) when nothing overflowed.
    overflow = jnp.sum(live, dtype=i32) - kept_ct
    act_resid = (live & (pos >= C)).astype(i32)

    def in_place_resume(n_base):
        rows = N // _LANE
        bh = _RESUME_BLOCK_H if rows % _RESUME_BLOCK_H == 0 else 8
        shp = (rows, _LANE)
        dyn_p = dyn_lane.reshape(shp)
        cr_p = cr.reshape(shp)
        ci_p = ci.reshape(shp)

        def cond(carry):
            it0r, live_ct = carry[0], carry[1]
            return (live_ct > 0) & (it0r <= total)

        def body(carry):
            it0r, _, zr_p, zi_p, n_p, act_p = carry
            zr2, zi2, n2, act2 = _pallas_resume(
                it0r.reshape(1, 1), dyn_p, cr_p, ci_p, zr_p, zi_p, n_p,
                act_p, seg_steps=seg_steps, unroll=unroll, block_h=bh,
                power=power, burning=burning, interpret=interpret)
            return (it0r + seg, jnp.sum(act2, dtype=i32), zr2, zi2, n2,
                    act2)

        out = lax.while_loop(cond, body,
                             (it0, overflow, zr.reshape(shp),
                              zi.reshape(shp), n_base.reshape(shp),
                              act_resid.reshape(shp)))
        return out[4].reshape(N)

    n_f = lax.cond(overflow > 0, in_place_resume, lambda nb: nb, n_f)

    # Per-lane uint8 scaling — the same integer expression as the plain
    # kernel's epilogue, applied after reassembly.
    counts = jnp.where(n_f >= dyn_lane, 0, n_f + 1)
    mrd_lane = dyn_lane + 1
    vals = (counts * 256 + (mrd_lane - 1)) // mrd_lane
    if clamp:
        vals = jnp.minimum(vals, 255)
    return vals.astype(jnp.uint8).reshape(k, height, width)


def compact_capacity(n_pixels: int) -> int:
    """Static compact-buffer capacity for a batch: ``n_pixels / 4``
    aligned up to a whole (32, 128) block grid."""
    granule = _RESUME_BLOCK_H * _LANE
    want = max(granule, n_pixels // COMPACT_CAPACITY_FRAC)
    return -(-want // granule) * granule


# Opt-in gate for the compacted dispatch.  MEASURED NEGATIVE on the
# current bench stack (2026-07-31, v5 lite via the axon tunnel): the
# resume kernel itself runs 520 Giter/s — 2.3x the plain kernel's best
# big-call rate, exactly the win the round-3 audit predicted — but
# XLA:TPU lowers the per-lane compaction glue to element-granular data
# movement (chained-delta measured: gather 4M-of-16M 29 ms, scatter 24
# ms, 16M sort 50 ms = 0.6-2.7 GB/s), which exceeds the ENTIRE device
# compute of the views it would accelerate (filament 16x1024^2: 16 ms).
# Patch-granular glue (8x128 DMA-able blocks) is affordable but removes
# only 1.3x of iteration work (straggler waste lives inside patches).
# Full numbers: ROUND4_NOTES.md "Live-lane compaction".  On a stack
# with healthy gather bandwidth, set DMTPU_COMPACT=1 to enable.
#
# Round 5: the ASSEMBLED pipeline finally ran on real silicon
# (tools/hw_compact.py -> COMPACT_HW_r05.json): byte-identical to the
# plain kernel on both the uniform and mixed-budget batches — the
# identity claim is now hardware-pinned — and the perf negative is
# confirmed emphatically (filament 16x1024^2 mi=2000: 5.5 device Mpix/s
# compacted vs 890 plain; the glue dominates end-to-end).  The opt-in
# stays exactly that: an escape hatch whose enablement path is tested,
# with hardware evidence that THIS stack should leave it off.
_COMPACT_OPTED_IN = bool(int(__import__("os").environ.get(
    "DMTPU_COMPACT", "0") or "0"))


def prefer_compaction(budget: int, n_pixels: int) -> bool:
    """Dispatch policy: opt-in only (see the measured-negative note on
    ``_COMPACT_OPTED_IN``), and then only when the budget is deep enough
    that phase 1 strands a straggler tail (>= 2x the phase-1 budget) but
    below the cycle-probe class, which the resume kernel does not carry
    (deep in-set-heavy views keep the probe's guarantees instead), and
    the batch has enough pixels to fill dense resume blocks."""
    from distributedmandelbrot_tpu.ops.escape_time import (
        CYCLE_CHECK_MIN_ITER)
    return (_COMPACT_OPTED_IN
            and 2 * PHASE1_BUDGET <= budget - 1
            and budget < CYCLE_CHECK_MIN_ITER
            and n_pixels >= 64 * _RESUME_BLOCK_H * _LANE)


def compact_escape_batch(params, mrds, *, k: int, height: int, width: int,
                         max_iter: int, unroll: int = DEFAULT_UNROLL,
                         block_h: int = DEFAULT_BLOCK_H,
                         block_w: int = DEFAULT_BLOCK_W,
                         clamp: bool = False, interior_check: bool = True,
                         cycle_check: bool | None = None,
                         julia: bool = False, power: int = 2,
                         burning: bool = False, interpret: bool = False,
                         phase_budget: int = PHASE1_BUDGET,
                         seg_steps: int = RESUME_SEG):
    """k tiles -> (k, height, width) uint8 via the compacted two-phase
    pipeline; bit-identical to ``_pallas_escape_batch`` (tested across
    the view/feature matrix in tests/test_compact.py).

    Callers should gate on :func:`prefer_compaction`; this wrapper
    enforces the structural requirements (cycle probe unsupported,
    budget deeper than phase 1, unroll-aligned phases)."""
    if resolve_cycle_check(cycle_check, max_iter):
        raise PallasUnsupported(
            "compacted dispatch does not carry the cycle probe; use the "
            "plain kernel for probe-class budgets")
    if max_iter - 1 <= phase_budget:
        raise PallasUnsupported(
            f"budget {max_iter} completes inside phase 1 ({phase_budget}); "
            "use the plain kernel")
    if phase_budget % unroll or seg_steps % unroll:
        raise PallasUnsupported(
            f"phase budget {phase_budget} / segment {seg_steps} must be "
            f"unroll-aligned ({unroll}) for resume bit-identity")
    if width % _LANE:
        raise PallasUnsupported(
            f"width {width} not a multiple of {_LANE}")
    if height % block_h or width % block_w:
        # Same silent-partial-grid hazard fit_blocks guards for the
        # plain kernels: a non-divisible extent would compute only
        # extent // block blocks and leave the rest garbage.
        raise PallasUnsupported(
            f"extents ({height}, {width}) not divisible by the "
            f"({block_h}, {block_w}) block")
    return _compact_escape(
        params, mrds, k=k, height=height, width=width, max_iter=max_iter,
        cap_lanes=compact_capacity(k * height * width),
        phase_budget=phase_budget, seg_steps=seg_steps, block_h=block_h,
        block_w=block_w, unroll=unroll, clamp=clamp,
        interior_check=interior_check, julia=julia, power=power,
        burning=burning, interpret=interpret)
