"""Pure-numpy golden reference for the escape-time computation.

This is the semantic pin for every accelerated kernel in the framework.  It
reproduces the reference worker's per-pixel loop
(``DistributedMandelbrotWorkerCUDA.py:39-68``) exactly, element-wise over
float64:

- ``z`` starts at ``c`` (not 0)
- iterations count from 1 to ``max_iter - 1`` inclusive
- each iteration computes ``z <- z*z + c`` (square first, then add), then
  tests ``|z|^2 >= 4`` and records the iteration number on escape
- a pixel that never escapes yields 0.

The vectorized form freezes escaped pixels (no further updates), which is
IEEE-identical to the reference's per-pixel early return: active pixels see
the same operations in the same order.
"""

from __future__ import annotations

import numpy as np


def escape_counts(c_real: np.ndarray, c_imag: np.ndarray,
                  max_iter: int) -> np.ndarray:
    """Escape iteration (int32) per pixel; 0 if never escaped within max_iter."""
    c_real = np.asarray(c_real, dtype=np.float64)
    c_imag = np.asarray(c_imag, dtype=np.float64)
    zr = c_real.copy()
    zi = c_imag.copy()
    counts = np.zeros(c_real.shape, dtype=np.int32)
    active = np.ones(c_real.shape, dtype=bool)
    for it in range(1, max_iter):
        new_zr = zr * zr - zi * zi + c_real
        new_zi = 2.0 * zr * zi + c_imag
        zr = np.where(active, new_zr, zr)
        zi = np.where(active, new_zi, zi)
        escaped = active & (zr * zr + zi * zi >= 4.0)
        counts = np.where(escaped, np.int32(it), counts)
        active &= ~escaped
        if not active.any():
            break
    return counts


def escape_counts_julia(z_real: np.ndarray, z_imag: np.ndarray, c: complex,
                        max_iter: int) -> np.ndarray:
    """Julia-family golden: z starts at the pixel, ``c`` is constant.

    Same loop protocol as :func:`escape_counts` (iterations 1..max_iter-1,
    test after update, 0 = never escaped); pins the semantics of the JAX
    Julia kernel (a capability extension — the reference renders only the
    Mandelbrot set).
    """
    zr = np.asarray(z_real, dtype=np.float64).copy()
    zi = np.asarray(z_imag, dtype=np.float64).copy()
    cr, ci = np.float64(c.real), np.float64(c.imag)
    counts = np.zeros(zr.shape, dtype=np.int32)
    active = np.ones(zr.shape, dtype=bool)
    for it in range(1, max_iter):
        new_zr = zr * zr - zi * zi + cr
        new_zi = 2.0 * zr * zi + ci
        zr = np.where(active, new_zr, zr)
        zi = np.where(active, new_zi, zi)
        escaped = active & (zr * zr + zi * zi >= 4.0)
        counts = np.where(escaped, np.int32(it), counts)
        active &= ~escaped
        if not active.any():
            break
    return counts


def escape_counts_family(c_real: np.ndarray, c_imag: np.ndarray,
                         max_iter: int, *, power: int = 2,
                         burning: bool = False) -> np.ndarray:
    """Multibrot / Burning Ship golden (capability extension; pins
    ops.families).  Same loop protocol as :func:`escape_counts`; the
    recurrence mirrors ``families.family_step``'s formula and operation
    order exactly (complex power by repeated multiplication; Burning Ship
    takes |Re z|, |Im z| before squaring)."""
    zr = np.asarray(c_real, dtype=np.float64).copy()
    zi = np.asarray(c_imag, dtype=np.float64).copy()
    c_real = np.asarray(c_real, dtype=np.float64)
    c_imag = np.asarray(c_imag, dtype=np.float64)
    counts = np.zeros(zr.shape, dtype=np.int32)
    active = np.ones(zr.shape, dtype=bool)
    for it in range(1, max_iter):
        azr = np.abs(zr) if burning else zr
        azi = np.abs(zi) if burning else zi
        wr, wi = azr, azi
        for _ in range(power - 1):
            wr, wi = wr * azr - wi * azi, wr * azi + wi * azr
        zr = np.where(active, wr + c_real, zr)
        zi = np.where(active, wi + c_imag, zi)
        escaped = active & (zr * zr + zi * zi >= 4.0)
        counts = np.where(escaped, np.int32(it), counts)
        active &= ~escaped
        if not active.any():
            break
    return counts


def scale_counts_to_uint8(counts: np.ndarray, max_iter: int,
                          clamp: bool = False) -> np.ndarray:
    """Scale escape counts to the uint8 pixel encoding.

    Parity mode (``clamp=False``) reproduces the reference exactly
    (``DistributedMandelbrotWorkerCUDA.py:96-98``): ``ceil(v * 256 /
    max_iter)`` cast to uint8, which *wraps* 256 -> 0 for ``max_iter > 256``
    (a pixel escaping near the iteration ceiling reads as in-set).  Quality
    mode (``clamp=True``) clamps to 255 instead.
    """
    scaled = np.ceil((counts.astype(np.float64) * 256.0) / max_iter)
    if clamp:
        scaled = np.minimum(scaled, 255.0)
    return scaled.astype(np.uint8)
