"""The sanctioned gateway for half-precision arithmetic in kernel code.

Why a module for two helpers: the static checker (``dmtpu check``,
rule ``jax-dtype-mix``) flags half-precision dtype literals inside
traced functions under ``ops/``/``parallel/`` — a bf16 value that leaks
into an output expression silently costs ~3 decimal digits, and escape
COUNTS are a bit-exact contract (the golden tests diff uint8 planes).
Importing from THIS module is the opt-in: it marks the file as a
reviewed mixed-precision site, the same way ``ensure_x64`` marks the
reviewed f64 sites for the ``jax-dtype`` rule.

The parity-guard contract every caller must keep (and the one the
megakernel's guard test pins): half precision may only ever feed
*advisory* products — scouting classifications, occupancy censuses,
scheduling hints — never the authoritative iteration state or anything
derived into tile output.  The f32 recurrence always runs from z0 and
alone decides escape counts, so scout-on vs scout-off is bit-identical
by construction.  There is no sound shortcut here: a bf16 orbit
diverges from the f32 orbit after a handful of steps on chaotic
boundary pixels (the iteration map amplifies the ~2^-8 mantissa gap
exponentially), so no conservative margin can hand a *count* across the
precision boundary — only a prediction.
"""

from __future__ import annotations

import jax.numpy as jnp

# The scouting dtype: bf16 keeps f32's exponent range (escape-radius
# tests never spuriously overflow out of range, only out of precision)
# and packs two lanes per f32 slot on the VPU.
SCOUT_DTYPE = jnp.bfloat16


def scout_cast(x):
    """Demote an f32 operand into the scouting precision (advisory lanes
    only — see the parity-guard contract in the module docstring)."""
    return x.astype(SCOUT_DTYPE)


def scout_const(value):
    """A scalar constant in the scouting precision (e.g. the escape
    radius squared) — the one place a half dtype literal is sanctioned."""
    return jnp.asarray(value, SCOUT_DTYPE)
