"""Perturbation-theory deep zoom: TPU-speed rendering at depths where
direct iteration runs out of precision.

The reference system's only deep-zoom story is float64 direct iteration
(the CUDA kernel at ``DistributedMandelbrotWorkerCUDA.py:39-68`` is
float64), which (a) emulates slowly on TPU and (b) hard-stops when the
pixel pitch approaches 1e-16.  The classic perturbation decomposition
removes both limits:

    z_n = Z_n + dz_n

where ``Z`` is ONE high-precision reference orbit for the tile center
(computed host-side in fixed-point bigints — exact, stdlib-only) and the
per-pixel delta obeys

    dz_{n+1} = 2 Z_n dz_n + dz_n^2 + dc

with every quantity now *relative* to the center, so f32/f64 device math
suffices: the deltas span the tile (~pixel pitch scale), not the plane.
The device kernel is a ``lax.scan`` over the truncated-orbit arrays —
per-iteration reference values stream in as scan inputs, pixels advance
in lockstep, and the MXU-free VPU math is identical in shape to the
direct kernel's.

Glitch handling (Pauldelbrot's criterion): where ``|z_n|`` collapses far
below ``|Z_n|`` the catastrophic cancellation makes the delta orbit
untrustworthy — those pixels are flagged on device and recomputed
exactly on host in fixed point (typically a small fraction of a tile;
the count is reported so callers can see it).  If the reference orbit
itself escapes before the budget, iteration past that point cannot use
the orbit — affected pixels are likewise flagged and recomputed.

Capability extension past the reference: ``DeepTileSpec`` carries the
center as *decimal strings*, so views with spans far below 1e-16 (where
float64 cannot even address pixel coordinates) render fine — only the
span and pixel offsets need floating point.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

logger = logging.getLogger(__name__)

# Fixed-point precision floor for the reference orbit (fractional bits);
# compute_counts_perturb widens automatically with depth so the orbit
# always carries >= 64 bits below the pixel pitch — the widening formula,
# not this floor, is what enforces the precision policy.  One 128-bit
# limb pair is the floor (the auto-widening already exceeds it beyond
# span ~1e-19): the old 256 floor doubled the limb work of every orbit
# and every exact glitch repair at production depths for no added
# guarantee (this rig is single-core — the repair loop is serial), a
# measured 2x on the config-4 repair pass.
DEFAULT_PREC_BITS = 128

# Pauldelbrot criterion: |z|^2 < GLITCH_TOL * |Z|^2 marks a pixel
# glitched (cancellation ate the significand).
GLITCH_TOL = 1e-6

# How many glitched pixels to try as the secondary reference before
# giving up on the device repair pass.  A candidate whose orbit escapes
# early costs only that escape-length bigint orbit, so misses are far
# cheaper than the per-pixel exact loop the pass replaces.
SECONDARY_REFERENCE_TRIES = 8


# -- host-side exact arithmetic (stdlib bigints) --------------------------


def _to_fixed(value: str | float, bits: int) -> int:
    """Decimal string (or float) -> fixed-point integer with ``bits``
    fractional bits, exactly."""
    if isinstance(value, float):
        # Floats convert exactly: value = num/den in lowest binary terms.
        from fractions import Fraction

        f = Fraction(value)
        return (f.numerator << bits) // f.denominator
    s = str(value).strip()
    neg = s.startswith("-")
    s = s.lstrip("+-")
    exp = 0
    if "e" in s or "E" in s:
        s, e = s.replace("E", "e").split("e")
        exp = int(e)
    if "." in s:
        whole, frac = s.split(".")
    else:
        whole, frac = s, ""
    digits = int((whole + frac) or "0")
    exp -= len(frac)
    # value = digits * 10^exp; scale by 2^bits exactly.
    if exp >= 0:
        num = digits * (10 ** exp) << bits
    else:
        num = (digits << bits) // (10 ** (-exp))
    return -num if neg else num


def _fixed_to_float(v: int, bits: int) -> float:
    """Fixed-point -> float64 without materializing float(v): the orbit
    extension stores values up to ~1e100, whose bigints exceed float64
    range once ``bits`` > ~690 (deep-zoom precision widening)."""
    if v == 0:
        return 0.0
    import math

    m = abs(v)
    shift = m.bit_length() - 53
    if shift > 0:
        # Round to nearest, not truncate — keeps exact round trips.
        out = math.ldexp((m + (1 << (shift - 1))) >> shift, shift - bits)
    else:
        out = math.ldexp(m, -bits)
    return -out if v < 0 else out


def reference_orbit(center_re: str | float, center_im: str | float,
                    max_iter: int, *,
                    prec_bits: int = 256
                    ) -> tuple[np.ndarray, np.ndarray, int]:
    """High-precision escape-time orbit of the center, truncated to
    float64 arrays.  The arrays are shared with an LRU cache — treat
    them as read-only.

    Returns ``(Z_re, Z_im, valid_len)`` with ``Z[k] = z_{k+1}`` — the
    orbit runs ``z_1 = c`` through ``z_{max_iter}`` (the last value the
    reference convention ever tests), so a full in-set orbit has
    ``valid_len == max_iter``.  The ARRAYS extend past ``valid_len`` by
    up to 12 further true orbit values (post-escape they diverge) so
    pixels escaping near the orbit's end can reach the smooth-coloring
    radius; consumers needing only the tested orbit must slice
    ``Z[:valid_len]``.  Arithmetic is ``prec_bits``-bit fixed-point
    bigint (stdlib): per-step rounding is 2^-prec_bits — the default
    stays 256 bits (~77 decimal digits of input precision) because this
    public helper takes raw decimal strings with NO depth auto-widening,
    unlike the _compute_perturb path and its 128-bit floor.
    """
    v_re = _to_fixed(center_re, prec_bits)
    v_im = _to_fixed(center_im, prec_bits)
    return _orbit_fixed(v_re, v_im, v_re, v_im, max_iter, prec_bits)


from functools import lru_cache


def _native_fixed(bits: int = 0, *vals: int) -> bool:
    """Use the native fixed-point kernels for these inputs?  (Exact-
    parity C++ limb loops, several times the CPython-bigint rate; tests
    pin bytewise parity.)  The native buffers bound input magnitudes at
    2^(bits+2) (|value| < 4 — anything beyond escapes at iteration 1
    but must still count CORRECTLY); wilder inputs, which the fixed
    buffers would overflow, stay on the unbounded Python path."""
    if any(abs(v).bit_length() > bits + 2 for v in vals):
        return False
    try:
        from distributedmandelbrot_tpu.native import bindings

        return bindings.native_supported()
    except Exception:
        return False


# Budgets whose orbit arrays stay worth caching at depth 64: above
# this, 64 cached entries of (max_iter+12) * 16 B each could hold
# gigabytes.  Giant orbits keep a 2-deep LRU instead of bypassing
# entirely — a zoom animation still reuses its center's orbit across
# frames (on the pure-Python fallback path a 200k+-step bigint
# recompute per frame would cost minutes), with memory bounded at two
# orbits' worth.
ORBIT_CACHE_MAX_STEPS = 200_000


def _orbit_fixed(za: int, zb: int, ca: int, cb: int, max_iter: int,
                 bits: int, extra: int = 12
                 ) -> tuple[np.ndarray, np.ndarray, int]:
    """Orbit entries ``z_1..`` plus up to ``extra`` true diverging steps
    past the first escape (or past the budget), so pixels escaping near
    the orbit's end can still reach the smooth-coloring radius.  The
    returned ``valid_len`` counts only the pre-extension entries; the
    arrays may be longer.  Post-escape values square each step, so the
    extension stops before float64 overflow (~1e100).

    LRU-cached below :data:`ORBIT_CACHE_MAX_STEPS` (treat the returned
    arrays as immutable): a zoom animation re-renders the same center
    at every frame, and the orbit depends only on (center, budget,
    precision) — with precision quantized by the caller, frames share
    one bigint computation.  The cache must hold at least 1 primary +
    SECONDARY_REFERENCE_TRIES candidate orbits per view or a single
    tile's repair pass evicts its own entries (64 covers several views;
    arrays are 16 B per orbit step, ~1 MB at the 50k BASELINE budget)."""
    if max_iter > ORBIT_CACHE_MAX_STEPS:
        return _orbit_cached_giant(za, zb, ca, cb, max_iter, bits, extra)
    return _orbit_cached(za, zb, ca, cb, max_iter, bits, extra)


@lru_cache(maxsize=64)
def _orbit_cached(za: int, zb: int, ca: int, cb: int, max_iter: int,
                  bits: int, extra: int
                  ) -> tuple[np.ndarray, np.ndarray, int]:
    return _orbit_fixed_impl(za, zb, ca, cb, max_iter, bits, extra)


@lru_cache(maxsize=2)
def _orbit_cached_giant(za: int, zb: int, ca: int, cb: int,
                        max_iter: int, bits: int, extra: int
                        ) -> tuple[np.ndarray, np.ndarray, int]:
    return _orbit_fixed_impl(za, zb, ca, cb, max_iter, bits, extra)


def _orbit_fixed_impl(za: int, zb: int, ca: int, cb: int, max_iter: int,
                      bits: int, extra: int = 12
                      ) -> tuple[np.ndarray, np.ndarray, int]:
    if _native_fixed(bits, za, zb, ca, cb):
        from distributedmandelbrot_tpu.native import bindings

        return bindings.fixed_orbit(za, zb, ca, cb, max_iter, bits, extra)
    one = 1 << bits
    four = 4 * one * one  # |z|^2 comparisons happen at 2*bits scale
    huge = (10 ** 100) * one * one
    steps = max(1, max_iter)
    z_re = np.empty(steps + extra, np.float64)
    z_im = np.empty(steps + extra, np.float64)
    a, b = za, zb
    n = 0
    valid = None
    while n < steps + extra:
        z_re[n] = _fixed_to_float(a, bits)
        z_im[n] = _fixed_to_float(b, bits)
        n += 1
        a2 = a * a
        b2 = b * b
        if valid is None and (n >= steps or a2 + b2 >= four):
            valid = n
        if valid is not None and (n >= valid + extra or a2 + b2 >= huge):
            break
        a, b = (a2 - b2 >> bits) + ca, ((a * b) >> (bits - 1)) + cb
    return z_re[:n], z_im[:n], valid if valid is not None else n


# The uncached implementation under the same attribute functools exposed
# before the size guard split the cache out (tests and instrumentation
# reach the raw loop this way).
_orbit_fixed.__wrapped__ = _orbit_fixed_impl  # type: ignore[attr-defined]


def _escape_counts_exact_batch(points: list[tuple[int, int]],
                               max_iter: int, bits: int,
                               julia_c: tuple[int, int] | None
                               ) -> np.ndarray:
    """Exact escape counts for a batch of fixed-point points — the
    glitch-repair remainder.  Native path: one C++ call, threaded over
    cores.  Fallback: the per-point loop."""
    flat = [v for p in points for v in p]
    if julia_c is not None:
        flat += list(julia_c)
    if _native_fixed(bits, *flat):
        from distributedmandelbrot_tpu.native import bindings

        return bindings.fixed_escape_batch(points, max_iter, bits,
                                           julia_c=julia_c)
    ca, cb = julia_c if julia_c is not None else (None, None)
    return np.array([_escape_count_fixed(pa, pb, max_iter, bits,
                                         ca=ca, cb=cb)
                     for pa, pb in points], np.int32)


def escape_counts_exact(c_re: str | float, c_im: str | float, max_iter: int,
                        *, prec_bits: int = 256) -> int:
    """Reference-convention escape count of one point in fixed point
    (the glitch-pixel fallback): 0 = never escaped within budget."""
    return _escape_count_fixed(_to_fixed(c_re, prec_bits),
                               _to_fixed(c_im, prec_bits),
                               max_iter, prec_bits)


# -- geometry -------------------------------------------------------------


@dataclass(frozen=True)
class DeepTileSpec:
    """A deep-zoom view: center as decimal strings (arbitrary precision),
    span in plane units (a float — spans are small, centers are not).

    Pixel (row, col) sits at center + ((col - (w-1)/2) * step,
    (row - (h-1)/2) * step) with step = span / (width - 1): deltas from
    the center are what the device kernel consumes, and they are
    comfortably representable at any zoom.
    """

    center_re: str
    center_im: str
    span: float
    width: int = 1024
    height: int = 1024

    @property
    def step(self) -> float:
        return self.span / (self.width - 1)

    def delta_grids(self, dtype=np.float64) -> tuple[np.ndarray, np.ndarray]:
        step = self.step
        col = (np.arange(self.width) - (self.width - 1) / 2) * step
        row = (np.arange(self.height) - (self.height - 1) / 2) * step
        dre = np.broadcast_to(col, (self.height, self.width))
        dim = np.broadcast_to(row[:, None], (self.height, self.width))
        return dre.astype(dtype).copy(), dim.astype(dtype).copy()


# -- device kernel --------------------------------------------------------


PERTURB_SEGMENT = 256


# Stagnation stop for the delta scans (round-4, config-4 profile): the
# device scan's whole-chunk early exit only fires when EVERY lane
# retires, so a handful of bounded lanes — which end up glitch-flagged
# and exactly recomputed anyway — dragged the full 512^2 chunk through
# the entire 50000-step orbit (measured: 678 such lanes = 74% of warm
# render time).  Once the live count has not changed for this many
# steps AND the live set is small, the scan stops and flags the
# stragglers as suspect; they join the exact-repair path that already
# guaranteed their values.  Output-exact by construction (the repair is
# exact); the trade is bounded: at most ``STAGNATION_MAX_LIVE`` lanes
# can be diverted, each costing one exact fixed-point orbit — a FLAT
# cap, because a relative one would let a minibrot sliver of thousands
# of clean in-set pixels (which the device scan retires for free) be
# diverted to the serial bigint loop (round-4 review finding).
STAGNATION_QUIET_STEPS = 2048
STAGNATION_MAX_LIVE = 64


def _segmented_orbit_scan(step, init, z_re, z_im, live_of, *,
                          segment: int = PERTURB_SEGMENT,
                          stagnation=None):
    """``lax.scan(step, init, orbit)`` with tile-granular early exit.

    The delta scans are select-free with sticky masks, so once no lane
    is live the remaining orbit steps are semantic no-ops — but a plain
    ``lax.scan`` still executes all of them, and deep budgets dwarf
    actual escape depths (measured: max escape 567 of a 50000 budget on
    the BASELINE config-4 window — 99% of the scan wasted).  Full
    ``segment``-step slices run under a ``while_loop`` that stops when
    ``live_of(carry)`` reports no live lanes; the ragged tail runs as a
    plain scan (its lanes are inert if the loop exited early).

    ``stagnation=(live_count_of, live_mask_of, cap)`` additionally arms
    the stagnation stop (see :data:`STAGNATION_QUIET_STEPS`): the loop
    also exits when the live count is both <= ``cap`` and unchanged for
    the quiet window, and the return becomes ``(carry, suspect)`` where
    ``suspect`` marks lanes still live at such a stop — their carry
    values are NOT trustworthy (the ragged tail may step them against
    mismatched orbit entries) and the caller MUST route them to an
    exact recompute.

    Identity scope: every carry component FROZEN by the live masks
    (masks, counts, frozen z) matches the full scan bit-for-bit; the
    raw dz components keep advancing in a full scan and may differ
    after an early exit — no consumer reads them post-scan, and a new
    one must not without revisiting this.

    Deliberately separate from ``escape_time.segmented_while``: that
    driver generates steps from a budget and lets the last segment
    OVERRUN (callers cancel the overrun arithmetically), which is
    impossible here — every step consumes one specific orbit entry, so
    segments must slice the streamed inputs exactly.
    """
    orbit_len = z_re.shape[0]
    full = orbit_len // segment

    def run_segment(seg, carry):
        zr = lax.dynamic_slice_in_dim(z_re, seg * segment, segment)
        zi = lax.dynamic_slice_in_dim(z_im, seg * segment, segment)
        carry, _ = lax.scan(step, carry, (zr, zi))
        return carry

    if stagnation is None:
        def seg_body(state):
            seg, carry = state
            return (seg + 1, run_segment(seg, carry))

        def seg_cond(state):
            seg, carry = state
            return (seg < full) & live_of(carry)

        carry = init
        if full:
            _, carry = lax.while_loop(seg_cond, seg_body,
                                      (jnp.asarray(0, jnp.int32), carry))
        if orbit_len - full * segment:
            carry, _ = lax.scan(step, carry, (z_re[full * segment:],
                                              z_im[full * segment:]))
        return carry

    live_count_of, live_mask_of, cap = stagnation
    quiet_segs = max(1, STAGNATION_QUIET_STEPS // segment)

    def seg_body(state):
        seg, last_change, prev, carry = state
        carry = run_segment(seg, carry)
        cnt = live_count_of(carry)
        last_change = jnp.where(cnt != prev, seg + 1, last_change)
        return (seg + 1, last_change, cnt, carry)

    def seg_cond(state):
        seg, last_change, prev, carry = state
        return ((seg < full) & (prev > 0)
                & (((seg - last_change) < quiet_segs) | (prev > cap)))

    carry = init
    seg_final = jnp.asarray(full, jnp.int32)
    if full:
        seg_final, _, _, carry = lax.while_loop(
            seg_cond, seg_body,
            (jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
             live_count_of(init), carry))
    # Lanes live at a premature stop: their values are suspect (and the
    # ragged tail below may advance them against the WRONG orbit
    # entries — harmless only because they are flagged here, before it
    # runs on the carry).
    suspect = live_mask_of(carry) & (seg_final < full)
    if orbit_len - full * segment:
        carry, _ = lax.scan(step, carry, (z_re[full * segment:],
                                          z_im[full * segment:]))
    return carry, suspect


@partial(jax.jit, static_argnames=("max_iter", "add_dc", "stagnation"))
def _perturb_scan(z_re, z_im, dc_re, dc_im, *, max_iter: int,
                  add_dc: bool = True, stagnation: bool = True):
    """Delta-orbit scan: returns (counts, glitched).

    Step ``k`` receives ``Z[k] = z_{k+1}`` of the center orbit and the
    carry holds ``dz_{k+1}`` (``dz_1 = dc``): it tests the full value
    ``z_{k+1} = Z + dz`` and then advances the delta.  ``n`` counts
    passed tests, so a pixel first escaping at ``z_e`` (reference count
    ``it = e - 1``) accumulates ``n = e - 1``; pixels failing even the
    untested-by-the-reference ``z_1`` probe (|c| > 2) get ``n = 0`` and
    are clamped up to the reference's ``1``.  Passing every test through
    ``z_{max_iter}`` (``n = max_iter``) means in-set -> 0.

    ``glitched`` marks pixels whose delta lost significance (Pauldelbrot
    cancellation) or that outlived an early-escaping reference orbit —
    their counts are unreliable and must be recomputed exactly.
    """
    dtype = jnp.result_type(dc_re)
    orbit_len = z_re.shape[0]
    shape = dc_re.shape
    four = jnp.asarray(4.0, dtype)
    tol = jnp.asarray(GLITCH_TOL, dtype)

    def step(carry, zs):
        dzr, dzi, active, n, glitched = carry
        zr, zi = zs
        # Full value z_{k+1} = Z + dz; escape test on it.
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (active & (mag2 < tol * zmag2))
        active = active & (mag2 < four)
        n = n + active.astype(jnp.int32)
        # dz_{k+2} = 2 Z_{k+1} dz + dz^2 [+ dc]  (escaped lanes keep
        # iterating, select-free — the sticky mask freezes their count).
        # The dc term re-adds the pixel's parameter offset — Mandelbrot
        # only; for Julia every pixel shares c, so deltas carry no dc
        # (dz_1 is the pixel's z0 offset instead).
        ndzr = (zr + zr) * dzr - (zi + zi) * dzi + (dzr * dzr - dzi * dzi)
        ndzi = (zr + zr) * dzi + (zi + zi) * dzr + 2 * dzr * dzi
        if add_dc:
            ndzr = ndzr + dc_re
            ndzi = ndzi + dc_im
        return (ndzr, ndzi, active, n, glitched), None

    init = (dc_re.astype(dtype), dc_im.astype(dtype),
            jnp.ones(shape, jnp.bool_), jnp.zeros(shape, jnp.int32),
            jnp.zeros(shape, jnp.bool_))
    # ``stagnation=False`` callers (the reference hop probe, the auto-BLA
    # probe) need the true alive-at-orbit-end mask — a stagnation stop
    # would report early-stopped lanes as alive and break the hop
    # invariant "probes still bounded when the orbit ran out"
    # (round-4 review finding).
    if stagnation:
        (dzr, dzi, active, n, glitched), suspect = _segmented_orbit_scan(
            step, init, z_re.astype(dtype), z_im.astype(dtype),
            lambda c: jnp.any(c[2]),
            stagnation=(lambda c: jnp.sum(c[2], dtype=jnp.int32),
                        lambda c: c[2], STAGNATION_MAX_LIVE))
    else:
        dzr, dzi, active, n, glitched = _segmented_orbit_scan(
            step, init, z_re.astype(dtype), z_im.astype(dtype),
            lambda c: jnp.any(c[2]))
        suspect = jnp.zeros(shape, jnp.bool_)

    # Pixels still bounded when the (possibly escaped-early) reference
    # orbit ran out: if the orbit covered the full budget they are
    # in-set; otherwise their fate is unknown -> glitched.  Stagnation-
    # stopped stragglers are likewise unknown -> glitched (the exact
    # repair that already guaranteed their values computes them).
    if orbit_len < max_iter:
        glitched = glitched | active
    glitched = glitched | suspect
    counts = jnp.where(n >= max_iter, 0, jnp.maximum(n, 1))
    return counts, glitched, active


def _pack_mask(g):
    """Bit-pack a boolean mask device-side (little-endian within each
    byte, matching ``np.unpackbits(..., bitorder="little")``): the
    glitch plane crosses the device->host link once per chunk, and on a
    tunneled rig that link (~35 MB/s) is a dominant cost of the deep
    path — 1 bit/pixel instead of 1 byte is a straight 8x on it.
    Must be traced inside the caller's jit (a bare call would pay its
    own dispatch and forfeit the saving)."""
    flat = g.reshape(-1).astype(jnp.uint8)
    pad = (-flat.size) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.int32)
    return jnp.sum(flat.reshape(-1, 8).astype(jnp.int32) * weights,
                   axis=1, dtype=jnp.int32).astype(jnp.uint8)


def _unpack_mask_np(packed: np.ndarray, shape) -> np.ndarray:
    """Host-side inverse of :func:`_pack_mask`."""
    n = int(np.prod(shape))
    return np.unpackbits(packed, bitorder="little")[:n].reshape(
        shape).astype(bool)


@partial(jax.jit, static_argnames=("max_iter", "add_dc", "stagnation"))
def _perturb_scan_fetch(z_re, z_im, dc_re, dc_im, *, max_iter: int,
                        add_dc: bool = True, stagnation: bool = True):
    """:func:`_perturb_scan` shaped for the device->host fetch: counts
    narrowed to uint16 when the budget allows (counts <= max_iter <
    2^16 — lossless) and the glitch mask bit-packed, both inside ONE
    jit so the trimming costs no extra dispatch.  The driver widens
    and unpacks on the host."""
    counts, glitched, _ = _perturb_scan(z_re, z_im, dc_re, dc_im,
                                        max_iter=max_iter, add_dc=add_dc,
                                        stagnation=stagnation)
    if max_iter < (1 << 16):
        counts = counts.astype(jnp.uint16)
    return counts, _pack_mask(glitched)


@partial(jax.jit, static_argnames=("max_iter", "bailout", "add_dc"))
def _perturb_scan_smooth_fetch(z_re, z_im, dc_re, dc_im, *, max_iter: int,
                               bailout: float, add_dc: bool = True):
    """Smooth twin of :func:`_perturb_scan_fetch` (nu stays f32; only
    the glitch mask packs)."""
    nu, glitched = _perturb_scan_smooth(z_re, z_im, dc_re, dc_im,
                                        max_iter=max_iter, bailout=bailout,
                                        add_dc=add_dc)
    return nu, _pack_mask(glitched)


# Auto-BLA gate (round-4, verdict item 3): ``bla=None`` probes whether
# the tile-granular skip path would pay before committing to either
# scan.  The probe is the EXACT delta scan on a ~4096-lane subsample of
# the tile, capped at BLA_AUTO_PROBE_STEPS: BLA wins exactly on views
# whose lanes stay bounded (and cancellation-clean) deep into a
# full-budget orbit — slow dynamics near parabolic points / minibrot
# margins, measured 9.5x on the bond-point bench — and loses on
# escape-rich views whose scans exit early anyway (measured -12% on the
# config-4 Misiurewicz window).  Survivor fraction at the probe horizon
# separates the two cleanly: ~1.0 on the bond view vs ~0.003 on
# config 4.  Decisions are cached per (orbit, budget, delta-scale)
# so animations and bench repeats pay the probe dispatch once.
BLA_AUTO_MIN_BUDGET = 20000
BLA_AUTO_PROBE_STEPS = 4096
BLA_AUTO_PROBE_LANES = 4096
BLA_AUTO_SURVIVOR_FRAC = 0.5
_AUTO_BLA_CACHE: "OrderedDict[tuple, bool]" = OrderedDict()
_AUTO_BLA_CACHE_MAX = 64


def _auto_bla(z_re: np.ndarray, z_im: np.ndarray, zr_dev, zi_dev,
              dre: np.ndarray, dim: np.ndarray, max_iter: int,
              add_dc: bool, dtype=np.float32) -> bool:
    """Decide the BLA question for one (orbit, view, budget) — see the
    gate note above.  ``z_re/z_im`` are the host orbit (cache key),
    ``zr_dev/zi_dev`` the device copies the probe scans against."""
    if max_iter < BLA_AUTO_MIN_BUDGET or len(z_re) < max_iter:
        # Shallow budgets have nothing worth skipping; an early-escaping
        # reference means an exterior-dominated view (config-4 class) —
        # the scan is short and BLA's table build would outcost it.
        return False
    scale = float(max(np.max(np.abs(dre)), np.max(np.abs(dim)), 1e-300))
    key = (len(z_re), float(z_re[0]), float(z_im[0]), float(z_re[-1]),
           float(z_im[-1]), max_iter, add_dc, np.dtype(dtype).str,
           int(np.round(np.log2(scale))))
    hit = _AUTO_BLA_CACHE.get(key)
    if hit is not None:
        _AUTO_BLA_CACHE.move_to_end(key)
        return hit
    # 2-D lattice over the separable delta grid (a raveled stride at a
    # width-multiple would collapse to one column — round-4 review
    # finding), probed at the RENDER dtype (an f32 cast of sub-f32-floor
    # f64 deltas would flush to zero and shadow the reference).
    h, w = dre.shape
    side = int(np.sqrt(BLA_AUTO_PROBE_LANES))
    ci = np.linspace(0, w - 1, min(side, w)).astype(int)
    ri = np.linspace(0, h - 1, min(side, h)).astype(int)
    pre = np.broadcast_to(dre[0, ci][None, :], (len(ri), len(ci)))
    pim = np.broadcast_to(dim[ri, 0][:, None], (len(ri), len(ci)))
    plen = min(BLA_AUTO_PROBE_STEPS, len(z_re))
    _, glitched, active = _perturb_scan(
        zr_dev[:plen], zi_dev[:plen],
        jnp.asarray(pre.astype(dtype)),
        jnp.asarray(pim.astype(dtype)),
        max_iter=plen, add_dc=add_dc, stagnation=False)
    frac = float(np.asarray(active & ~glitched).mean())
    decision = frac >= BLA_AUTO_SURVIVOR_FRAC
    logger.info("BLA auto-%s: probe survivor fraction %.3f at step %d "
                "(budget %d)", "enabled" if decision else "disabled",
                frac, plen, max_iter)
    _AUTO_BLA_CACHE[key] = decision
    while len(_AUTO_BLA_CACHE) > _AUTO_BLA_CACHE_MAX:
        _AUTO_BLA_CACHE.popitem(last=False)
    return decision


@lru_cache(maxsize=16)
def _find_reference(za: int, zb: int, ca: int, cb: int, span: float,
                    max_iter: int, bits: int, *, add_dc: bool = True,
                    probes: int = 5, hops: int = 8
                    ) -> tuple[np.ndarray, np.ndarray, int, float, float]:
    """Pick a reference point whose orbit survives as long as possible.

    The view center is rarely in the set, and an early-escaping reference
    orbit strands every pixel that outlives it.  Iterative deepening
    fixes that cheaply: compute the current candidate's orbit, scan a
    coarse probe lattice of the tile against it (the same device kernel,
    ``probes^2`` pixels — microseconds), and hop to a probe that outlives
    the orbit; repeat until the orbit covers the full budget or nothing
    in the lattice outlives it (tile is all-exterior — the longest-lived
    candidate then covers all but a handful of pixels, which fall back
    to exact recompute).  Returns the orbit and the chosen reference's
    offset from the original center (plane units, pixel scale).

    LRU-cached (treat the returned arrays as immutable): the hop search
    is deterministic in its arguments, and each hop costs a device
    probe-scan dispatch + fetch — measured 0.42 s of a 0.50 s call on a
    tunneled rig for an early-escaping center re-searched every call.
    This pays off on exact same-view recomputes (repeated renders of
    one view in a process, the bench's timing repeats); a zoom
    animation's span changes every frame, so IT misses here and relies
    on the span-free _orbit_fixed cache underneath instead.
    """
    off_re = 0.0
    off_im = 0.0
    # Lattice density escalates once: the coarse pass is enough while
    # outliving probes exist, but on all-exterior views the deepest
    # pixels occupy a sliver of the area (config-4 1024^2: ~0.25%) that
    # a probes^2 lattice almost never samples — the dense pass trades
    # ONE more probe-scan dispatch (cold path only; the whole search is
    # LRU-cached) for hundreds fewer serial exact repairs.
    # Mandelbrot only: in julia mode a deeper exterior reference was
    # measured to SHIFT cancellation mis-certification (a bounded pixel
    # slipping the 1e-6 tolerance reads as escaped) on the repelling-
    # fixed-point test view — the escalation's win is the exterior-
    # dominated Mandelbrot case, so julia keeps the coarse-lattice
    # behavior unchanged.
    lattices = [probes, 64] if add_dc else [probes]
    li = 0
    z_re, z_im, n = _orbit_fixed(za, zb, ca, cb, max_iter, bits)
    for _ in range(hops + len(lattices)):
        if n >= max_iter:
            break
        side = lattices[li]
        lat = np.linspace(-span / 2, span / 2, side)
        pre = np.broadcast_to(lat, (side, side)).ravel() - off_re
        pim = np.repeat(lat, side) - off_im
        # Probe against the orbit's VALID prefix: the post-escape
        # extension (there for smooth laggards) diverges and would
        # corrupt the alive mask with cancellation noise.
        counts, _, alive = _perturb_scan(
            jnp.asarray(z_re[:n]), jnp.asarray(z_im[:n]),
            jnp.asarray(pre.astype(np.float64)),
            jnp.asarray(pim.astype(np.float64)), max_iter=max_iter,
            add_dc=add_dc, stagnation=False)
        # Hop targets are probes still bounded when the orbit ran out —
        # NOT the glitched mask, which also contains cancellation-flagged
        # probes that escaped earlier than the reference did.
        alive = np.asarray(alive)
        if alive.any():
            # Hop to the outliving probe nearest the view center.
            idx = np.argwhere(alive).ravel()
            best = idx[np.argmin(np.abs(pre[idx] + off_re)
                                 + np.abs(pim[idx] + off_im))]
        else:
            if not add_dc:
                # Julia mode: no deepening at all (see the lattice note
                # above) — an all-exterior lattice ends the search.
                break
            # All-exterior lattice: climb the escape-depth gradient —
            # hop to the DEEPEST-escaping probe while the orbit
            # strictly deepens, then escalate the lattice once.  Every
            # iteration of coverage recovered converts outliving pixels
            # from the serial exact-repair loop back to the device scan;
            # the deepening orbits are escape-length bigints — cheap.
            best = int(np.argmax(np.asarray(counts)))
        d_re, d_im = float(pre[best]), float(pim[best])
        za2 = za + _to_fixed(d_re, bits)
        zb2 = zb + _to_fixed(d_im, bits)
        ca2, cb2 = (za2, zb2) if add_dc else (ca, cb)
        z_re2, z_im2, n2 = _orbit_fixed(za2, zb2, ca2, cb2, max_iter, bits)
        if not alive.any() and n2 <= n:
            if li + 1 < len(lattices):
                li += 1  # densify and retry from the current best
                continue
            break  # depth gradient exhausted at the densest lattice
        za, zb, ca, cb = za2, zb2, ca2, cb2
        z_re, z_im, n = z_re2, z_im2, n2
        off_re += d_re
        off_im += d_im
    return z_re, z_im, n, off_re, off_im


def _secondary_candidates(bad: np.ndarray, scanned: np.ndarray,
                          height: int, width: int) -> np.ndarray:
    """Order glitched pixels by how likely their exact orbit is to cover
    the full budget: scanned value 0 first (the pixel stayed bounded
    through the whole scan, however unreliably — 0 means in-set on both
    the integer and smooth planes), then deeper escape values, ties
    broken toward the view center (glitches cluster around the bounded
    structure causing them, so central pixels are likelier in-set)."""
    mid = np.array([(height - 1) / 2, (width - 1) / 2])
    center_dist = np.abs(bad - mid).sum(axis=1)
    return np.lexsort((center_dist, -scanned, scanned != 0))


_DEVICE_ORBIT_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_DEVICE_ORBIT_MAX = 8
# Byte bound: giant-budget orbits (the _orbit_cached_giant class, ~80 MB
# for a 5M-step f64 orbit) must not pin hundreds of MB of HBM when the
# upstream 2-deep host cache thrashes and strands stale ids here.
_DEVICE_ORBIT_MAX_BYTES = 256 * 1024 * 1024


def _device_orbit(z_re: np.ndarray, z_im: np.ndarray):
    """Device-resident copy of a reference orbit, LRU-cached.

    Re-uploading the orbit dominates deep-zoom wall time on tunneled dev
    rigs (measured ~48 ms H2D for a 50000-step orbit vs ~40 ms of scan
    compute on the config-4 view); repeated renders of a view and the
    frames of an animation all reuse the same HOST orbit arrays (the
    lru caches on _find_reference/_orbit_fixed), so the device copy is
    keyed by host-array identity.  A content fingerprint guards against
    id reuse after an upstream lru eviction frees the array; the x64
    flag is part of the key because it changes the device dtype
    jnp.asarray produces.  Entries: ~a few MB each at production
    budgets, bounded at 8."""
    key = (id(z_re), id(z_im), z_re.shape[0],
           bool(jax.config.jax_enable_x64))
    fp = (float(z_re[0]), float(z_re[-1]), float(z_im[0]),
          float(z_im[-1]))
    hit = _DEVICE_ORBIT_CACHE.get(key)
    if hit is not None and hit[0] == fp:
        _DEVICE_ORBIT_CACHE.move_to_end(key)
        return hit[1], hit[2]
    # The orbit's post-escape extension squares toward ~1e100; without
    # x64 the f32 upload saturates those entries to inf BY DESIGN (the
    # scans treat them as escaped/invalid) — the numpy cast warning is
    # noise here.
    with np.errstate(over="ignore"):
        zr = jnp.asarray(z_re)
        zi = jnp.asarray(z_im)
    _DEVICE_ORBIT_CACHE[key] = (fp, zr, zi)

    def total_bytes():
        return sum(e[1].nbytes + e[2].nbytes
                   for e in _DEVICE_ORBIT_CACHE.values())

    while (len(_DEVICE_ORBIT_CACHE) > _DEVICE_ORBIT_MAX
           or (len(_DEVICE_ORBIT_CACHE) > 1
               and total_bytes() > _DEVICE_ORBIT_MAX_BYTES)):
        _DEVICE_ORBIT_CACHE.popitem(last=False)
    return zr, zi


def _compute_perturb(spec: DeepTileSpec, max_iter: int, scan_fn, *,
                     dtype, prec_bits: int, max_glitch_fix: int | None,
                     julia_c: tuple[str, str] | None = None,
                     scan_factory=None, repair_scan_fn=None,
                     bla: bool | None = False) -> tuple[np.ndarray, int]:
    """Shared perturbation driver: validates the span/dtype combination,
    widens orbit precision with depth, auto-selects the reference, runs
    ``scan_fn(zr, zi, dre, dim)`` over row chunks (it returns a value
    plane and a glitch mask), and patches glitched pixels with their
    exact fixed-point escape count.

    ``julia_c`` switches to the Julia family: the tile varies the START
    point ``z_0`` (the spec's center names a z-plane location) under the
    fixed parameter ``c`` — the delta recurrence simply loses its ``dc``
    term, everything else (reference selection, glitch handling, exact
    fallback) is family-agnostic.

    ``scan_factory(z_re, z_im, dc_max) -> scan_fn`` (optional) builds an
    orbit-specific scan instead of the shared ``scan_fn`` — the BLA fast
    path needs its skip tables rebuilt per reference orbit, including
    the secondary-reference repair pass.

    Spans must keep deltas representable: ~1e-30 floor for f32 deltas,
    ~1e-290 for f64 — deeper spans are rejected rather than silently
    flushed to a uniform tile.
    """
    span_floor = 1e-30 if np.dtype(dtype) == np.float32 else 1e-290
    if spec.span < span_floor:
        raise ValueError(
            f"span {spec.span:g} below the {np.dtype(dtype).name} delta "
            f"floor ({span_floor:g}); use a wider dtype")
    if np.dtype(dtype) == np.float64:
        from distributedmandelbrot_tpu.utils.precision import ensure_x64
        ensure_x64()  # without x64, f64 requests silently truncate to f32
    # Orbit precision tracks depth (>= 64 bits below the pixel pitch),
    # quantized to 128-bit steps so consecutive animation frames land on
    # the same precision and hit the orbit cache.
    need = int(-np.log2(max(spec.step, 1e-300))) + 64
    bits = max(prec_bits, -(-need // 128) * 128)
    za = _to_fixed(spec.center_re, bits)
    zb = _to_fixed(spec.center_im, bits)
    if julia_c is None:
        ca, cb = za, zb
        add_dc = True
    else:
        ca = _to_fixed(julia_c[0], bits)
        cb = _to_fixed(julia_c[1], bits)
        add_dc = False
    z_re, z_im, _, off_re, off_im = _find_reference(
        za, zb, ca, cb, spec.span, max_iter, bits, add_dc=add_dc)
    dre, dim = spec.delta_grids(np.float64)
    # Deltas are relative to the chosen reference, not the view center.
    dre -= off_re
    dim -= off_im
    zr, zi = _device_orbit(z_re, z_im)
    # bla tri-state: True/False = caller decided; None = probe (cached).
    use_bla = bla
    if use_bla is None:
        use_bla = (scan_factory is not None
                   and _auto_bla(z_re, z_im, zr, zi, dre, dim, max_iter,
                                 julia_c is None, dtype=dtype))
    if not use_bla:
        scan_factory = None  # secondary pass stays on the exact scan too
    if scan_factory is not None:
        dc_max = float(np.sqrt(np.max(dre * dre + dim * dim)))
        scan_fn = scan_factory(z_re, z_im, dc_max)
    # Row-chunked: the scan carries its state through every step; big
    # tiles are walked in row bands to bound the carry footprint.  The
    # band size is a measured trade (dev v5e, config-4 view, mi=50000):
    # each extra chunk pays a full dispatch + orbit re-walk, and raising
    # the limit from 2^17 to 2^20 pixels was monotonically faster at
    # every tile size tried (512^2: 0.47 -> 0.96 Mpix/s; 1024^2: 0.78 ->
    # 1.39).  f64 carries twice the bytes, so its limit is halved.
    limit = (1 << 20) if np.dtype(dtype) == np.float32 else (1 << 19)
    chunk = max(1, min(spec.height, limit // max(1, spec.width)))
    vals, glitches = [], []
    # The main grid's deltas are separable (dre varies along columns
    # only, dim along rows — delta_grids' construction): upload the two
    # VECTORS (KBs) and broadcast on device, instead of H x W planes —
    # on the tunneled rig the old 2D upload (8 MB at 1024^2 f32) cost
    # more than the scan itself.  Values are bit-identical: the same
    # host-f64 numbers, cast at upload, broadcast.
    # The vector upload (and _auto_bla's lattice probe) are correct ONLY
    # for separable grids; nothing else enforces that, and a future
    # non-separable delta_grids (rotation, jittered supersampling) would
    # silently render wrong pixels.  Cheap spot check, not a full scan.
    # A data-contract check in library code, so a real raise (assert
    # would vanish under python -O and let every pixel render wrong).
    # Full-array comparison: first-vs-last row/column alone would miss
    # interior-only jitter (e.g. a supersampling pattern that perturbs
    # every row but the edges).
    if not ((dre == dre[0]).all() and (dim == dim[:, :1]).all()):
        raise ValueError(
            "delta_grids produced a non-separable grid; the vector-upload "
            "broadcast path requires dre to vary by column only and dim "
            "by row only")
    dre_row = jnp.asarray(dre[0].astype(dtype))
    for r0 in range(0, spec.height, chunk):
        rows = min(chunk, spec.height - r0)
        dim_col = jnp.asarray(dim[r0:r0 + chunk, 0].astype(dtype))
        # device_get on the pair fetches both planes concurrently — two
        # sequential np.asarray calls pay the host link's round trip
        # twice (measured 2x on the dev rig's tunnel).
        v_part, g_part = jax.device_get(scan_fn(
            zr, zi,
            jnp.broadcast_to(dre_row[None, :], (rows, spec.width)),
            jnp.broadcast_to(dim_col[:, None], (rows, spec.width))))
        # Providers may trim the fetch (uint16 counts, bit-packed glitch
        # mask — see _perturb_scan_fetch): widen/unpack on the host.
        if g_part.dtype == np.uint8:
            g_part = _unpack_mask_np(g_part, v_part.shape)
        if v_part.dtype == np.uint16:
            v_part = v_part.astype(np.int32)
        vals.append(v_part)
        glitches.append(g_part)
    out = np.concatenate(vals).copy()
    glitched = np.concatenate(glitches)
    bad = np.argwhere(glitched)
    n_flagged = len(bad)
    step = spec.step
    if len(bad) > 1:
        # Secondary-reference pass (Pauldelbrot's standard fix): pick a
        # glitched pixel as a new reference — one further bigint orbit,
        # the same cost as exactly recomputing a single pixel — and
        # re-run just the glitched pixels' deltas against it on device.
        # Pixels that glitch against BOTH references fall through to the
        # exact loop.
        #
        # The pass engages only when the secondary orbit covers the FULL
        # budget (see below), so candidates are tried in in-set-
        # likelihood order until one does.  A failed candidate's orbit
        # stops at its escape, so misses are cheap (and LRU-cached for
        # the next frame); a cluster around bounded structure engages at
        # the first genuinely in-set pixel instead of giving up when the
        # single nearest-center pick happens to be exterior.
        z2 = None
        for ci in _secondary_candidates(bad, out[bad[:, 0], bad[:, 1]],
                                        spec.height, spec.width)[
                                            :SECONDARY_REFERENCE_TRIES]:
            r2, c2 = bad[ci]
            d2_re = float((c2 - (spec.width - 1) / 2) * step)
            d2_im = float((r2 - (spec.height - 1) / 2) * step)
            pa = za + _to_fixed(d2_re, bits)
            pb = zb + _to_fixed(d2_im, bits)
            if julia_c is None:
                z2_re, z2_im, n2v = _orbit_fixed(pa, pb, pa, pb,
                                                 max_iter, bits)
            else:
                z2_re, z2_im, n2v = _orbit_fixed(pa, pb, ca, cb,
                                                 max_iter, bits)
            if n2v >= max_iter:
                z2 = (z2_re, z2_im)
                break
        if z2 is not None:
            z2_re, z2_im = z2
            # Engage only when the secondary orbit covers the FULL
            # budget: an early-escaping secondary would scan bounded
            # lanes against its diverging post-escape extension, and
            # the scan values it produces for delicate pixels are not
            # reliably exact even when unflagged (measured on the
            # seahorse span-1e-10 window: a truncated-prefix repair
            # left a pixel at 3294 vs 3247 exact, and an f64 rescan
            # still mis-repaired 1 of 8 — the 1e-6 cancellation
            # tolerance cannot certify exactness near a minibrot).
            # All-exterior glitch clusters therefore take the exact
            # loop, which the native fixed-point kernel keeps cheap.
            #
            # Deltas relative to the secondary reference: exact in f64 —
            # they are index differences at pixel scale.  Padded to a
            # power-of-two length with far-exterior deltas so the jitted
            # scans see stable shapes (a zoom animation's per-frame
            # glitch count varies; each distinct shape would recompile).
            k = len(bad)
            k_pad = max(16, 1 << (k - 1).bit_length())
            dre2 = np.full(k_pad, 3.0)
            dim2 = np.zeros(k_pad)
            dre2[:k] = (bad[:, 1] - c2).astype(np.float64) * step
            dim2[:k] = (bad[:, 0] - r2).astype(np.float64) * step
            zr2_dev, zi2_dev = _device_orbit(z2_re, z2_im)
            if scan_factory is not None:
                dc2_max = float(np.sqrt(np.max(
                    dre2[:k] * dre2[:k] + dim2[:k] * dim2[:k])))
                scan2 = scan_factory(z2_re, z2_im, dc2_max)
            else:
                scan2 = repair_scan_fn or scan_fn
            v2, g2 = jax.device_get(scan2(
                zr2_dev, zi2_dev,
                jnp.asarray(dre2.astype(dtype)),
                jnp.asarray(dim2.astype(dtype))))
            if g2.dtype == np.uint8:
                g2 = _unpack_mask_np(g2, v2.shape)
            if v2.dtype == np.uint16:
                v2 = v2.astype(np.int32)
            v2 = v2[:k]
            g2 = g2[:k]
            fixed = bad[~g2]
            out[fixed[:, 0], fixed[:, 1]] = v2[~g2]
            bad = bad[g2]
    # Cap on the exact-repair remainder: a FRACTION of the tile, not a
    # flat count — a 256^2 frame at a deep Misiurewicz span legitimately
    # leaves ~10% of its pixels doubly-glitched (measured: 6272/65536 at
    # span ~1e-13, budget 20000, every candidate exterior), and the old
    # flat 4096 cap killed such renders outright.  Beyond a quarter of
    # the tile, perturbation genuinely isn't working for this view.
    cap = (max_glitch_fix if max_glitch_fix is not None
           else max(4096, (spec.width * spec.height) // 4))
    if len(bad) > cap:
        raise ValueError(
            f"{len(bad)} doubly-glitched pixels (> {cap}); "
            f"no reference orbit suits this view")
    # Exact per-pixel recompute in fixed point for the remainder —
    # batched through the native kernel (threaded in C++) when
    # available.  Pixel coordinates are center + delta, formed in fixed
    # point so no precision is lost.  (On the smooth plane this patches
    # an *integer* count — a one-level banding artifact on isolated
    # pixels; the second-reference pass above patches with true smooth
    # values.)
    if len(bad):
        pts = []
        for r, c in bad:
            d_re = float((c - (spec.width - 1) / 2) * step)
            d_im = float((r - (spec.height - 1) / 2) * step)
            pts.append((za + _to_fixed(d_re, bits),
                        zb + _to_fixed(d_im, bits)))
        jc = None if julia_c is None else (ca, cb)
        out[bad[:, 0], bad[:, 1]] = _escape_counts_exact_batch(
            pts, max_iter, bits, jc)
    return out, n_flagged


def compute_counts_perturb(spec: DeepTileSpec, max_iter: int, *,
                           dtype=np.float32,
                           prec_bits: int = DEFAULT_PREC_BITS,
                           max_glitch_fix: int | None = None,
                           julia_c: tuple[str, str] | None = None,
                           bla: bool | None = None
                           ) -> tuple[np.ndarray, int]:
    """Escape counts for a deep-zoom tile via perturbation.

    Returns ``(counts, n_glitched)``: int32 (height, width) counts in
    the reference convention, and how many pixels the primary reference
    FLAGGED as glitched (most are repaired on device by the secondary-
    reference pass; only the doubly-glitched remainder pays the exact
    fixed-point fallback).  Raises if more than ``max_glitch_fix``
    pixels remain glitched against both references — default: a quarter
    of the tile (deep boundary views legitimately leave ~10% doubly
    glitched; beyond 25% perturbation is not working for the view).

    ``julia_c=(re, im)`` (decimal strings) renders the Julia set for
    that constant instead — the spec's center then names a z-plane
    location.

    The delta dtype defaults to f32: deltas live at pixel scale, so the
    precision of the *view location* comes from the bigint reference
    orbit, not the device dtype (see :func:`_compute_perturb` for the
    span floors and precision widening).

    ``bla`` selects the tile-granular bilinear-approximation fast path
    (ops/bla.py) — far fewer device iterations at giant budgets in
    exchange for a documented approximation (late escape/glitch
    detection at skip boundaries).  ``True``/``False`` force the
    choice; the default ``None`` probes the view (see ``_auto_bla``)
    and enables BLA only where slow bounded dynamics make it win.
    """
    if max_iter <= 1:
        return np.zeros((spec.height, spec.width), np.int32), 0
    add_dc = julia_c is None

    def scan(zr, zi, dre, dim):
        return _perturb_scan_fetch(zr, zi, dre, dim, max_iter=max_iter,
                                   add_dc=add_dc)

    def repair_scan(zr, zi, dre, dim):
        # The secondary repair pass scans exactly the bounded lanes the
        # stagnation stop would re-flag — it must run stagnation-free
        # or the pass is always wasted (round-4 review finding).
        return _perturb_scan_fetch(zr, zi, dre, dim, max_iter=max_iter,
                                   add_dc=add_dc, stagnation=False)

    def factory(z_re, z_im, dc_max):
        from distributedmandelbrot_tpu.ops.bla import bla_scan_factory
        return bla_scan_factory(z_re, z_im, dc_max,
                                max_iter=max_iter, dtype=dtype,
                                add_dc=add_dc)

    return _compute_perturb(spec, max_iter, scan, dtype=dtype,
                            prec_bits=prec_bits,
                            max_glitch_fix=max_glitch_fix,
                            julia_c=julia_c, scan_factory=factory,
                            repair_scan_fn=repair_scan, bla=bla)


def _escape_count_fixed(za: int, zb: int, max_iter: int, bits: int,
                        ca: int | None = None,
                        cb: int | None = None) -> int:
    """Reference convention exactly (DistributedMandelbrotWorkerCUDA.py:
    44-68): z starts at ``(za, zb)``, each iteration updates THEN tests,
    counts 1..max_iter-1, 0 = never escaped.  ``(ca, cb)`` is the
    additive constant — defaults to the start point (Mandelbrot); pass
    it separately for the Julia family."""
    if ca is None:
        ca, cb = za, zb
    if _native_fixed(bits, za, zb, ca, cb):
        from distributedmandelbrot_tpu.native import bindings

        return bindings.fixed_escape(za, zb, ca, cb, max_iter, bits)
    one = 1 << bits
    four = 4 * one * one
    a, b = za, zb
    a2, b2 = a * a, b * b
    for it in range(1, max_iter):
        a, b = (a2 - b2 >> bits) + ca, ((a * b) >> (bits - 1)) + cb
        a2, b2 = a * a, b * b
        if a2 + b2 >= four:
            return it
    return 0


def compute_tile_perturb(spec: DeepTileSpec, max_iter: int, *,
                         dtype=np.float32,
                         prec_bits: int = DEFAULT_PREC_BITS,
                         clamp: bool = False,
                         julia_c: tuple[str, str] | None = None
                         ) -> np.ndarray:
    """Deep-zoom tile -> flat uint8 pixels (canonical scaling/order)."""
    from distributedmandelbrot_tpu.ops.escape_time import (
        scale_counts_to_uint8)

    counts, _ = compute_counts_perturb(spec, max_iter, dtype=dtype,
                                       prec_bits=prec_bits,
                                       julia_c=julia_c)
    pixels = scale_counts_to_uint8(jnp.asarray(counts), max_iter=max_iter,
                                   clamp=clamp)
    return np.asarray(pixels).ravel()


# -- smooth (band-free) coloring ------------------------------------------


@partial(jax.jit, static_argnames=("max_iter", "bailout", "add_dc"))
def _perturb_scan_smooth(z_re, z_im, dc_re, dc_im, *, max_iter: int,
                         bailout: float, add_dc: bool = True):
    """Smooth twin of :func:`_perturb_scan`: additionally freezes the
    full value at the first radius-``bailout`` crossing, from which the
    renormalized iteration count is recovered (the delta keeps iterating
    select-free; only the frozen full value is load-bearing).  Returns
    ``(nu, glitched)`` with the same conventions as
    :func:`~distributedmandelbrot_tpu.ops.escape_time.escape_smooth`:
    0 = in-set (radius-2 budget exhausted), else the continuous count.
    """
    dtype = jnp.result_type(dc_re)
    orbit_len = z_re.shape[0]
    shape = dc_re.shape
    four = jnp.asarray(4.0, dtype)
    b2 = jnp.asarray(bailout * bailout, dtype)
    tol = jnp.asarray(GLITCH_TOL, dtype)

    def step(carry, zs):
        dzr, dzi, act_b, n, act2, n2, fzr, fzi, glitched = carry
        zr, zi = zs
        fr = zr + dzr
        fi = zi + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zr * zr + zi * zi
        glitched = glitched | (act2 & (mag2 < tol * zmag2))
        newly = act_b & (mag2 >= b2)
        fzr = jnp.where(newly, fr, fzr)
        fzi = jnp.where(newly, fi, fzi)
        act_b = act_b & (mag2 < b2)
        n = n + act_b.astype(jnp.int32)
        # Radius-2 count runs alongside so in-set classification matches
        # the integer path exactly (sticky, like escape_smooth's).
        act2 = act2 & (mag2 < four)
        n2 = n2 + act2.astype(jnp.int32)
        ndzr = (zr + zr) * dzr - (zi + zi) * dzi + (dzr * dzr - dzi * dzi)
        ndzi = (zr + zr) * dzi + (zi + zi) * dzr + 2 * dzr * dzi
        if add_dc:
            ndzr = ndzr + dc_re
            ndzi = ndzi + dc_im
        return (ndzr, ndzi, act_b, n, act2, n2, fzr, fzi, glitched), None

    ones = jnp.ones(shape, jnp.bool_)
    zeros_i = jnp.zeros(shape, jnp.int32)
    init = (dc_re.astype(dtype), dc_im.astype(dtype), ones, zeros_i,
            ones, zeros_i, jnp.full(shape, bailout, dtype),
            jnp.zeros(shape, dtype), jnp.zeros(shape, jnp.bool_))
    # Live signal: the union of both sticky masks, so the exit is
    # correct for ANY bailout (for the standard bailout >= 2, act2 is a
    # subset of act_b and the union degenerates to act_b; for exotic
    # bailout < 2 the radius-2 count can outlive the bailout mask and
    # must keep the loop alive).
    # NO stagnation stop here (round-4 review finding): a stagnant-but-
    # eventually-escaping lane diverted to the exact repair would come
    # back as an INTEGER count — the repair cannot produce smooth nu —
    # so the stop would trade exact smooth values for banding.  The
    # smooth plane keeps the plain whole-chunk early exit.
    dzr, dzi, act_b, n, act2, n2, fzr, fzi, glitched = \
        _segmented_orbit_scan(step, init, z_re.astype(dtype),
                              z_im.astype(dtype),
                              lambda c: jnp.any(c[2] | c[4]))

    if orbit_len < max_iter:
        glitched = glitched | act2
    # Scan-n counts passed radius-bailout tests over z_1..: one more than
    # escape_smooth's update-counting n, hence the +1 (its formula adds
    # +2).  Laggards that crossed radius 2 but not the smoothing radius
    # within the orbit get the same log_ratio >= 1 clamp.
    mag2 = jnp.maximum(fzr * fzr + fzi * fzi, b2)
    log_ratio = jnp.log(mag2) / jnp.asarray(2.0 * np.log(bailout), dtype)
    nu = (n + 1).astype(dtype) - jnp.log2(log_ratio)
    nu = jnp.where(n2 >= max_iter, jnp.zeros((), dtype), nu)
    return nu, glitched


def compute_smooth_perturb(spec: DeepTileSpec, max_iter: int, *,
                           dtype=np.float32,
                           prec_bits: int = DEFAULT_PREC_BITS,
                           bailout: float = 256.0,
                           max_glitch_fix: int | None = None,
                           julia_c: tuple[str, str] | None = None,
                           bla: bool | None = None
                           ) -> tuple[np.ndarray, int]:
    """Smooth (band-free) deep-zoom values via perturbation.

    Returns ``(nu, n_glitched)``: float (height, width) renormalized
    counts (0 = in-set), and how many pixels the primary reference
    flagged as glitched.  Most are repaired on device with TRUE smooth
    values by the secondary-reference pass; only pixels glitched
    against both references are patched with their *integer* count from
    the exact fixed-point fallback (a one-level banding artifact on
    those isolated pixels — acceptable, since the alternative is
    arbitrary-precision log arithmetic).

    ``bla=True``: the tile-granular bilinear-approximation fast path
    (ops/bla.py) — the table's ``z_cap`` guard keeps every frozen
    smoothing value exact; escape/glitch timing carries the documented
    skip-boundary contract.
    """
    if max_iter <= 1:
        return np.zeros((spec.height, spec.width), dtype), 0
    add_dc = julia_c is None

    def scan(zr, zi, dre, dim):
        return _perturb_scan_smooth_fetch(zr, zi, dre, dim,
                                          max_iter=max_iter,
                                          bailout=float(bailout),
                                          add_dc=add_dc)

    def factory(z_re, z_im, dc_max):
        from distributedmandelbrot_tpu.ops.bla import bla_smooth_scan_factory
        return bla_smooth_scan_factory(z_re, z_im, dc_max,
                                       max_iter=max_iter,
                                       bailout=float(bailout),
                                       dtype=dtype, add_dc=add_dc)

    return _compute_perturb(spec, max_iter, scan, dtype=dtype,
                            prec_bits=prec_bits,
                            max_glitch_fix=max_glitch_fix,
                            julia_c=julia_c, scan_factory=factory,
                            bla=bla)
