"""Compute kernels: numpy golden reference, JAX/XLA escape time, Pallas,
perturbation deep zoom."""

from distributedmandelbrot_tpu.ops import reference
from distributedmandelbrot_tpu.ops.escape_time import (DEFAULT_SEGMENT,
                                                       compute_tile,
                                                       compute_tile_julia,
                                                       compute_tile_smooth,
                                                       escape_counts,
                                                       escape_counts_julia,
                                                       escape_smooth,
                                                       escape_smooth_julia,
                                                       scale_counts_to_uint8)
from distributedmandelbrot_tpu.ops.families import (
    compute_tile_family, compute_tile_smooth_family, escape_counts_family,
    escape_smooth_family)
from distributedmandelbrot_tpu.ops.perturbation import (DeepTileSpec,
                                                        compute_counts_perturb,
                                                        compute_smooth_perturb,
                                                        compute_tile_perturb)

__all__ = ["reference", "DEFAULT_SEGMENT", "compute_tile",
           "compute_tile_julia", "compute_tile_smooth", "escape_counts",
           "escape_counts_julia", "escape_smooth", "escape_smooth_julia",
           "scale_counts_to_uint8", "compute_tile_family",
           "compute_tile_smooth_family", "escape_counts_family",
           "escape_smooth_family", "DeepTileSpec", "compute_counts_perturb",
           "compute_smooth_perturb", "compute_tile_perturb"]
