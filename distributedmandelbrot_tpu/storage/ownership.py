"""Per-level ownership locks for a data directory.

The reference keeps a process-global claimed-levels set so two
Distributers can never serve the same level (``Distributer.cs:14,109-115``)
— but that guard lives in one process's memory.  Here coordinators are
independent processes that may be pointed at the same data directory, so
the claim is an OS-level ``flock`` on a per-level file inside ``Data/``:
a second coordinator claiming an overlapping level fails loudly at
startup instead of silently duplicating work and index entries.

``flock`` rather than pid files: the kernel drops the lock the instant
the owning process dies, so there is no stale-lock state and no
reclaim logic to race (a pid-file scheme needs read-check-unlink, and
two concurrent claimants reclaiming the same stale file can both
"win").  The lock file itself is never unlinked — unlinking a path
others may be flocking reintroduces exactly that race (lock-by-inode vs
claim-by-path).  The owning pid is written into the file purely for the
error message.  Caveat: flock is advisory and historically unreliable
on NFS; the data dir is expected to be a local filesystem (the
reference makes the same assumption for its index file locking).
"""

from __future__ import annotations

import errno
import fcntl
import logging
import os

logger = logging.getLogger("dmtpu.storage")


class LevelOwnedError(RuntimeError):
    """Another live coordinator already owns one of the requested levels."""


def _lock_path(data_dir: str, level: int, namespace: str = "") -> str:
    return os.path.join(data_dir, f"_level_{level}{namespace}.lock")


class LevelClaims:
    """Holds flocks on the coordinator's level files; release() on stop.

    ``namespace`` scopes the claim to one ring shard: N sharded
    coordinators legitimately share every level of one data directory
    (each owning a disjoint keyspace slice), so each claims
    ``_level_<n>-sKofN.lock`` — exclusive against a restarted self,
    not against its peers or against differently-sharded launches.
    """

    def __init__(self, data_dir: str, levels: list[int], *,
                 namespace: str = "") -> None:
        self.data_dir = data_dir
        self.namespace = namespace
        self._fds: dict[int, int] = {}
        try:
            for level in levels:
                self._claim_one(level)
        except BaseException:
            self.release()
            raise

    def _claim_one(self, level: int) -> None:
        path = _lock_path(self.data_dir, level, self.namespace)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            owner = self._read_owner(fd)
            os.close(fd)
            if e.errno not in (errno.EACCES, errno.EAGAIN):
                raise
            raise LevelOwnedError(
                f"level {level} is already owned by a live coordinator"
                + (f" (pid {owner})" if owner else "")
                + f" — lock {path}; two coordinators on one data "
                "directory would duplicate work and index entries"
            ) from None
        # Register BEFORE the diagnostic pid write: if the write failed
        # (e.g. disk full) with the fd unregistered, release() could
        # never drop the flock and the level would stay locked for the
        # life of this process.  Ownership is the flock, not the content.
        self._fds[level] = fd
        try:
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
        except OSError:
            logger.warning("could not record pid in %s (lock still held)",
                           path, exc_info=True)

    @staticmethod
    def _read_owner(fd: int) -> int | None:
        try:
            data = os.pread(fd, 64, 0)
            pid = int(data.decode().strip())
            return pid if pid > 0 else None
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        """Drop every held flock (idempotent; the files stay behind —
        see the module docstring for why they are never unlinked)."""
        for level, fd in list(self._fds.items()):
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass
            try:
                os.close(fd)
            except OSError:
                pass
            del self._fds[level]
