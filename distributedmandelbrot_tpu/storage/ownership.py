"""Per-level ownership locks for a data directory.

The reference keeps a process-global claimed-levels set so two
Distributers can never serve the same level (``Distributer.cs:14,109-115``)
— but that guard lives in one process's memory.  Here coordinators are
independent processes that may be pointed at the same data directory, so
the claim is a lock *file* per level inside ``Data/``: a second
coordinator claiming an overlapping level fails loudly at startup instead
of silently duplicating work and index entries.

Lock files are ``_level_<n>.lock`` containing the owner's pid.  A lock
whose pid is no longer alive is stale (crashed coordinator — the
reference's in-memory set has the same semantics: claims die with the
process) and is reclaimed.  Claims are released on clean shutdown.
"""

from __future__ import annotations

import errno
import logging
import os

logger = logging.getLogger("dmtpu.storage")


class LevelOwnedError(RuntimeError):
    """Another live coordinator already owns one of the requested levels."""


def _lock_path(data_dir: str, level: int) -> str:
    return os.path.join(data_dir, f"_level_{level}.lock")


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


class LevelClaims:
    """Holds the lock files for a coordinator's levels; release() on stop."""

    def __init__(self, data_dir: str, levels: list[int]) -> None:
        self.data_dir = data_dir
        self._held: list[int] = []
        try:
            for level in levels:
                self._claim_one(level)
        except BaseException:
            self.release()
            raise

    def _claim_one(self, level: int, retried: bool = False) -> None:
        # Atomic publish: the lock is materialized via os.link from a
        # fully-written temp file, so it is never visible without its
        # owner pid — a concurrent claimant can't race the pid write and
        # misread a half-created lock as stale (classic TOCTOU).
        path = _lock_path(self.data_dir, level)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(str(os.getpid()))
        try:
            try:
                os.link(tmp, path)
            except OSError as e:
                if e.errno != errno.EEXIST:
                    raise
                owner = self._read_owner(path)
                if owner is None or _pid_alive(owner):
                    # Live owner — or unreadable content, which a correct
                    # claimant can never produce (atomic publish above):
                    # treat foreign junk as contested, never reclaim it.
                    raise LevelOwnedError(
                        f"level {level} is already owned by "
                        + (f"a live coordinator (pid {owner}, "
                           if owner is not None else "an unreadable claim (")
                        + f"lock {path}); two coordinators on one data "
                        "directory would duplicate work and index entries"
                    ) from None
                # Stale lock: the owning pid is gone (crashed coordinator).
                if retried:
                    raise LevelOwnedError(
                        f"cannot reclaim contested lock {path}") from None
                logger.info("reclaiming stale level lock %s (pid %s)", path,
                            owner)
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                self._claim_one(level, retried=True)
                return
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        self._held.append(level)

    @staticmethod
    def _read_owner(path: str) -> int | None:
        """The claiming pid, or None when the file is unreadable or holds
        anything but a positive integer (callers treat None as contested,
        not stale — see _claim_one)."""
        try:
            with open(path) as f:
                pid = int(f.read().strip())
            return pid if pid > 0 else None
        except FileNotFoundError:
            # Vanished between EEXIST and the read: the other claimant
            # reclaimed a stale lock — report as a dead owner so our
            # retry path re-races the os.link cleanly.
            return -1
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        """Unlink every held lock (idempotent; best-effort on errors)."""
        for level in self._held:
            try:
                os.unlink(_lock_path(self.data_dir, level))
            except OSError:
                pass
        self._held = []
