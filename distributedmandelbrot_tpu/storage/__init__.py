"""Durable persistence: append-only tile index + codec'd chunk blobs
over pluggable backends (local files or an object-store layout)."""

from distributedmandelbrot_tpu.storage.backends import (DirObjectStore,
                                                        LocalFileBackend,
                                                        MemoryObjectStore,
                                                        ObjectStore,
                                                        ObjectStoreBackend,
                                                        StoreBackend)
from distributedmandelbrot_tpu.storage.index import (CorruptIndexError,
                                                     EntryType, IndexEntry,
                                                     read_entry, scan_entries)
from distributedmandelbrot_tpu.storage.store import (DATA_DIR_NAME,
                                                     INDEX_FILENAME,
                                                     ChunkStore,
                                                     DataDirError)

__all__ = ["CorruptIndexError", "EntryType", "IndexEntry", "read_entry",
           "scan_entries", "ChunkStore", "DataDirError", "DATA_DIR_NAME",
           "INDEX_FILENAME", "StoreBackend", "LocalFileBackend",
           "ObjectStore", "ObjectStoreBackend", "MemoryObjectStore",
           "DirObjectStore"]
