"""Durable persistence: append-only tile index + codec'd chunk files."""

from distributedmandelbrot_tpu.storage.index import (CorruptIndexError,
                                                     EntryType, IndexEntry,
                                                     read_entry, scan_entries)
from distributedmandelbrot_tpu.storage.store import (DATA_DIR_NAME,
                                                     INDEX_FILENAME,
                                                     ChunkStore)

__all__ = ["CorruptIndexError", "EntryType", "IndexEntry", "read_entry",
           "scan_entries", "ChunkStore", "DATA_DIR_NAME", "INDEX_FILENAME"]
