"""Append-only tile index: the system's durable checkpoint.

Every accepted tile is recorded by appending an entry to ``_index.dat``; on
restart the coordinator replays the index to rebuild its completed set, so
the index *is* the resume mechanism (reference: ``DataStorage.cs:10-13,
358-387,187-225``; resume seeding ``Distributer.cs:165-175``).

Entry wire format (byte-compatible with the reference — note the comment in
the reference claims the type is uint8 but the code writes **int32 LE**;
the code is the truth, ``DataStorage.cs:205-206,373-374``):

    level:u32 LE | index_real:u32 LE | index_imag:u32 LE | type:i32 LE
    [ if type == Regular: filename_len:i32 LE | filename:ASCII ]

Entry types: ``Regular`` (pixels live in a chunk file), ``Never`` (all
pixels 0 — tile entirely in-set), ``Immediate`` (all pixels 1).  The
special types collapse a 16 MiB tile to a tag.

Durability fix over the reference: entries are written with a single
``write`` call (not field-by-field) and optionally fsync'd, and the scan
treats a *trailing* torn entry as end-of-log (recoverable) rather than
corrupting the whole index — only a malformed interior entry raises.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import BinaryIO, Iterator, Optional

_FIXED = struct.Struct("<IIIi")
_LEN = struct.Struct("<i")

MAX_FILENAME_LEN = 4096  # sanity bound; real filenames are ~20 chars


class EntryType(enum.IntEnum):
    REGULAR = 0
    NEVER = 1
    IMMEDIATE = 2


class CorruptIndexError(Exception):
    """An interior index entry is malformed (not a recoverable torn tail)."""


@dataclass(frozen=True)
class IndexEntry:
    level: int
    index_real: int
    index_imag: int
    type: EntryType
    filename: Optional[str] = None

    def __post_init__(self) -> None:
        if self.type == EntryType.REGULAR and not self.filename:
            raise ValueError("Regular index entries require a filename")
        if self.type != EntryType.REGULAR and self.filename:
            raise ValueError(f"{self.type.name} entries carry no filename")

    @property
    def key(self) -> tuple[int, int, int]:
        return (self.level, self.index_real, self.index_imag)

    def to_bytes(self) -> bytes:
        head = _FIXED.pack(self.level, self.index_real, self.index_imag,
                           int(self.type))
        if self.type != EntryType.REGULAR:
            return head
        name = self.filename.encode("ascii")
        return head + _LEN.pack(len(name)) + name


class TornEntry(Exception):
    """Internal: entry truncated at end of stream (torn append)."""


def _read_exact(f: BinaryIO, n: int) -> bytes:
    data = f.read(n)
    if len(data) == 0 and n > 0:
        raise EOFError
    if len(data) < n:
        raise TornEntry
    return data


def read_entry(f: BinaryIO) -> IndexEntry:
    """Read one entry at the current stream position.

    Raises ``EOFError`` at a clean end, ``TornEntry`` on a truncated tail,
    ``CorruptIndexError`` on malformed content.
    """
    head = _read_exact(f, _FIXED.size)
    level, index_real, index_imag, type_raw = _FIXED.unpack(head)
    try:
        etype = EntryType(type_raw)
    except ValueError:
        raise CorruptIndexError(
            f"unknown index entry type {type_raw}") from None
    if etype != EntryType.REGULAR:
        return IndexEntry(level, index_real, index_imag, etype)
    try:
        (name_len,) = _LEN.unpack(_read_exact(f, _LEN.size))
    except EOFError:
        raise TornEntry from None
    if not (0 < name_len <= MAX_FILENAME_LEN):
        raise CorruptIndexError(f"implausible filename length {name_len}")
    try:
        name = _read_exact(f, name_len)
    except EOFError:
        raise TornEntry from None
    try:
        filename = name.decode("ascii")
    except UnicodeDecodeError:
        raise CorruptIndexError("non-ASCII filename in index") from None
    return IndexEntry(level, index_real, index_imag, etype, filename)


def scan_entries(f: BinaryIO, *, tolerate_torn_tail: bool = True
                 ) -> Iterator[IndexEntry]:
    """Yield all entries in an index stream.

    A truncated final entry (torn append from a crash mid-write) ends the
    scan cleanly when ``tolerate_torn_tail`` — the preceding entries are
    all durable.  Malformed interior content always raises
    :class:`CorruptIndexError`.
    """
    while True:
        try:
            yield read_entry(f)
        except EOFError:
            return
        except TornEntry:
            # A short read on a regular file only happens at EOF, so a torn
            # entry is by construction the tail.
            if tolerate_torn_tail:
                return
            raise CorruptIndexError("truncated entry at end of index") from None
