"""Chunk store: data directory + append-only index + codec'd chunk files.

Capabilities mirrored from the reference (``DataStorage.cs``), instance-based
rather than process-global so tests and multi-store coordinators compose:

- ``Data/`` directory with ``_index.dat`` created on demand
  (``DataStorage.cs:131-144``)
- chunk files named ``level;re;im`` with a numeric suffix on collision
  (``DataStorage.cs:392-405``)
- ``save()`` appends an index entry, then writes the chunk file for Regular
  chunks (``DataStorage.cs:410-427``); Never/Immediate chunks are tag-only
- ``load()``/``load_many()`` scan the index and synthesize Never/Immediate
  chunks in memory (``DataStorage.cs:256-292,86-118``); with duplicate
  entries the *last* (most recent append) wins
- ``completed_keys()`` replays the index for resume seeding
  (``Distributer.cs:165-175``)

Fixes over the reference (survey caveats): one lock serializes index
appends AND the per-file guard is a real mutex (the reference's
check-then-add spin-wait races, ``DataStorage.cs:158-162,337-341``);
optional fsync for the index; a serialized-payload LRU so the read path
doesn't decode + re-encode a chunk per request (the reference re-serializes
every fetch, ``DataServer.cs:204-221``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Iterable, Optional

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.storage.index import (EntryType, IndexEntry,
                                                     scan_entries)

if TYPE_CHECKING:
    from distributedmandelbrot_tpu.obs.metrics import Registry

INDEX_FILENAME = "_index.dat"
DATA_DIR_NAME = "Data"


class DataDirError(OSError):
    """The data directory cannot be created or written (clean CLI error;
    reference: the pre-start writability probe, ``Program.cs:159-176``)."""


class ChunkStore:
    """Durable chunk storage rooted at ``parent_dir/Data/``."""

    def __init__(self, parent_dir: str = "", *, fsync_index: bool = False,
                 payload_cache_size: int = 64,
                 registry: Optional["Registry"] = None) -> None:
        # Optional latency telemetry (store_read/write_seconds); None
        # keeps the store dependency-free for scripts and tests.
        self._registry = registry
        self.data_dir = os.path.join(parent_dir, DATA_DIR_NAME)
        self.index_path = os.path.join(self.data_dir, INDEX_FILENAME)
        self._fsync_index = fsync_index
        self._index_lock = threading.Lock()
        self._file_locks: dict[str, threading.Lock] = {}
        self._file_locks_guard = threading.Lock()
        self._payload_cache: OrderedDict[tuple[int, int, int], bytes] = \
            OrderedDict()
        self._payload_cache_size = payload_cache_size
        self._cache_lock = threading.Lock()
        self.setup()

    # -- directory / bookkeeping ------------------------------------------

    def setup(self) -> None:
        """Create the data directory and an empty index if absent.

        Probes writability the way the reference does before starting
        (``Program.cs:159-176`` writes and deletes a test file) and
        raises :class:`DataDirError` with a clean message instead of
        letting a raw OSError traceback surface from the CLI.
        """
        try:
            os.makedirs(self.data_dir, exist_ok=True)
        except (OSError, ValueError) as e:
            # NotADirectoryError/FileExistsError: the path (or a parent)
            # is occupied by a file; PermissionError: unwritable parent.
            raise DataDirError(
                f"cannot create data directory {self.data_dir!r}: "
                f"{e}") from e
        probe = os.path.join(self.data_dir,
                             f"_writable_probe_{os.getpid()}.tmp")
        try:
            with open(probe, "wb") as f:
                f.write(b"probe")
            os.unlink(probe)
        except OSError as e:
            raise DataDirError(
                f"data directory {self.data_dir!r} is not writable: "
                f"{e}") from e
        with self._index_lock:
            if not os.path.exists(self.index_path):
                with open(self.index_path, "wb"):
                    pass

    def _chunk_path(self, filename: str) -> str:
        return os.path.join(self.data_dir, filename)

    def _file_lock(self, filename: str) -> threading.Lock:
        with self._file_locks_guard:
            return self._file_locks.setdefault(filename, threading.Lock())

    def _generate_filename(self, chunk: Chunk) -> str:
        base = f"{chunk.level};{chunk.index_real};{chunk.index_imag}"
        if not os.path.exists(self._chunk_path(base)):
            return base
        suffix = 0
        while os.path.exists(self._chunk_path(base + str(suffix))):
            suffix += 1
        return base + str(suffix)

    # -- write path -------------------------------------------------------

    def save(self, chunk: Chunk) -> IndexEntry:
        """Persist a chunk: write its file (if Regular), then its index entry.

        The file is written *before* the index entry so a crash between the
        two leaves an orphaned data file (harmless) rather than an index
        entry pointing at nothing — the reverse of the reference's order,
        which can break resume.
        """
        t0 = time.monotonic()
        if chunk.is_never:
            entry = IndexEntry(*chunk.key, EntryType.NEVER)
        elif chunk.is_immediate:
            entry = IndexEntry(*chunk.key, EntryType.IMMEDIATE)
        else:
            filename = self._generate_filename(chunk)
            payload = chunk.serialize()
            with self._file_lock(filename):
                tmp = self._chunk_path(filename) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(payload)
                os.replace(tmp, self._chunk_path(filename))
            entry = IndexEntry(*chunk.key, EntryType.REGULAR, filename)
            self._cache_payload(chunk.key, payload)

        with self._index_lock:
            with open(self.index_path, "ab") as f:
                f.write(entry.to_bytes())
                f.flush()
                if self._fsync_index:
                    os.fsync(f.fileno())
        if self._registry is not None:
            self._registry.observe(obs_names.HIST_STORE_WRITE_SECONDS,
                                   time.monotonic() - t0)
        return entry

    # -- read path --------------------------------------------------------

    def entries(self) -> list[IndexEntry]:
        with self._index_lock:
            with open(self.index_path, "rb") as f:
                return list(scan_entries(f))

    def completed_keys(self, levels: Optional[Iterable[int]] = None
                       ) -> set[tuple[int, int, int]]:
        """Replay the index into a set of completed tile keys (resume path)."""
        level_filter = set(levels) if levels is not None else None
        keys: set[tuple[int, int, int]] = set()
        for entry in self.entries():
            if level_filter is None or entry.level in level_filter:
                keys.add(entry.key)
        return keys

    def load_many(self, keys: list[tuple[int, int, int]]
                  ) -> list[Optional[Chunk]]:
        """Load several chunks in one index scan; None where absent."""
        wanted = {key: i for i, key in enumerate(keys)}
        found: dict[tuple[int, int, int], IndexEntry] = {}
        for entry in self.entries():
            if entry.key in wanted:
                found[entry.key] = entry  # last entry wins
        out: list[Optional[Chunk]] = [None] * len(keys)
        for key, entry in found.items():
            out[wanted[key]] = self._entry_to_chunk(entry)
        return out

    def load(self, level: int, index_real: int, index_imag: int
             ) -> Optional[Chunk]:
        return self.load_many([(level, index_real, index_imag)])[0]

    def load_payload(self, level: int, index_real: int, index_imag: int
                     ) -> Optional[bytes]:
        """Serialized payload (code byte + body) for a chunk, LRU-cached.

        This is what the read-side server sends; caching skips the
        decode/re-encode round trip per request.
        """
        key = (level, index_real, index_imag)
        with self._cache_lock:
            if key in self._payload_cache:
                self._payload_cache.move_to_end(key)
                return self._payload_cache[key]
        # Only the miss path is timed: it is the index scan + file read +
        # re-encode an operator tunes the payload LRU to avoid.
        t0 = time.monotonic()
        chunk = self.load(level, index_real, index_imag)
        if chunk is not None and self._registry is not None:
            self._registry.observe(obs_names.HIST_STORE_READ_SECONDS,
                                   time.monotonic() - t0)
        if chunk is None:
            return None
        payload = chunk.serialize()
        self._cache_payload(key, payload)
        return payload

    def _cache_payload(self, key: tuple[int, int, int],
                       payload: bytes) -> None:
        if self._payload_cache_size <= 0:
            return
        with self._cache_lock:
            self._payload_cache[key] = payload
            self._payload_cache.move_to_end(key)
            while len(self._payload_cache) > self._payload_cache_size:
                self._payload_cache.popitem(last=False)

    def _entry_to_chunk(self, entry: IndexEntry) -> Chunk:
        if entry.type == EntryType.NEVER:
            return Chunk.never(*entry.key)
        if entry.type == EntryType.IMMEDIATE:
            return Chunk.immediate(*entry.key)
        with self._file_lock(entry.filename):
            with open(self._chunk_path(entry.filename), "rb") as f:
                payload = f.read()
        data = Chunk.deserialize_data(payload)
        if data.size != CHUNK_PIXELS:
            raise ValueError(
                f"chunk file {entry.filename} decodes to {data.size} pixels")
        return Chunk(*entry.key, data)


def compact(parent_dir: str = "", *, remove_orphans: bool = True,
            fsync: bool = True) -> dict:
    """Rewrite ``Data/_index.dat`` with one (last-wins) entry per tile
    and optionally delete chunk files no surviving entry references.

    The reference's index is append-only by design (``DataStorage.cs``
    has no compaction; duplicate entries accumulate on re-saves and old
    chunk-file versions linger via collision suffixing) — fine for a
    run, unbounded for a long-lived farm.  Offline maintenance:

    - claims EVERY level present in the index via the flock ownership
      locks, so running against a live coordinator fails loudly instead
      of racing its appends;
    - last entry per tile key wins (the store's own read rule);
    - the new index is written to a temp file and atomically renamed,
      with the directory fsynced, so a crash leaves either the old or
      the new index — never a torn one;
    - orphan removal only touches files matching the chunk-name pattern
      ``level;re;im[suffix]`` for tiles the index knows, never foreign
      files.

    Returns a stats dict: entries before/after, orphans removed, bytes
    reclaimed from the index.
    """
    import re as _re

    from distributedmandelbrot_tpu.storage.ownership import LevelClaims

    probe = os.path.join(parent_dir, DATA_DIR_NAME, INDEX_FILENAME)
    if not os.path.exists(probe):
        # A maintenance command must not scaffold a farm out of a typo'd
        # path (ChunkStore.setup would create Data/ and an empty index,
        # masking the mistake as 'compacted: 0 -> 0').
        raise DataDirError(f"no tile index at {probe!r}; nothing to "
                           "compact (check -o)")
    store = ChunkStore(parent_dir)
    # Size BEFORE reading entries: an append landing between the two is
    # then included in the final-size comparison (conservative abort),
    # never silently dropped by the rewrite.
    size_at_read = os.path.getsize(store.index_path)
    entries = store.entries()
    if not entries:
        # Nothing to compact — and rewriting an empty index beside a
        # just-started coordinator (no entries yet, so no levels to
        # claim) could drop its first concurrent append.
        return {"entries_before": 0, "entries_after": 0,
                "orphans_removed": 0,
                "index_bytes": os.path.getsize(store.index_path)}
    levels = sorted({e.level for e in entries})
    claims = LevelClaims(store.data_dir, levels)
    try:
        last: dict[tuple[int, int, int], IndexEntry] = {}
        for e in entries:
            last[e.key] = e
        kept = [last[k] for k in sorted(last)]
        tmp = store.index_path + ".compact"
        with open(tmp, "wb") as f:
            for e in kept:
                f.write(e.to_bytes())
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        # The level claims exclude coordinators serving the levels we
        # read; a coordinator serving a level NOT yet in the index could
        # still append concurrently.  Last-moment growth check narrows
        # that window to microseconds and fails loudly instead of
        # silently dropping the newcomer's entries.
        if os.path.getsize(store.index_path) != size_at_read:
            os.unlink(tmp)
            raise RuntimeError(
                "index grew during compaction; a coordinator appears to "
                "be running on this data directory — stop it first")
        os.replace(tmp, store.index_path)
        if fsync:
            dir_fd = os.open(store.data_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

        removed = 0
        if remove_orphans:
            referenced = {e.filename for e in kept if e.filename}
            # Chunk files are all-digit 'level;re;im[suffix]' names (the
            # suffix is indistinguishable from trailing index digits);
            # '.tmp' leftovers are saves that crashed before their
            # rename — safe to sweep under the level claims.
            pat = _re.compile(r"^\d+;\d+;\d+(\.tmp)?$")
            for name in os.listdir(store.data_dir):
                if name in referenced or not pat.match(name):
                    continue
                try:
                    os.unlink(os.path.join(store.data_dir, name))
                    removed += 1
                except OSError:
                    pass
        before = len(entries)
        return {"entries_before": before, "entries_after": len(kept),
                "orphans_removed": removed,
                "index_bytes": os.path.getsize(store.index_path)}
    finally:
        claims.release()
