"""Chunk store: append-only index + codec'd chunk blobs over a backend.

Capabilities mirrored from the reference (``DataStorage.cs``), instance-based
rather than process-global so tests and multi-store coordinators compose:

- ``Data/`` directory with ``_index.dat`` created on demand
  (``DataStorage.cs:131-144``)
- chunk files named ``level;re;im`` with a numeric suffix on collision
  (``DataStorage.cs:392-405``)
- ``save()`` appends an index entry, then writes the chunk file for Regular
  chunks (``DataStorage.cs:410-427``); Never/Immediate chunks are tag-only
- ``load()``/``load_many()`` scan the index and synthesize Never/Immediate
  chunks in memory (``DataStorage.cs:256-292,86-118``); with duplicate
  entries the *last* (most recent append) wins
- ``completed_keys()`` replays the index for resume seeding
  (``Distributer.cs:165-175``)

Fixes over the reference (survey caveats): one lock serializes index
appends AND the per-file guard is a real mutex (the reference's
check-then-add spin-wait races, ``DataStorage.cs:158-162,337-341``);
optional fsync for the index; a serialized-payload LRU so the read path
doesn't decode + re-encode a chunk per request (the reference re-serializes
every fetch, ``DataServer.cs:204-221``).

Where the bytes live is a :class:`~distributedmandelbrot_tpu.storage
.backends.StoreBackend`: the default :class:`LocalFileBackend` keeps the
reference's exact on-disk layout, while :class:`ObjectStoreBackend` maps
the same index + blobs onto object-store primitives.  This module owns
every policy above the backend — entry format, filenames, caching,
torn-tail repair — so the two layouts behave identically.

Durability details this layer owns:

- startup **torn-tail repair**: a crash mid-append leaves a truncated
  final entry; appending after it (``"ab"``) would bury the tear as
  *interior* corruption, so setup scans to the last valid entry boundary
  and truncates the tail before any post-restart append;
- **logical index offsets**: :meth:`ChunkStore.index_offset` /
  :meth:`ChunkStore.entries_from` let the coordinator checkpoint a
  high-water mark and replay only the suffix on restore;
- armed **crash points** (``utils/faults.py``) at the save path's two
  nasty interleavings, so the recovery tests can die exactly between the
  blob write and the index append.
"""

from __future__ import annotations

import io
import logging
import threading
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Iterable, Optional

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.storage.backends import (DATA_DIR_NAME,
                                                        INDEX_FILENAME,
                                                        DataDirError,
                                                        LocalFileBackend,
                                                        StoreBackend)
from distributedmandelbrot_tpu.storage.index import (CorruptIndexError,
                                                     EntryType, IndexEntry,
                                                     TornEntry, read_entry,
                                                     scan_entries)
from distributedmandelbrot_tpu.utils import faults

if TYPE_CHECKING:
    from distributedmandelbrot_tpu.obs.metrics import Registry

__all__ = ["ChunkStore", "DataDirError", "compact", "DATA_DIR_NAME",
           "INDEX_FILENAME"]

logger = logging.getLogger("dmtpu.store")


class ChunkStore:
    """Durable chunk storage over a backend (default: ``parent_dir/Data/``)."""

    def __init__(self, parent_dir: str = "", *, fsync_index: bool = False,
                 payload_cache_size: int = 64,
                 registry: Optional["Registry"] = None,
                 backend: Optional[StoreBackend] = None,
                 namespace: str = "") -> None:
        # Optional latency telemetry (store_read/write_seconds); None
        # keeps the store dependency-free for scripts and tests.
        self._registry = registry
        # ``namespace`` gives one coordinator shard a private index log
        # inside a data dir shared with its peers; a caller supplying
        # its own backend namespaces it there instead.
        self.backend = backend if backend is not None \
            else LocalFileBackend(parent_dir, namespace=namespace)
        # Path attributes exist only for the local layout (ownership
        # flocks, offline compaction); object-store layouts have neither.
        self.data_dir = getattr(self.backend, "data_dir", None)
        self.index_path = getattr(self.backend, "index_path", None)
        self._fsync_index = fsync_index
        self._index_lock = threading.Lock()
        self._file_locks: dict[str, threading.Lock] = {}
        self._file_locks_guard = threading.Lock()
        self._payload_cache: OrderedDict[tuple[int, int, int], bytes] = \
            OrderedDict()
        self._payload_cache_size = payload_cache_size
        self._cache_lock = threading.Lock()
        self.setup()

    # -- directory / bookkeeping ------------------------------------------

    def setup(self) -> None:
        """Create the backing location, then repair any torn index tail.

        Backend setup probes writability the way the reference does
        before starting (``Program.cs:159-176``) and raises
        :class:`DataDirError` with a clean message instead of letting a
        raw OSError traceback surface from the CLI.  The tail repair
        must run before the first post-restart append: the index opens
        in append mode, so writing after a crash-torn final entry would
        turn it from a recoverable truncated tail into interior
        corruption on the next scan.
        """
        self.backend.setup()
        with self._index_lock:
            self._repair_index_tail()

    def _repair_index_tail(self) -> None:
        """Truncate the index to its last valid entry boundary (lock held)."""
        data = self.backend.read_index()
        size = len(data)
        f = io.BytesIO(data)
        valid = 0
        while True:
            try:
                read_entry(f)
            except EOFError:
                break  # clean end: valid == size
            except TornEntry:
                break  # crash-torn tail: truncate past `valid`
            except CorruptIndexError:
                # Interior corruption is not repairable; keep the bytes
                # for forensics and let entries() raise loudly, exactly
                # as an unrepaired store would.
                return
            valid = f.tell()
        if valid < size:
            self.backend.truncate_index(valid)
            logger.warning(
                "repaired torn index tail: truncated %d trailing bytes "
                "(crash mid-append); %d valid bytes kept", size - valid,
                valid)
            if self._registry is not None:
                self._registry.inc(obs_names.STORE_TORN_TAILS_REPAIRED)

    def _file_lock(self, filename: str) -> threading.Lock:
        with self._file_locks_guard:
            return self._file_locks.setdefault(filename, threading.Lock())

    def _generate_filename(self, chunk: Chunk) -> str:
        base = f"{chunk.level};{chunk.index_real};{chunk.index_imag}"
        if not self.backend.blob_exists(base):
            return base
        suffix = 0
        while self.backend.blob_exists(base + str(suffix)):
            suffix += 1
        return base + str(suffix)

    # -- write path -------------------------------------------------------

    def save(self, chunk: Chunk) -> IndexEntry:
        """Persist a chunk: write its blob (if Regular), then its index entry.

        The blob is written *before* the index entry so a crash between
        the two leaves an orphaned data blob (harmless) rather than an
        index entry pointing at nothing — the reverse of the reference's
        order, which can break resume.
        """
        t0 = time.monotonic()
        if chunk.is_never:
            entry = IndexEntry(*chunk.key, EntryType.NEVER)
        elif chunk.is_immediate:
            entry = IndexEntry(*chunk.key, EntryType.IMMEDIATE)
        else:
            filename = self._generate_filename(chunk)
            payload = chunk.serialize()
            with self._file_lock(filename):
                faults.hit("store.before_chunk_write")
                self.backend.put_blob(filename, payload)
            faults.hit("store.after_chunk_write")
            entry = IndexEntry(*chunk.key, EntryType.REGULAR, filename)
            self._cache_payload(chunk.key, payload)

        with self._index_lock:
            self.backend.append_index(entry.to_bytes(),
                                      fsync=self._fsync_index)
        # Outside the lock: the entry is already durable, and a slowpoint
        # here must not stall every other writer's append.
        faults.hit("store.after_index_append")
        if self._registry is not None:
            self._registry.observe(obs_names.HIST_STORE_WRITE_SECONDS,
                                   time.monotonic() - t0)
        return entry

    def put_many(self, chunks: list[Chunk]) -> list[IndexEntry]:
        """Group-commit a batch: blob writes first, then ONE index append.

        Same ordering guarantee as :meth:`save` (blobs before index, so a
        crash orphans blobs rather than dangling entries), but the index
        entries for the whole batch are concatenated into a single
        ``append_index`` call — one write + one optional fsync per batch,
        and a single atomic commit point: a crash before the append loses
        the whole batch's entries (tiles are re-granted), never a torn
        subset interleaved with other writers.
        """
        if not chunks:
            return []
        t0 = time.monotonic()
        entries: list[IndexEntry] = []
        for chunk in chunks:
            if chunk.is_never:
                entries.append(IndexEntry(*chunk.key, EntryType.NEVER))
            elif chunk.is_immediate:
                entries.append(IndexEntry(*chunk.key, EntryType.IMMEDIATE))
            else:
                filename = self._generate_filename(chunk)
                payload = chunk.serialize()
                with self._file_lock(filename):
                    faults.hit("store.before_chunk_write")
                    self.backend.put_blob(filename, payload)
                faults.hit("store.after_chunk_write")
                entries.append(
                    IndexEntry(*chunk.key, EntryType.REGULAR, filename))
                self._cache_payload(chunk.key, payload)
        with self._index_lock:
            self.backend.append_index(
                b"".join(e.to_bytes() for e in entries),
                fsync=self._fsync_index)
        faults.hit("store.after_index_append")  # see save(): post-commit
        if self._registry is not None:
            self._registry.observe(obs_names.HIST_STORE_WRITE_SECONDS,
                                   time.monotonic() - t0)
            self._registry.inc(obs_names.STORE_GROUP_COMMITS)
            self._registry.inc(obs_names.STORE_FLUSH_TILES, len(entries))
        return entries

    # -- read path --------------------------------------------------------

    def entries(self) -> list[IndexEntry]:
        with self._index_lock:
            data = self.backend.read_index()
        return list(scan_entries(io.BytesIO(data)))

    def index_offset(self) -> int:
        """Logical end offset of the index — the replay high-water mark a
        checkpoint records so restore can scan only the suffix."""
        with self._index_lock:
            return self.backend.index_size()

    def entries_from(self, offset: int) -> list[IndexEntry]:
        """Entries wholly past logical ``offset`` (the checkpointed
        prefix is already accounted; only the suffix needs replaying)."""
        with self._index_lock:
            data = self.backend.read_index(offset)
        return list(scan_entries(io.BytesIO(data)))

    def completed_keys(self, levels: Optional[Iterable[int]] = None
                       ) -> set[tuple[int, int, int]]:
        """Replay the index into a set of completed tile keys (resume path)."""
        level_filter = set(levels) if levels is not None else None
        keys: set[tuple[int, int, int]] = set()
        for entry in self.entries():
            if level_filter is None or entry.level in level_filter:
                keys.add(entry.key)
        return keys

    def load_many(self, keys: list[tuple[int, int, int]]
                  ) -> list[Optional[Chunk]]:
        """Load several chunks in one index scan; None where absent."""
        wanted = {key: i for i, key in enumerate(keys)}
        found: dict[tuple[int, int, int], IndexEntry] = {}
        for entry in self.entries():
            if entry.key in wanted:
                found[entry.key] = entry  # last entry wins
        out: list[Optional[Chunk]] = [None] * len(keys)
        for key, entry in found.items():
            out[wanted[key]] = self._entry_to_chunk(entry)
        return out

    def load(self, level: int, index_real: int, index_imag: int
             ) -> Optional[Chunk]:
        return self.load_many([(level, index_real, index_imag)])[0]

    def load_payload(self, level: int, index_real: int, index_imag: int
                     ) -> Optional[bytes]:
        """Serialized payload (code byte + body) for a chunk, LRU-cached.

        This is what the read-side server sends; caching skips the
        decode/re-encode round trip per request.
        """
        key = (level, index_real, index_imag)
        with self._cache_lock:
            if key in self._payload_cache:
                self._payload_cache.move_to_end(key)
                return self._payload_cache[key]
        # Only the miss path is timed: it is the index scan + file read +
        # re-encode an operator tunes the payload LRU to avoid.
        t0 = time.monotonic()
        chunk = self.load(level, index_real, index_imag)
        if chunk is not None and self._registry is not None:
            self._registry.observe(obs_names.HIST_STORE_READ_SECONDS,
                                   time.monotonic() - t0)
        if chunk is None:
            return None
        payload = chunk.serialize()
        self._cache_payload(key, payload)
        return payload

    def _cache_payload(self, key: tuple[int, int, int],
                       payload: bytes) -> None:
        if self._payload_cache_size <= 0:
            return
        with self._cache_lock:
            self._payload_cache[key] = payload
            self._payload_cache.move_to_end(key)
            while len(self._payload_cache) > self._payload_cache_size:
                self._payload_cache.popitem(last=False)

    def _entry_to_chunk(self, entry: IndexEntry) -> Chunk:
        if entry.type == EntryType.NEVER:
            return Chunk.never(*entry.key)
        if entry.type == EntryType.IMMEDIATE:
            return Chunk.immediate(*entry.key)
        with self._file_lock(entry.filename):
            payload = self.backend.get_blob(entry.filename)
        if payload is None:
            raise FileNotFoundError(
                f"chunk blob {entry.filename!r} referenced by the index "
                f"is missing from {self.backend.describe()}")
        data = Chunk.deserialize_data(payload)
        if data.size != CHUNK_PIXELS:
            raise ValueError(
                f"chunk file {entry.filename} decodes to {data.size} pixels")
        return Chunk(*entry.key, data)


def compact(parent_dir: str = "", *, remove_orphans: bool = True,
            fsync: bool = True) -> dict:
    """Rewrite ``Data/_index.dat`` with one (last-wins) entry per tile
    and optionally delete chunk files no surviving entry references.

    The reference's index is append-only by design (``DataStorage.cs``
    has no compaction; duplicate entries accumulate on re-saves and old
    chunk-file versions linger via collision suffixing) — fine for a
    run, unbounded for a long-lived farm.  Offline maintenance over the
    local-file layout (object-store layouts rotate their own segments):

    - claims EVERY level present in the index via the flock ownership
      locks, so running against a live coordinator fails loudly instead
      of racing its appends;
    - last entry per tile key wins (the store's own read rule);
    - the new index is written to a temp file and atomically renamed,
      with the directory fsynced, so a crash leaves either the old or
      the new index — never a torn one;
    - orphan removal only touches files matching the chunk-name pattern
      ``level;re;im[suffix]`` for tiles the index knows, never foreign
      files.

    Returns a stats dict: entries before/after, orphans removed, bytes
    reclaimed from the index.
    """
    import os
    import re as _re

    from distributedmandelbrot_tpu.storage.ownership import LevelClaims

    probe = os.path.join(parent_dir, DATA_DIR_NAME, INDEX_FILENAME)
    if not os.path.exists(probe):
        # A maintenance command must not scaffold a farm out of a typo'd
        # path (ChunkStore.setup would create Data/ and an empty index,
        # masking the mistake as 'compacted: 0 -> 0').
        raise DataDirError(f"no tile index at {probe!r}; nothing to "
                           "compact (check -o)")
    store = ChunkStore(parent_dir)
    # Size BEFORE reading entries: an append landing between the two is
    # then included in the final-size comparison (conservative abort),
    # never silently dropped by the rewrite.
    size_at_read = os.path.getsize(store.index_path)
    entries = store.entries()
    if not entries:
        # Nothing to compact — and rewriting an empty index beside a
        # just-started coordinator (no entries yet, so no levels to
        # claim) could drop its first concurrent append.
        return {"entries_before": 0, "entries_after": 0,
                "orphans_removed": 0,
                "index_bytes": os.path.getsize(store.index_path)}
    levels = sorted({e.level for e in entries})
    claims = LevelClaims(store.data_dir, levels)
    try:
        last: dict[tuple[int, int, int], IndexEntry] = {}
        for e in entries:
            last[e.key] = e
        kept = [last[k] for k in sorted(last)]
        tmp = store.index_path + ".compact"
        with open(tmp, "wb") as f:
            for e in kept:
                f.write(e.to_bytes())
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        # The level claims exclude coordinators serving the levels we
        # read; a coordinator serving a level NOT yet in the index could
        # still append concurrently.  Last-moment growth check narrows
        # that window to microseconds and fails loudly instead of
        # silently dropping the newcomer's entries.
        if os.path.getsize(store.index_path) != size_at_read:
            os.unlink(tmp)
            raise RuntimeError(
                "index grew during compaction; a coordinator appears to "
                "be running on this data directory — stop it first")
        os.replace(tmp, store.index_path)
        if fsync:
            dir_fd = os.open(store.data_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

        removed = 0
        if remove_orphans:
            referenced = {e.filename for e in kept if e.filename}
            # Chunk files are all-digit 'level;re;im[suffix]' names (the
            # suffix is indistinguishable from trailing index digits);
            # '.tmp' leftovers are saves that crashed before their
            # rename — safe to sweep under the level claims.
            pat = _re.compile(r"^\d+;\d+;\d+(\.tmp)?$")
            for name in os.listdir(store.data_dir):
                if name in referenced or not pat.match(name):
                    continue
                try:
                    os.unlink(os.path.join(store.data_dir, name))
                    removed += 1
                except OSError:
                    pass
        before = len(entries)
        return {"entries_before": before, "entries_after": len(kept),
                "orphans_removed": removed,
                "index_bytes": os.path.getsize(store.index_path)}
    finally:
        claims.release()
