"""Pluggable store backends: where the index log and chunk blobs live.

The store's durable state is exactly two things — an append-only index
log (the resume mechanism, ``storage/index.py``) and a namespace of
immutable chunk blobs — so that is the whole backend interface:
:class:`StoreBackend` is append-log segment ops plus blob put/get/list,
and :class:`~distributedmandelbrot_tpu.storage.store.ChunkStore` holds
every policy above it (entry format, filenames, caching, locking).

Two layouts:

- :class:`LocalFileBackend` — byte-compatible with the layout the
  reference wrote (``DataStorage.cs``): ``Data/_index.dat`` plus
  ``level;re;im`` chunk files beside it.  A data directory written by
  any earlier build reads back unchanged.
- :class:`ObjectStoreBackend` — an object-store-shaped layout for the
  deployment the Julia-to-Cloud-TPU paper assumes (no rename, no
  append, atomic single-key PUT): a flat immutable keyspace under
  ``blobs/``, the index as rotated log segments under ``index/``
  (one small tail object per append, periodically merged into sealed
  segments), and an atomic ``index/manifest`` JSON naming the sealed
  segments in order.  Every operation maps 1:1 onto GCS/S3 primitives
  (PUT / GET / LIST / DELETE); the bundled :class:`MemoryObjectStore`
  and :class:`DirObjectStore` fakes back it for tests and benches.

Logical index offsets: both backends address the log by a cumulative
byte offset in read order, so a checkpoint can record a high-water mark
and a restore can replay only the suffix past it regardless of how the
bytes are physically segmented.
"""

from __future__ import annotations

import abc
import json
import os
import threading
from typing import Optional

INDEX_FILENAME = "_index.dat"
DATA_DIR_NAME = "Data"


class DataDirError(OSError):
    """The backing location cannot be created or written (clean CLI error;
    reference: the pre-start writability probe, ``Program.cs:159-176``)."""


class StoreBackend(abc.ABC):
    """Durable home of one store: an append log plus immutable blobs."""

    # -- lifecycle --------------------------------------------------------

    @abc.abstractmethod
    def setup(self) -> None:
        """Create the backing location and probe writability.

        Raises :class:`DataDirError` on an uncreatable/unwritable home.
        """

    @abc.abstractmethod
    def describe(self) -> str:
        """Human-readable location (logs, error messages)."""

    # -- append log (the tile index) --------------------------------------

    @abc.abstractmethod
    def append_index(self, data: bytes, *, fsync: bool = False) -> int:
        """Append ``data`` to the log; returns the end offset after it."""

    @abc.abstractmethod
    def index_size(self) -> int:
        """Current logical size of the log in bytes."""

    @abc.abstractmethod
    def read_index(self, offset: int = 0) -> bytes:
        """The log's bytes from logical ``offset`` to its end."""

    @abc.abstractmethod
    def truncate_index(self, size: int) -> None:
        """Discard log bytes past logical ``size`` (torn-tail repair)."""

    # -- immutable blobs (chunk payloads, checkpoints) --------------------

    @abc.abstractmethod
    def put_blob(self, name: str, data: bytes, *, fsync: bool = False
                 ) -> None:
        """Durably write ``name`` in one atomic step (PUT semantics)."""

    @abc.abstractmethod
    def get_blob(self, name: str) -> Optional[bytes]:
        """Blob contents, or None when absent."""

    @abc.abstractmethod
    def blob_exists(self, name: str) -> bool: ...

    @abc.abstractmethod
    def list_blobs(self) -> list[str]: ...

    def peek_blob(self, name: str, n: int) -> Optional[bytes]:
        """First ``n`` bytes of a blob (header sniffing), or None."""
        data = self.get_blob(name)
        return None if data is None else data[:n]


# -- local files (the reference's layout) ---------------------------------


class LocalFileBackend(StoreBackend):
    """``parent_dir/Data/`` with ``_index.dat`` + chunk files beside it.

    Byte-compatible with the layout every earlier build (and the C#
    reference) wrote: same directory, same index file, blobs are plain
    files named by the caller.  Blob puts go through a same-directory
    temp file and ``os.replace`` so a reader never sees a half-written
    chunk and a crash leaves at worst a ``.tmp`` orphan.
    """

    def __init__(self, parent_dir: str = "", *, namespace: str = "") -> None:
        # ``namespace`` isolates the append log of one coordinator shard
        # sharing the directory with others (``_index-s0of4.dat``); the
        # blob namespace stays shared — ring ownership is disjoint, so
        # shards never write the same chunk name.
        self.data_dir = os.path.join(parent_dir, DATA_DIR_NAME)
        self.namespace = namespace
        index_name = INDEX_FILENAME if not namespace else \
            f"_index{namespace}.dat"
        self.index_path = os.path.join(self.data_dir, index_name)

    def describe(self) -> str:
        return self.data_dir

    def _path(self, name: str) -> str:
        return os.path.join(self.data_dir, name)

    def setup(self) -> None:
        try:
            os.makedirs(self.data_dir, exist_ok=True)
        except (OSError, ValueError) as e:
            # NotADirectoryError/FileExistsError: the path (or a parent)
            # is occupied by a file; PermissionError: unwritable parent.
            raise DataDirError(
                f"cannot create data directory {self.data_dir!r}: "
                f"{e}") from e
        probe = os.path.join(self.data_dir,
                             f"_writable_probe_{os.getpid()}.tmp")
        try:
            with open(probe, "wb") as f:
                f.write(b"probe")
            os.unlink(probe)
        except OSError as e:
            raise DataDirError(
                f"data directory {self.data_dir!r} is not writable: "
                f"{e}") from e
        if not os.path.exists(self.index_path):
            with open(self.index_path, "wb"):
                pass

    # -- append log -------------------------------------------------------

    def append_index(self, data: bytes, *, fsync: bool = False) -> int:
        with open(self.index_path, "ab") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
            return f.tell()

    def index_size(self) -> int:
        return os.path.getsize(self.index_path)

    def read_index(self, offset: int = 0) -> bytes:
        with open(self.index_path, "rb") as f:
            if offset:
                f.seek(offset)
            return f.read()

    def truncate_index(self, size: int) -> None:
        with open(self.index_path, "r+b") as f:
            f.truncate(size)
            os.fsync(f.fileno())

    # -- blobs ------------------------------------------------------------

    def put_blob(self, name: str, data: bytes, *, fsync: bool = False
                 ) -> None:
        tmp = self._path(name) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self._path(name))

    def get_blob(self, name: str) -> Optional[bytes]:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def peek_blob(self, name: str, n: int) -> Optional[bytes]:
        try:
            with open(self._path(name), "rb") as f:
                return f.read(n)
        except FileNotFoundError:
            return None

    def blob_exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))

    def list_blobs(self) -> list[str]:
        # Every per-shard index log is backend-internal, like
        # INDEX_FILENAME itself — never a blob.
        return sorted(
            name for name in os.listdir(self.data_dir)
            if not name.startswith("_index") and not name.endswith(".tmp"))


# -- object-store kv fakes ------------------------------------------------


class ObjectStore(abc.ABC):
    """The five primitives GCS/S3 give you: atomic PUT, GET, HEAD-ish
    size, LIST-by-prefix, DELETE.  No append, no rename — everything the
    :class:`ObjectStoreBackend` layout is built around."""

    @abc.abstractmethod
    def put(self, key: str, data: bytes, *, fsync: bool = False) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def size(self, key: str) -> Optional[int]: ...

    @abc.abstractmethod
    def list(self, prefix: str = "") -> list[str]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    def exists(self, key: str) -> bool:
        return self.size(key) is not None

    def describe(self) -> str:
        return type(self).__name__


class MemoryObjectStore(ObjectStore):
    """In-memory kv fake — the unit-test double for a bucket."""

    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes, *, fsync: bool = False) -> None:
        with self._lock:
            self._objects[key] = bytes(data)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(key)

    def size(self, key: str) -> Optional[int]:
        with self._lock:
            data = self._objects.get(key)
            return None if data is None else len(data)

    def list(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._objects if k.startswith(prefix))

    def delete(self, key: str) -> None:
        with self._lock:
            self._objects.pop(key, None)


class DirObjectStore(ObjectStore):
    """Directory-backed kv fake: keys become paths, ``/`` nests.

    PUT is temp-file + ``os.replace`` in the destination directory, so
    every object appears atomically — the invariant the object-store
    layout leans on instead of file appends.
    """

    def __init__(self, root: str) -> None:
        self.root = root

    def describe(self) -> str:
        return self.root

    def _path(self, key: str) -> str:
        # Keys are backend-internal ("index/tail-...", "blobs/4;1;2"):
        # forward slashes nest, nothing may escape the root.
        if key.startswith("/") or ".." in key.split("/"):
            raise ValueError(f"bad object key {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, data: bytes, *, fsync: bool = False) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def size(self, key: str) -> Optional[int]:
        try:
            return os.path.getsize(self._path(key))
        except OSError:
            return None

    def list(self, prefix: str = "") -> list[str]:
        out: list[str] = []
        for dirpath, _dirnames, filenames in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            for name in filenames:
                if name.endswith(".tmp"):
                    continue
                key = name if rel == "." else \
                    "/".join(rel.split(os.sep) + [name])
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


# -- object-store layout --------------------------------------------------

_TAIL_PREFIX = "index/tail-"
_SEG_PREFIX = "index/seg-"
_MANIFEST_KEY = "index/manifest"
_BLOB_PREFIX = "blobs/"
_MANIFEST_FORMAT = 1


class ObjectStoreBackend(StoreBackend):
    """Index log + blobs over five object-store primitives.

    Layout (flat keyspace, every object immutable once read):

    - ``blobs/<name>`` — chunk payloads and checkpoints, one PUT each;
    - ``index/tail-<seq>`` — one object per index append (object stores
      cannot append, so the log's tail is a run of tiny objects);
    - ``index/seg-<n>`` — sealed segments: every ``rotate_threshold``
      appends the tail run is merged into one segment object;
    - ``index/manifest`` — JSON naming the sealed segments in order plus
      the tail floor, PUT atomically *after* its segment exists, so a
      crash mid-rotation leaves the previous manifest + the still-live
      tail objects — never a torn log.

    Readers order the log as manifest segments then tail objects with
    ``seq > tail_floor`` (discovered by LIST); merged tails are deleted
    only after the manifest lands, so rotation is crash-safe at every
    step.  Logical offsets are cumulative bytes in that read order.
    """

    def __init__(self, kv: ObjectStore, *, rotate_threshold: int = 256,
                 namespace: str = "") -> None:
        if rotate_threshold < 1:
            raise ValueError("rotate_threshold must be >= 1")
        self.kv = kv
        self.rotate_threshold = rotate_threshold
        # ``namespace`` isolates one shard's index log in a shared
        # bucket (``index-s0of4/...``); blobs stay shared — ring
        # ownership is disjoint, so shards never write the same name.
        self.namespace = namespace
        self._tail_prefix = f"index{namespace}/tail-"
        self._seg_prefix = f"index{namespace}/seg-"
        self._manifest_key = f"index{namespace}/manifest"
        # Re-entrant: append_index rotates and setup loads under the
        # lock, and both helpers take it again for their own mutations.
        self._lock = threading.RLock()
        self._sealed: list[tuple[str, int]] = []  # (key, size), log order
        self._sealed_bytes = 0
        self._tails: list[tuple[int, int]] = []  # (seq, size), log order
        self._tail_floor = 0  # highest seq merged into a sealed segment
        self._next_seq = 1

    def describe(self) -> str:
        return f"object-store:{self.kv.describe()}"

    def _tail_key(self, seq: int) -> str:
        return f"{self._tail_prefix}{seq:012d}"

    def setup(self) -> None:
        probe_key = f"meta/_writable_probe_{os.getpid()}"
        try:
            self.kv.put(probe_key, b"probe")
            self.kv.delete(probe_key)
        except OSError as e:
            raise DataDirError(
                f"object store {self.kv.describe()!r} is not writable: "
                f"{e}") from e
        with self._lock:
            self._load_state()

    def _load_state(self) -> None:
        with self._lock:  # re-entrant under setup()'s hold
            self._sealed = []
            self._sealed_bytes = 0
            self._tail_floor = 0
            raw = self.kv.get(self._manifest_key)
            if raw is not None:
                manifest = json.loads(raw.decode("utf-8"))
                if manifest.get("format") != _MANIFEST_FORMAT:
                    raise DataDirError(
                        f"unsupported index manifest format "
                        f"{manifest.get('format')!r} in "
                        f"{self.kv.describe()!r}")
                self._sealed = [(key, int(size))
                                for key, size in manifest["sealed"]]
                self._sealed_bytes = sum(size for _, size in self._sealed)
                self._tail_floor = int(manifest["tail_floor"])
            self._tails = []
            for key in self.kv.list(self._tail_prefix):
                seq = int(key[len(self._tail_prefix):])
                if seq <= self._tail_floor:
                    continue  # merged into segment; deletion never finished
                size = self.kv.size(key)
                if size is not None:
                    self._tails.append((seq, size))
            self._tails.sort()
            self._next_seq = max([self._tail_floor]
                                 + [seq for seq, _ in self._tails]) + 1

    # -- append log -------------------------------------------------------

    def append_index(self, data: bytes, *, fsync: bool = False) -> int:
        with self._lock:
            seq = self._next_seq
            self.kv.put(self._tail_key(seq), data, fsync=fsync)
            self._next_seq += 1
            self._tails.append((seq, len(data)))
            if len(self._tails) >= self.rotate_threshold:
                self._rotate(fsync=fsync)
            return self._sealed_bytes + sum(s for _, s in self._tails)

    def _rotate(self, *, fsync: bool) -> None:
        """Merge the tail run into one sealed segment (re-entrant under
        append_index's hold)."""
        with self._lock:
            merged = b"".join(
                self.kv.get(self._tail_key(seq)) or b""
                for seq, _ in self._tails)
            seg_key = f"{self._seg_prefix}{len(self._sealed):08d}"
            self.kv.put(seg_key, merged, fsync=fsync)
            sealed = self._sealed + [(seg_key, len(merged))]
            floor = self._tails[-1][0]
            manifest = {"format": _MANIFEST_FORMAT,
                        "sealed": [[k, s] for k, s in sealed],
                        "tail_floor": floor}
            # The manifest PUT is the commit point: before it, readers see
            # the old manifest + live tails; after it, the new segment.
            # Tail deletion is garbage collection — a crash here just
            # leaves objects the floor tells every reader to skip.
            self.kv.put(self._manifest_key,
                        json.dumps(manifest, sort_keys=True).encode("utf-8"),
                        fsync=fsync)
            old_tails = self._tails
            self._sealed = sealed
            self._sealed_bytes += len(merged)
            self._tail_floor = floor
            self._tails = []
            for seq, _ in old_tails:
                self.kv.delete(self._tail_key(seq))

    def index_size(self) -> int:
        with self._lock:
            return self._sealed_bytes + sum(s for _, s in self._tails)

    def read_index(self, offset: int = 0) -> bytes:
        with self._lock:
            pieces = [(key, size) for key, size in self._sealed]
            pieces += [(self._tail_key(seq), size)
                       for seq, size in self._tails]
        out: list[bytes] = []
        skip = offset
        for key, size in pieces:
            if skip >= size:
                skip -= size
                continue
            data = self.kv.get(key)
            if data is None:
                raise DataDirError(
                    f"index object {key!r} vanished from "
                    f"{self.kv.describe()!r}")
            out.append(data[skip:])
            skip = 0
        return b"".join(out)

    def truncate_index(self, size: int) -> None:
        # Object PUTs are atomic, so a torn tail cannot occur in this
        # layout; repair is still honored for interface parity (property
        # tests drive both backends through the same sequences).
        with self._lock:
            if size < self._sealed_bytes:
                raise ValueError(
                    f"cannot truncate into sealed segments "
                    f"({size} < {self._sealed_bytes})")
            keep = size - self._sealed_bytes
            kept: list[tuple[int, int]] = []
            for seq, tail_size in self._tails:
                if keep >= tail_size:
                    kept.append((seq, tail_size))
                    keep -= tail_size
                elif keep > 0:
                    data = self.kv.get(self._tail_key(seq)) or b""
                    self.kv.put(self._tail_key(seq), data[:keep])
                    kept.append((seq, keep))
                    keep = 0
                else:
                    self.kv.delete(self._tail_key(seq))
            self._tails = kept

    # -- blobs ------------------------------------------------------------

    def put_blob(self, name: str, data: bytes, *, fsync: bool = False
                 ) -> None:
        self.kv.put(_BLOB_PREFIX + name, data, fsync=fsync)

    def get_blob(self, name: str) -> Optional[bytes]:
        return self.kv.get(_BLOB_PREFIX + name)

    def blob_exists(self, name: str) -> bool:
        return self.kv.exists(_BLOB_PREFIX + name)

    def list_blobs(self) -> list[str]:
        return [key[len(_BLOB_PREFIX):]
                for key in self.kv.list(_BLOB_PREFIX)]
