import sys

from distributedmandelbrot_tpu.cli import main

sys.exit(main())
