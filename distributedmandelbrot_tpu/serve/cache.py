"""Tier-1 decoded-tile cache in front of the store's byte-payload LRU.

The store already keeps a serialized-payload LRU (:class:`ChunkStore`'s
``load_payload`` cache) so hot reads skip the index scan + file read +
re-encode.  The gateway adds a second, richer tier on top of it: an LRU of
:class:`CachedTile` entries holding the wire payload *and* (lazily) the
decoded pixel array, keyed like the store on ``(level, index_real,
index_imag)``.  A tier-1 hit serves a query with zero store traffic; a
tier-1 miss that the store satisfies *promotes* the payload into tier 1.

Promotion is also where cold raw payloads get one shot at the wire-RLE
win: a payload stored with the Raw codec (legacy raw-only data dirs —
this repo's own save path already picks the smallest codec) runs the
RLE ``estimate_ratio`` heuristic and is re-encoded before it enters the
cache when RLE clearly wins, so every later hit ships the small body.

:class:`RenderedTileCache` is the third tier: colormapped palette-PNG
bodies keyed by ``(level, index_real, index_imag, colormap_id)`` — a hot
rendered tile ships ~50-200 KB instead of the 16 MiB escape payload.

Every movement is counted through :class:`~distributedmandelbrot_tpu.utils.
metrics.Counters` (``tile_cache_hits`` / ``tile_cache_misses`` /
``tile_cache_evictions`` / ``tile_cache_promotions``) so the serving bench
and the load-shed policy can see the cache working.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from distributedmandelbrot_tpu import codecs
from distributedmandelbrot_tpu.codecs.base import RAW_CODE
from distributedmandelbrot_tpu.codecs.rle import estimate_ratio
from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters

Key = tuple[int, int, int]
RenderKey = tuple[int, int, int, int]


class CachedTile:
    """One resident tile: the wire payload, pixels decoded on first use."""

    __slots__ = ("payload", "_pixels", "_decode_lock")

    def __init__(self, payload: bytes) -> None:
        self.payload = payload
        self._pixels: Optional[np.ndarray] = None
        self._decode_lock = threading.Lock()

    @property
    def pixels(self) -> np.ndarray:
        """Decoded flat uint8 pixels, cached after the first decode."""
        with self._decode_lock:
            if self._pixels is None:
                self._pixels = Chunk.deserialize_data(self.payload)
                self._pixels.setflags(write=False)
            return self._pixels


class DecodedTileCache:
    """LRU of :class:`CachedTile` over a :class:`ChunkStore`.

    Thread-safe: the gateway's event loop reads inline while store lookups
    run on worker threads.  ``capacity`` is in tiles (payloads are codec-
    compressed, so byte-exact accounting would punish exactly the cheap
    Never/Immediate tiles worth keeping resident).
    """

    def __init__(self, store: ChunkStore, *, capacity: int = 64,
                 recompress_min_ratio: float = 2.0,
                 counters: Optional[Counters] = None) -> None:
        self.store = store
        self.capacity = capacity
        # Minimum estimated RLE ratio before a cold raw payload is
        # re-encoded on promotion; <= 0 disables the recompression pass.
        self.recompress_min_ratio = recompress_min_ratio
        self.counters = counters if counters is not None else Counters()
        self._entries: OrderedDict[Key, CachedTile] = OrderedDict()
        self._lock = threading.Lock()
        # Live hit-ratio gauges, derived from the movement counters at
        # scrape time (callback gauges — nothing to update per request).
        registry = self.counters.registry

        def _ratio(hit_name: str, miss_name: str) -> float:
            hits = registry.counter_value(hit_name) or 0
            misses = registry.counter_value(miss_name) or 0
            total = hits + misses
            return hits / total if total else 0.0

        registry.gauge(
            obs_names.GAUGE_TIER1_HIT_RATIO,
            help="decoded-tile LRU hits / lookups",
            fn=lambda: _ratio(obs_names.TILE_CACHE_HITS,
                              obs_names.TILE_CACHE_MISSES))
        registry.gauge(
            obs_names.GAUGE_TIER2_HIT_RATIO,
            help="store (payload LRU + disk) hits / tier-1 misses",
            fn=lambda: _ratio(obs_names.TILE_CACHE_PROMOTIONS,
                              obs_names.TILE_CACHE_STORE_MISSES))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- tier 1 (no I/O) --------------------------------------------------

    def get_cached(self, key: Key) -> Optional[CachedTile]:
        """Tier-1 lookup only; never touches the store."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.counters.inc("tile_cache_misses")
                return None
            self._entries.move_to_end(key)
            self.counters.inc("tile_cache_hits")
            return entry

    def contains(self, key: Key) -> bool:
        """Residency peek for planners: no promotion, no LRU bump, and —
        unlike :meth:`get_cached` — no hit/miss accounting, so probing
        does not skew the tier-1 ratio gauge."""
        with self._lock:
            return key in self._entries

    def invalidate(self, key: Key) -> bool:
        """Drop a tile so the next read re-reads the store.

        Called when a deeper-``max_iter`` variant of the tile persists:
        the store's payload LRU is refreshed by the save itself, but an
        entry here would keep serving the stale shallow pixels.  Not a
        miss — nothing was looked up.
        """
        with self._lock:
            if self._entries.pop(key, None) is None:
                return False
        self.counters.inc(obs_names.TILE_CACHE_INVALIDATIONS)
        return True

    def put(self, key: Key, payload: bytes) -> CachedTile:
        """Insert/refresh a tile, evicting LRU entries past capacity."""
        entry = CachedTile(payload)
        if self.capacity <= 0:
            return entry
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.counters.inc("tile_cache_evictions")
        return entry

    # -- tier 1 -> tier 2 (store; blocking I/O) ---------------------------

    def load(self, key: Key) -> Optional[CachedTile]:
        """Tier-1 lookup, falling through to the store (payload LRU, then
        disk) and promoting what it finds.  Blocking — call off-loop."""
        entry = self.get_cached(key)
        if entry is not None:
            return entry
        payload = self.store.load_payload(*key)
        if payload is None:
            self.counters.inc(obs_names.TILE_CACHE_STORE_MISSES)
            return None
        self.counters.inc("tile_cache_promotions")
        return self.put(key, self._maybe_recompress(payload))

    def _maybe_recompress(self, payload: bytes) -> bytes:
        """Re-encode a raw-codec payload to RLE when the estimate says the
        wire win is clear (>= ``recompress_min_ratio``).

        Runs once per promotion, on the store-read thread, so the cost
        (a strided histogram, plus one boundary pass only for plausible
        tiles) is paid off-loop and only on cold fetches.  Payloads this
        repo saved are already pick-smallest encoded; this path is for
        data dirs written by raw-only writers (the reference's early
        builds).
        """
        if self.recompress_min_ratio <= 0 or not payload \
                or payload[0] != RAW_CODE:
            return payload
        pixels = np.frombuffer(payload, dtype=np.uint8, offset=1)
        if estimate_ratio(pixels,
                          self.recompress_min_ratio) < self.recompress_min_ratio:
            self.counters.inc(obs_names.SERVE_RLE_SKIPPED)
            return payload
        body = codecs.RLE.encode(pixels)
        recoded = bytes([codecs.RLE.code]) + body
        if len(recoded) >= len(payload):
            # The estimate was optimistic; keep the bytes we trust.
            self.counters.inc(obs_names.SERVE_RLE_SKIPPED)
            return payload
        self.counters.inc(obs_names.SERVE_RLE_RECOMPRESSIONS)
        self.counters.inc(obs_names.SERVE_RLE_BYTES_SAVED,
                          len(payload) - len(recoded))
        return recoded


class RenderedTileCache:
    """Tier-3 LRU of rendered palette-PNG bodies.

    Keyed by ``(level, index_real, index_imag, colormap_id)`` — the same
    tile rendered under two colormaps is two entries.  Thread-safe like
    the decoded-tile tier (the gateway's loop reads inline while renders
    happen on worker threads); ``capacity`` is in entries, since bodies
    are already deflate-compressed and roughly uniform for a workload.
    """

    def __init__(self, *, capacity: int = 64,
                 counters: Optional[Counters] = None) -> None:
        self.capacity = capacity
        self.counters = counters if counters is not None else Counters()
        self._entries: OrderedDict[RenderKey, bytes] = OrderedDict()
        self._lock = threading.Lock()
        registry = self.counters.registry

        def _hit_ratio() -> float:
            hits = registry.counter_value(
                obs_names.GATEWAY_RENDER_CACHE_HITS) or 0
            misses = registry.counter_value(
                obs_names.GATEWAY_RENDER_CACHE_MISSES) or 0
            total = hits + misses
            return hits / total if total else 0.0

        registry.gauge(obs_names.GAUGE_RENDER_HIT_RATIO,
                       help="rendered-tile LRU hits / lookups",
                       fn=_hit_ratio)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: RenderKey) -> Optional[bytes]:
        with self._lock:
            body = self._entries.get(key)
            if body is None:
                self.counters.inc(obs_names.GATEWAY_RENDER_CACHE_MISSES)
                return None
            self._entries.move_to_end(key)
            self.counters.inc(obs_names.GATEWAY_RENDER_CACHE_HITS)
            return body

    def put(self, key: RenderKey, body: bytes) -> bytes:
        if self.capacity <= 0:
            return body
        with self._lock:
            self._entries[key] = body
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.counters.inc(obs_names.GATEWAY_RENDER_CACHE_EVICTIONS)
        return body

    def invalidate_tile(self, key: Key) -> int:
        """Drop every colormap variant of one tile (a deeper-``max_iter``
        variant persisted; cached PNGs render the stale shallow pixels).
        Returns how many entries went."""
        level, index_real, index_imag = key
        with self._lock:
            stale = [k for k in self._entries
                     if k[0] == level and k[1] == index_real
                     and k[2] == index_imag]
            for k in stale:
                del self._entries[k]
        if stale:
            self.counters.inc(obs_names.GATEWAY_RENDER_CACHE_INVALIDATIONS,
                              len(stale))
        return len(stale)
