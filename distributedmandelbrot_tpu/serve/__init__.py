"""Tile-serving gateway: multi-tier cache, coalescing, compute-on-read.

The read-side subsystem in front of the store/scheduler/worker farm.  See
:mod:`.gateway` for the wire formats and admission-control model.
"""

from distributedmandelbrot_tpu.serve.cache import (CachedTile,
                                                   DecodedTileCache,
                                                   RenderedTileCache)
from distributedmandelbrot_tpu.serve.coalesce import SingleFlight
from distributedmandelbrot_tpu.serve.gateway import TileGateway, TokenBucket
from distributedmandelbrot_tpu.serve.ondemand import OnDemandComputer

__all__ = [
    "CachedTile",
    "DecodedTileCache",
    "RenderedTileCache",
    "SingleFlight",
    "TileGateway",
    "TokenBucket",
    "OnDemandComputer",
]
