"""Compute-on-read: turn a gateway miss into a farm job and await the tile.

A cache/store miss for a tile the run is configured to render does not
have to be a 404: the scheduler already knows how to get it computed.  The
on-demand path pushes the tile to the FRONT of the scheduler's frontier
(:meth:`TileScheduler.prioritize`), so the next worker lease grants it
ahead of the background sweep, then awaits the resulting upload+persist
with a per-request deadline.

Arrival is signalled by the distributer's save path (the coordinator wires
:meth:`notify_saved` into it), with a slow poll of the store as a backstop
for tiles that land through any other route (a second coordinator on the
same data dir, an operator copying files in).

A miss for a tile the scheduler has already marked completed is usually a
save still in flight — but if the store stays empty past one poll window
the bytes are genuinely gone, and the tile is un-completed and re-granted
(:meth:`TileScheduler.refine`) rather than letting every reader time out.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Optional

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.serve.cache import DecodedTileCache
from distributedmandelbrot_tpu.utils.metrics import Counters

if TYPE_CHECKING:  # import would cycle through coordinator.__init__ -> app
    from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler

logger = logging.getLogger("dmtpu.serve")

Key = tuple[int, int, int]


class OnDemandComputer:
    """Awaitable miss->compute->serve bridge between gateway and scheduler."""

    def __init__(self, scheduler: "TileScheduler", cache: DecodedTileCache, *,
                 deadline: float = proto.DEFAULT_ONDEMAND_DEADLINE,
                 poll_interval: float = 1.0,
                 counters: Optional[Counters] = None) -> None:
        self.scheduler = scheduler
        self.cache = cache
        self.deadline = deadline
        self.poll_interval = poll_interval
        self.counters = counters if counters is not None else Counters()
        self._arrivals: dict[Key, asyncio.Event] = {}

    def notify_saved(self, key: Key) -> None:
        """Wake waiters for a freshly persisted tile (coordinator loop)."""
        event = self._arrivals.get(key)
        if event is not None:
            event.set()

    async def compute(self, workload: Workload):
        """Prioritize the tile and await its arrival; the promoted
        :class:`CachedTile` on success, None past the deadline.

        Callers coalesce upstream (``SingleFlight``), so one call here is
        one scheduler injection no matter how many clients are waiting.
        """
        loop = asyncio.get_running_loop()
        t_deadline = loop.time() + self.deadline
        key = workload.key
        event = self._arrivals.get(key)
        if event is None:
            event = self._arrivals[key] = asyncio.Event()
        self.counters.inc("ondemand_requests")
        # Prioritize returns False for out-of-grid keys and for tiles the
        # scheduler already recorded as completed.  The usual completed
        # case is a save still in flight, which lands within a poll; but
        # we only get here after a cache/store miss, so a completed tile
        # that stays missing means the bytes are gone (wiped data dir, a
        # foreign store).  Give the in-flight save one poll window, then
        # heal: un-complete via ``refine`` and re-grant the compute
        # instead of waiting out the whole deadline for nothing.
        heal = not self.scheduler.prioritize(workload)
        if not heal:
            logger.info("on-demand: prioritized %s", workload)
        try:
            while True:
                remaining = t_deadline - loop.time()
                if remaining <= 0:
                    self.counters.inc("ondemand_timeouts")
                    logger.info("on-demand: deadline expired for %s", key)
                    return None
                try:
                    await asyncio.wait_for(
                        event.wait(), min(remaining, self.poll_interval))
                except (TimeoutError, asyncio.TimeoutError):
                    pass  # poll the store below, then keep waiting
                entry = await asyncio.to_thread(self.cache.load, key)
                if entry is not None:
                    self.counters.inc("ondemand_served")
                    return entry
                if heal:
                    heal = False
                    refine = getattr(self.scheduler, "refine", None)
                    if refine is not None and refine(workload):
                        self.counters.inc("ondemand_healed")
                        logger.info(
                            "on-demand: completed tile missing from store,"
                            " re-granted %s", workload)
                # Save notification without a loadable payload (save error
                # reopened the tile, or a spurious wake): re-arm and wait.
                event.clear()
        finally:
            # Callers coalesce upstream, so this compute() owns the entry:
            # drop it (served, timed out, or cancelled) to keep the table
            # bounded; the next miss for the key re-arms a fresh event.
            if self._arrivals.get(key) is event:
                del self._arrivals[key]
