"""Asyncio tile-serving gateway: cache + coalesce + compute-on-read.

The read-side front door for heavy traffic.  Speaks two framings on one
port:

- **Legacy query** — the reference DataServer's 12-byte ``<III`` ``(level,
  index_real, index_imag)`` query, answered with a status byte and, on
  accept, a u32-length-prefixed codec payload.  Existing viewers work
  against the gateway unmodified.
- **Batched query** — a query whose first u32 is
  :data:`~distributedmandelbrot_tpu.net.protocol.GATEWAY_BATCH_MAGIC`
  (an impossible level) is instead ``magic, count, count x 12-byte
  queries``; the reply is ``count`` single-query responses in request
  order.  Items resolve concurrently, so a batch of neighbours rides the
  coalescer and the store's readahead instead of serializing round trips.
- **Rendered query** — first u32 is
  :data:`~distributedmandelbrot_tpu.net.protocol.GATEWAY_RENDER_MAGIC`,
  followed by the 14-byte ``RENDER_QUERY_TAIL``; the accept payload is a
  colormapped palette PNG (:mod:`.render`) instead of the escape-count
  codec body.  A viewer that only displays tiles downloads ~50-200 KB
  instead of 16 MiB, which is what makes million-viewer read fan-out a
  bandwidth problem the gateway can actually win.
- **Session query** — first u32 is
  :data:`~distributedmandelbrot_tpu.net.protocol.GATEWAY_SESSION_MAGIC`,
  followed by the 22-byte ``SESSION_QUERY_TAIL`` (session id + viewport
  + colormap + capability flags); the reply leads with the 9-byte
  ``SESSION_REPLY`` (issued/echoed id, granted caps) before the standard
  status byte + rendered body.  Live only when a
  :class:`~distributedmandelbrot_tpu.sessions.SessionService` is
  attached (duck-typed — the serve layer must not import the sessions
  package, which imports this module): the service tracks each session's
  viewport trajectory for predictive prefetch, serves first paints from
  a cheap low-``max_iter`` variant while the full depth refines in the
  background, and charges a per-session token budget *before* the global
  one so a flash crowd's hot session sheds onto itself, not everyone.

On top of the :class:`DataServer` semantics the gateway adds:

- a tier-1 decoded-tile LRU (:mod:`.cache`) over the store's payload LRU,
- single-flight coalescing (:mod:`.coalesce`) so a stampede on one tile
  costs one store read / one farm compute,
- compute-on-read (:mod:`.ondemand`): a miss for a tile the run is
  configured to render is injected at the scheduler's frontier head and
  the response waits (bounded by a deadline) for the worker upload,
- admission control: a token bucket on request rate plus a cap on
  concurrently serving queries; rejected work gets an explicit
  ``QUERY_OVERLOADED`` byte instead of an unbounded queue.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.trace import TraceLog
from distributedmandelbrot_tpu.serve import render
from distributedmandelbrot_tpu.serve.cache import (DecodedTileCache,
                                                   RenderedTileCache)
from distributedmandelbrot_tpu.serve.coalesce import SingleFlight
from distributedmandelbrot_tpu.serve.ondemand import OnDemandComputer
from distributedmandelbrot_tpu.utils.metrics import Counters

logger = logging.getLogger("dmtpu.gateway")

MAX_BATCH_QUERIES = 4096  # mirrors the distributer's MAX_BATCH bound


class TokenBucket:
    """Classic token bucket; ``rate=None`` (or <= 0) admits everything."""

    def __init__(self, rate: Optional[float], burst: float, *,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = rate
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.rate is None or self.rate <= 0:
            return True
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class TileGateway:
    """The serving front door.  One instance per coordinator event loop.

    ``max_queue_depth`` caps queries in service at once (load shedding);
    ``rate``/``burst`` feed the token bucket.  Both default to permissive
    values — the embedded coordinator's tests dial them down.
    """

    def __init__(self, cache: DecodedTileCache, *,
                 ondemand: Optional[OnDemandComputer] = None,
                 host: str = "0.0.0.0",
                 port: int = proto.DEFAULT_GATEWAY_PORT,
                 read_timeout: Optional[float] = proto.DEFAULT_READ_TIMEOUT,
                 max_queue_depth: int = 1024,
                 rate: Optional[float] = None,
                 burst: float = 256.0,
                 render_cache_tiles: int = 64,
                 counters: Optional[Counters] = None,
                 trace: Optional[TraceLog] = None,
                 ring_slice=None,
                 sessions=None) -> None:
        self.cache = cache
        self.ondemand = ondemand
        # Duck-typed sessions.SessionService (open/touch/note_query/
        # prefetch/first_paint_iter/schedule_refine) — import cycle, see
        # the module docstring.  None answers the session framing with a
        # named reject counter and a dropped connection.
        self.sessions = sessions
        # Duck-typed control.ring.RingSlice (owns/owner_of/version) — the
        # serve layer must not import the control package (cycle).  When
        # set, queries for keys outside this shard's slice are answered
        # with QUERY_REDIRECT + the authoritative shard instead of a read
        # that could only miss.
        self.ring_slice = ring_slice
        self.host = host
        self.port = port
        self.read_timeout = read_timeout
        self.max_queue_depth = max_queue_depth
        self.counters = counters if counters is not None else Counters()
        self.registry = self.counters.registry
        self.trace = trace if trace is not None else TraceLog()
        self.bucket = TokenBucket(rate, burst)
        self.singleflight = SingleFlight(self.counters)
        self.render_cache = RenderedTileCache(capacity=render_cache_tiles,
                                              counters=self.counters)
        # Compute-on-read needs the depth the run renders each level at;
        # the scheduler's work definition is the source of truth.
        self._level_max_iter: dict[int, int] = {}
        if ondemand is not None:
            self._level_max_iter = {
                s.level: s.max_iter
                for s in ondemand.scheduler.level_settings}
        self._active = 0
        self._server: Optional[asyncio.Server] = None
        self._conn_tasks: set[asyncio.Task] = set()
        # Detached prefetch-warming tasks (fire-and-forget off the
        # response path); held so stop() can cancel them.
        self._bg_tasks: set[asyncio.Task] = set()

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("gateway listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # Connections may be parked in an on-demand wait (minutes); cancel
        # them rather than letting wait_closed() (3.12+: waits for all
        # handlers) stall shutdown for the deadline.
        for task in list(self._conn_tasks | self._bg_tasks):
            task.cancel()
        if self._conn_tasks or self._bg_tasks:
            await asyncio.gather(*self._conn_tasks, *self._bg_tasks,
                                 return_exceptions=True)
        flights = self.singleflight.cancel_inflight()
        if flights:
            await asyncio.gather(*flights, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    # -- connection handling ----------------------------------------------

    async def _read(self, coro):
        if self.read_timeout is None:
            return await coro
        return await asyncio.wait_for(coro, self.read_timeout)

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    first = await self._read(framing.read_u32(reader))
                except (ConnectionError, TimeoutError, asyncio.TimeoutError):
                    break  # clean EOF / idle close between queries
                if first == proto.GATEWAY_BATCH_MAGIC:
                    await self._serve_batch(reader, writer)
                elif first == proto.GATEWAY_RENDER_MAGIC:
                    await self._serve_render(reader, writer)
                elif first == proto.GATEWAY_SESSION_MAGIC:
                    await self._serve_session(reader, writer)
                else:
                    rest = await self._read(framing.read_exact(
                        reader, proto.QUERY_TAIL.size))
                    index_real, index_imag = proto.QUERY_TAIL.unpack(rest)
                    status, payload = await self._resolve_admitted(
                        first, index_real, index_imag)
                    self._write_response(writer, status, payload)
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        except framing.ProtocolError as e:
            # Malformed or hostile frame: drop the connection, leave a
            # trail, keep the accept loop alive.
            self.counters.inc(obs_names.GATEWAY_FRAMES_REJECTED)
            logger.error("dropping %s: %s", peer, e)
        except Exception:
            logger.exception("error serving %s", peer)
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _serve_batch(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        count = proto.validate_count(
            await self._read(framing.read_u32(reader)), MAX_BATCH_QUERIES,
            "batch count")
        if count == 0:
            raise framing.ProtocolError("empty batch")
        raw = await self._read(framing.read_exact(
            reader, count * proto.QUERY.size))
        queries = [proto.QUERY.unpack_from(raw, n * proto.QUERY.size)
                   for n in range(count)]
        self.counters.inc("gateway_batches")
        # Resolve concurrently — neighbours coalesce and overlap their
        # store reads — but reply strictly in request order.
        results = await asyncio.gather(
            *(self._resolve_admitted(*q) for q in queries))
        for status, payload in results:
            self._write_response(writer, status, payload)

    async def _serve_render(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        """One rendered-tile exchange: 14-byte tail in, status (+ PNG) out.

        The tail's colormap id and flags are wire-controlled bytes and go
        through the sanctioned validators before anything dereferences
        them; an unknown colormap bumps its own named counter so a fleet
        of version-skewed viewers shows up as a spike, then kills the
        connection like every other validator failure.
        """
        raw = await self._read(framing.read_exact(
            reader, proto.RENDER_QUERY_TAIL.size))
        (level, index_real, index_imag,
         colormap_id, flags) = proto.RENDER_QUERY_TAIL.unpack(raw)
        try:
            proto.validate_colormap(colormap_id)
        except framing.ProtocolError:
            self.counters.inc(obs_names.GATEWAY_RENDER_UNKNOWN_COLORMAP)
            raise
        proto.validate_count(flags, 0, "render flags")
        status, payload = await self._resolve_render(
            level, index_real, index_imag, colormap_id)
        self._write_response(writer, status, payload)

    async def _serve_session(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        """One session-scoped exchange: 22-byte tail in, SESSION_REPLY +
        status (+ PNG) out.

        The tail's colormap and flag bytes are wire-controlled and go
        through the sanctioned validators (each behind its own named
        counter) before anything dereferences them; the session id is
        never more than a dict-key probe.  A gateway without a session
        service kills the connection like a validator failure — the
        client's capability story is "no reply header means no
        sessions", same as a legacy DataServer dropping the magic.
        """
        raw = await self._read(framing.read_exact(
            reader, proto.SESSION_QUERY_TAIL.size))
        (session_id, level, index_real, index_imag,
         colormap_id, flags) = proto.SESSION_QUERY_TAIL.unpack(raw)
        if self.sessions is None:
            self.counters.inc(obs_names.SESSION_UNSUPPORTED)
            raise framing.ProtocolError(
                "session query on a gateway without a session service")
        try:
            proto.validate_colormap(colormap_id)
        except framing.ProtocolError:
            self.counters.inc(obs_names.GATEWAY_RENDER_UNKNOWN_COLORMAP)
            raise
        try:
            proto.validate_session_flags(flags)
        except framing.ProtocolError:
            self.counters.inc(obs_names.SESSION_BAD_FLAGS)
            raise
        sid, caps, status, payload = await self._resolve_session(
            session_id, level, index_real, index_imag, colormap_id, flags)
        writer.write(proto.SESSION_REPLY.pack(sid, caps))
        self._write_response(writer, status, payload)

    def _write_response(self, writer: asyncio.StreamWriter, status: int,
                        payload: Optional[bytes | tuple[int, int]]) -> None:
        framing.write_byte(writer, status)
        if status == proto.QUERY_REDIRECT:
            # Fixed-size REDIRECT tail, no length prefix (net/protocol).
            # Packed here, after the status byte, so source order mirrors
            # wire order for the proto-frames parity check.
            assert isinstance(payload, tuple)
            writer.write(proto.REDIRECT.pack(*payload))
        elif status == proto.QUERY_ACCEPT:
            assert isinstance(payload, bytes)
            framing.write_u32(writer, len(payload))
            writer.write(payload)

    def _redirect_for(self, level: int, index_real: int,
                      index_imag: int) -> Optional[tuple[int, int]]:
        """``(authoritative shard, ring version)`` for a key another
        shard owns, else ``None``."""
        if self.ring_slice is None:
            return None
        key = (level, index_real, index_imag)
        if self.ring_slice.owns(key):
            return None
        self.counters.inc(obs_names.GATEWAY_REDIRECTS)
        return (self.ring_slice.owner_of(key), self.ring_slice.version)

    # -- the serve path ---------------------------------------------------

    async def _resolve_admitted(
            self, level: int, index_real: int,
            index_imag: int) -> tuple[int, Optional[bytes | tuple[int, int]]]:
        """Admission control, then resolve; returns (status, payload).

        One latency histogram (``gateway_request_seconds``) split by an
        ``outcome`` label: tier-1 hit / store hit / computed-on-read /
        unavailable / rejected / overloaded — the split is what makes a
        p99 actionable (a slow p99 of ``computed`` is farm latency; of
        ``store_hit``, disk).
        """
        t0 = time.monotonic()
        status, payload, outcome = await self._resolve_outcome(
            level, index_real, index_imag)
        self.registry.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS,
                              time.monotonic() - t0,
                              labels={"outcome": outcome})
        if status == proto.QUERY_ACCEPT:
            self.trace.record("served", (level, index_real, index_imag))
        return status, payload

    async def _resolve_outcome(
            self, level: int, index_real: int,
            index_imag: int) -> tuple[int, Optional[bytes | tuple[int, int]], str]:
        self.counters.inc("gateway_queries")
        if not proto.query_in_range(level, index_real, index_imag):
            self.counters.inc("gateway_rejected")
            flight.note(obs_events.GW_REJECT,
                        key=(level, index_real, index_imag), path="query")
            return proto.QUERY_REJECT, None, obs_names.OUTCOME_REJECTED
        redirect = self._redirect_for(level, index_real, index_imag)
        if redirect is not None:
            return proto.QUERY_REDIRECT, redirect, obs_names.OUTCOME_REDIRECTED
        # Tier-1 hits are answered before admission: they cost no I/O and
        # no compute, so shedding them would only push load onto retries.
        entry = self.cache.get_cached((level, index_real, index_imag))
        if entry is not None:
            self.counters.inc("gateway_served")
            return proto.QUERY_ACCEPT, entry.payload, obs_names.OUTCOME_TIER1
        if self._active >= self.max_queue_depth or not self.bucket.try_acquire():
            self.counters.inc("gateway_overloaded")
            logger.info("shed query (%d,%d,%d): %d in service",
                        level, index_real, index_imag, self._active)
            flight.note(obs_events.GW_SHED,
                        key=(level, index_real, index_imag), path="query",
                        in_service=self._active)
            return proto.QUERY_OVERLOADED, None, obs_names.OUTCOME_OVERLOADED
        self._active += 1
        try:
            payload, outcome = await self._resolve(level, index_real,
                                                   index_imag)
        finally:
            self._active -= 1
        if payload is None:
            self.counters.inc("gateway_unavailable")
            return (proto.QUERY_NOT_AVAILABLE, None,
                    obs_names.OUTCOME_UNAVAILABLE)
        self.counters.inc("gateway_served")
        return proto.QUERY_ACCEPT, payload, outcome

    # -- the render path --------------------------------------------------

    async def _resolve_render(
            self, level: int, index_real: int, index_imag: int,
            colormap_id: int) -> tuple[int, Optional[bytes | tuple[int, int]]]:
        """Render-path twin of :meth:`_resolve_admitted`: same admission
        gates, same latency histogram (new ``outcome`` values), payload is
        a palette PNG instead of the codec body."""
        t0 = time.monotonic()
        status, payload, outcome = await self._render_outcome(
            level, index_real, index_imag, colormap_id)
        self.registry.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS,
                              time.monotonic() - t0,
                              labels={"outcome": outcome})
        if status == proto.QUERY_ACCEPT:
            self.trace.record(
                "render_served",
                (level, index_real, index_imag, colormap_id))
        return status, payload

    async def _render_outcome(
            self, level: int, index_real: int, index_imag: int,
            colormap_id: int) -> tuple[int, Optional[bytes | tuple[int, int]], str]:
        self.counters.inc(obs_names.GATEWAY_RENDER_QUERIES)
        if not proto.query_in_range(level, index_real, index_imag):
            self.counters.inc("gateway_rejected")
            flight.note(obs_events.GW_REJECT,
                        key=(level, index_real, index_imag), path="render")
            return proto.QUERY_REJECT, None, obs_names.OUTCOME_REJECTED
        redirect = self._redirect_for(level, index_real, index_imag)
        if redirect is not None:
            return proto.QUERY_REDIRECT, redirect, obs_names.OUTCOME_REDIRECTED
        # Like tier-1 raw hits, rendered-cache hits are answered before
        # admission: a hot body is a memcpy, and the render cache is the
        # whole point under flash-crowd load.
        render_key = (level, index_real, index_imag, colormap_id)
        body = self.render_cache.get(render_key)
        if body is not None:
            self.counters.inc(obs_names.GATEWAY_RENDER_SERVED)
            return (proto.QUERY_ACCEPT, body,
                    obs_names.OUTCOME_RENDER_CACHE)
        if self._active >= self.max_queue_depth \
                or not self.bucket.try_acquire():
            self.counters.inc("gateway_overloaded")
            logger.info("shed render (%d,%d,%d): %d in service",
                        level, index_real, index_imag, self._active)
            flight.note(obs_events.GW_SHED,
                        key=(level, index_real, index_imag), path="render",
                        in_service=self._active)
            return proto.QUERY_OVERLOADED, None, obs_names.OUTCOME_OVERLOADED
        self._active += 1
        try:
            body = await self._render(level, index_real, index_imag,
                                      colormap_id)
        finally:
            self._active -= 1
        if body is None:
            self.counters.inc("gateway_unavailable")
            return (proto.QUERY_NOT_AVAILABLE, None,
                    obs_names.OUTCOME_UNAVAILABLE)
        self.counters.inc(obs_names.GATEWAY_RENDER_SERVED)
        return proto.QUERY_ACCEPT, body, obs_names.OUTCOME_RENDERED

    async def _render(self, level: int, index_real: int, index_imag: int,
                      colormap_id: int) -> Optional[bytes]:
        """Resolve the escape payload through the raw serve path (tier-1 /
        store / compute-on-read, coalesced), then colormap + PNG-encode on
        a worker thread.  Single-flight per (tile, colormap): a stampede
        on one hot rendered tile costs one render."""
        max_iter = self._level_max_iter.get(level)
        flight_key = ("render", level, max_iter, index_real, index_imag,
                      colormap_id)

        async def supplier() -> Optional[bytes]:
            payload, _outcome = await self._resolve(level, index_real,
                                                    index_imag)
            if payload is None:
                return None
            t0 = time.monotonic()
            body = await asyncio.to_thread(
                self._render_body, payload, colormap_id)
            self.registry.observe(obs_names.HIST_GATEWAY_RENDER_SECONDS,
                                  time.monotonic() - t0)
            return self.render_cache.put(
                (level, index_real, index_imag, colormap_id), body)

        return await self.singleflight.run(flight_key, supplier)

    def _render_body(self, payload: bytes, colormap_id: int) -> bytes:
        """Blocking decode + render; runs on a worker thread."""
        pixels = Chunk.deserialize_data(payload)
        return render.render_tile_png(pixels,
                                      proto.COLORMAPS[colormap_id])

    # -- the session path --------------------------------------------------

    async def _resolve_session(
            self, session_id: int, level: int, index_real: int,
            index_imag: int, colormap_id: int, flags: int
    ) -> tuple[int, int, int, Optional[bytes | tuple[int, int]]]:
        """Session lifecycle + admission + render; returns
        ``(session id, granted caps, status, payload)``.

        Latency lands in its own histogram (``session_request_seconds``,
        split by the same outcome label family) so first-paint latency is
        directly comparable against the full-depth render path.
        """
        svc = self.sessions
        self.counters.inc(obs_names.SESSION_QUERIES)
        if session_id == 0:
            state = svc.open(flags)
        else:
            state = svc.touch(session_id)
            if state is None:
                # Soft reject on a live connection: expired/unknown ids
                # are a normal part of the lifecycle (TTL, LRU eviction,
                # gateway restart) — the client reopens with id 0.
                self.counters.inc(obs_names.SESSION_UNKNOWN)
                return 0, 0, proto.QUERY_REJECT, None
        t0 = time.monotonic()
        status, payload, outcome = await self._session_outcome(
            state, level, index_real, index_imag, colormap_id)
        self.registry.observe(obs_names.HIST_SESSION_REQUEST_SECONDS,
                              time.monotonic() - t0,
                              labels={"outcome": outcome})
        return state.session_id, state.caps, status, payload

    async def _session_outcome(
            self, state, level: int, index_real: int, index_imag: int,
            colormap_id: int
    ) -> tuple[int, Optional[bytes | tuple[int, int]], str]:
        if not proto.query_in_range(level, index_real, index_imag):
            self.counters.inc("gateway_rejected")
            flight.note(obs_events.GW_REJECT,
                        key=(level, index_real, index_imag), path="session")
            return proto.QUERY_REJECT, None, obs_names.OUTCOME_REJECTED
        redirect = self._redirect_for(level, index_real, index_imag)
        if redirect is not None:
            return proto.QUERY_REDIRECT, redirect, obs_names.OUTCOME_REDIRECTED
        # The viewport hint always lands (trajectory + prefetch verdict
        # + plan), whatever the admission verdict below — a shed query
        # is still evidence of where the user is heading.
        planned = self.sessions.note_query(state, level, index_real,
                                           index_imag)
        if planned:
            self._spawn_prefetch(planned)
        # Weighted fair admission: the session's private budget is
        # charged before everything — even cache hits.  The global
        # bucket below protects compute, so cached bytes rightly skip
        # it; this one bounds the *session's* service rate, and a hot
        # session replaying cached tiles must not dodge its budget
        # while the rest of the crowd queues.
        if not state.admit():
            self.counters.inc(obs_names.SESSION_THROTTLED)
            flight.note(obs_events.GW_SESSION_THROTTLE,
                        key=(level, index_real, index_imag),
                        session=state.session_id)
            return (proto.QUERY_OVERLOADED, None,
                    obs_names.OUTCOME_SESSION_THROTTLED)
        render_key = (level, index_real, index_imag, colormap_id)
        body = self.render_cache.get(render_key)
        if body is not None:
            self.counters.inc(obs_names.GATEWAY_RENDER_SERVED)
            return (proto.QUERY_ACCEPT, body,
                    obs_names.OUTCOME_RENDER_CACHE)
        if self._active >= self.max_queue_depth \
                or not self.bucket.try_acquire():
            self.counters.inc("gateway_overloaded")
            flight.note(obs_events.GW_SHED,
                        key=(level, index_real, index_imag),
                        path="session", in_service=self._active)
            return proto.QUERY_OVERLOADED, None, obs_names.OUTCOME_OVERLOADED
        self._active += 1
        try:
            body, outcome = await self._render_session(
                state, level, index_real, index_imag, colormap_id)
        finally:
            self._active -= 1
        if body is None:
            self.counters.inc("gateway_unavailable")
            return (proto.QUERY_NOT_AVAILABLE, None,
                    obs_names.OUTCOME_UNAVAILABLE)
        self.counters.inc(obs_names.GATEWAY_RENDER_SERVED)
        return proto.QUERY_ACCEPT, body, outcome

    def _spawn_prefetch(self, keys: list[tuple[int, int, int]]) -> None:
        """Warm planned tiles off the response path (fire-and-forget)."""
        task = asyncio.get_running_loop().create_task(
            self.sessions.prefetch(keys))
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def _render_session(
            self, state, level: int, index_real: int, index_imag: int,
            colormap_id: int) -> tuple[Optional[bytes], str]:
        """Render for a session: refine-capable sessions get cold tiles
        as a cheap low-``max_iter`` first paint (the full depth is
        scheduled behind it); everything else takes the standard render
        path.  Warm tiles (tier 1 / store) are full quality either way —
        the first-paint shortcut only pays when pixels would have to be
        computed."""
        full_iter = self._level_max_iter.get(level)
        fp_iter = None
        if state.caps & proto.SESSION_CAP_REFINE:
            fp_iter = self.sessions.first_paint_iter(full_iter)
        if fp_iter is None or self.ondemand is None:
            body = await self._render(level, index_real, index_imag,
                                      colormap_id)
            return body, obs_names.OUTCOME_RENDERED
        flight_key = ("render", level, fp_iter, index_real, index_imag,
                      colormap_id)

        async def supplier() -> tuple[Optional[bytes], str]:
            payload, outcome = await self._resolve_first_paint(
                level, index_real, index_imag, fp_iter, full_iter)
            if payload is None:
                return None, obs_names.OUTCOME_UNAVAILABLE
            t0 = time.monotonic()
            body = await asyncio.to_thread(
                self._render_body, payload, colormap_id)
            self.registry.observe(obs_names.HIST_GATEWAY_RENDER_SECONDS,
                                  time.monotonic() - t0)
            if outcome is not obs_names.OUTCOME_FIRST_PAINT:
                # Shallow bodies must not linger in the render cache:
                # they'd outlive the deep save's invalidation sweep only
                # if cached before it — which this put would be.
                body = self.render_cache.put(
                    (level, index_real, index_imag, colormap_id), body)
            return body, outcome

        return await self.singleflight.run(flight_key, supplier)

    async def _resolve_first_paint(
            self, level: int, index_real: int, index_imag: int,
            fp_iter: int, full_iter: int) -> tuple[Optional[bytes], str]:
        """Payload for a first paint: warm reads are full quality; a true
        miss computes the cheap variant and queues the deep one."""
        key = (level, index_real, index_imag)
        flight_key = (level, fp_iter, index_real, index_imag)

        async def supplier() -> tuple[Optional[bytes], str]:
            entry = await asyncio.to_thread(self.cache.load, key)
            if entry is not None:
                return entry.payload, obs_names.OUTCOME_STORE
            entry = await self.ondemand.compute(
                Workload(level, fp_iter, index_real, index_imag))
            if entry is None:
                return None, obs_names.OUTCOME_UNAVAILABLE
            # Deliberately NOT promoted into tier 1: the shallow payload
            # is a one-shot paint, and the deep save that follows would
            # have to invalidate it anyway.
            self.counters.inc(obs_names.SESSION_FIRST_PAINTS)
            self.sessions.schedule_refine(
                Workload(level, full_iter, index_real, index_imag))
            return entry.payload, obs_names.OUTCOME_FIRST_PAINT

        return await self.singleflight.run(flight_key, supplier)

    def invalidate_saved(self, key: tuple[int, int, int]) -> None:
        """A (possibly deeper) variant of ``key`` just persisted: drop
        the stale decoded and rendered cache entries and settle any
        pending refinement.  The coordinator's save hook fans in here."""
        self.cache.invalidate(key)
        self.render_cache.invalidate_tile(key)
        if self.sessions is not None:
            self.sessions.on_chunk_saved(key)

    async def _resolve(self, level: int, index_real: int,
                       index_imag: int) -> tuple[Optional[bytes], str]:
        """Store lookup falling through to compute-on-read, single-flight
        per full workload identity ``(level, max_iter, i, j)``; returns
        ``(payload, outcome)`` (followers inherit the leader's outcome —
        they paid the leader's resolution path's latency)."""
        key = (level, index_real, index_imag)
        max_iter = self._level_max_iter.get(level)
        flight_key = (level, max_iter, index_real, index_imag)

        async def supplier() -> tuple[Optional[bytes], str]:
            entry = await asyncio.to_thread(self.cache.load, key)
            outcome = obs_names.OUTCOME_STORE
            if entry is None and self.ondemand is not None \
                    and max_iter is not None:
                entry = await self.ondemand.compute(
                    Workload(level, max_iter, index_real, index_imag))
                outcome = obs_names.OUTCOME_COMPUTED
                if entry is not None:
                    # Promote the fresh tile so follow-up requests are
                    # tier-1 hits, not store reads.
                    entry = self.cache.put(key, entry.payload)
            if entry is None:
                return None, obs_names.OUTCOME_UNAVAILABLE
            return entry.payload, outcome

        return await self.singleflight.run(flight_key, supplier)
