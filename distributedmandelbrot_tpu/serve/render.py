"""Shared colormapping core + server-side tile rendering to palette PNG.

The colormap pipeline used to live in ``viewer/render.py`` (client-side
only, every viewer shipping the raw 16 MiB payload first); it moved here
so the gateway can render on the server and the viewer keeps consuming
the exact same functions — the golden parity test pins server bytes ==
viewer bytes.  ``value_to_rgba`` reproduces the reference viewer's
pipeline exactly (``DistributedMandelbrotViewer.py:110-135``): normalize
/256, invert, apply matplotlib's ``jet``, then paint in-set pixels
(value 0, i.e. inverted 1.0) black.

Server-side rendering exploits that a colormapped escape-count tile has
at most 256 distinct colors (one per uint8 value, with value 0 forced
black): the wire image is an 8-bit *palette* PNG whose PLTE is the
colormap LUT and whose index plane is the escape counts themselves.
Smooth interior tiles deflate to ~50-200 KB; the worst case (boundary
soup) stays under the raw 16 MiB, so the render body always fits the
``MAX_PAYLOAD_BYTES`` bound.  Encoder and decoder are stdlib ``zlib``
only — no imaging dependency, and byte-deterministic for the parity
test.

matplotlib is imported lazily inside the colormap calls, so importing
this module (and everything above it: gateway, loadgen, ``dmtpu check``)
stays matplotlib-free.
"""

from __future__ import annotations

import struct
import threading
import zlib

import numpy as np

PNG_SIGNATURE = b"\x89PNG\r\n\x1a\n"

# LUT memo: building one costs a 256-element matplotlib colormap call;
# the gateway renders thousands of tiles per colormap.
_LUT_LOCK = threading.Lock()
_LUTS: dict[str, np.ndarray] = {}


def _masked_colormap(vs: np.ndarray, in_set: np.ndarray,
                     colormap: str) -> np.ndarray:
    """Shared tail of both render paths: colormap ``vs``, paint in-set
    pixels black."""
    import matplotlib

    mapped = matplotlib.colormaps[colormap](vs).astype(float)
    black = np.array((0.0, 0.0, 0.0, 1.0))
    return np.where(in_set[..., None], black, mapped)


def value_to_rgba(values: np.ndarray, colormap: str = "jet") -> np.ndarray:
    """Flat or 2-D uint8 values -> float RGBA array (reference pipeline)."""
    if values.ndim == 1:
        side = int(round(values.size ** 0.5))
        if side * side != values.size:
            raise ValueError(f"cannot square-reshape {values.size} pixels")
        values = values.reshape((side, side))
    vs = 1.0 - values.astype(float) / 256.0
    return _masked_colormap(vs, vs == 1.0, colormap)


def smooth_to_rgba(nu: np.ndarray, max_iter: int,
                   colormap: str = "jet",
                   normalize: bool = False) -> np.ndarray:
    """Continuous escape values (:func:`...ops.escape_smooth`) -> RGBA.

    Same visual convention as :func:`value_to_rgba` — in-set (0) pixels
    black, others through the inverted colormap — but band-free: the
    fractional part of ``nu`` varies continuously across iteration
    boundaries.  Log-scaled so deep zooms (large max_iter) keep contrast.

    ``normalize`` stretches the view's OWN escaped-value range over the
    full colormap (log-domain min-max): deep windows occupy a sliver of
    the absolute scale (a span-1e-10 view at budget 50000 spans ~6% of
    it — near-flat color), and auto-contrast is what makes them
    readable.  View-dependent by construction, so animations must NOT
    use it per-frame (the stretch would flicker as ranges drift).
    """
    nu = np.asarray(nu, float)
    logs = np.log1p(np.maximum(nu, 0.0))
    escaped = nu > 0.0
    if normalize and escaped.any():
        sel = logs[escaped]
        lo, hi = float(sel.min()), float(sel.max())
        vs = (logs - lo) / max(hi - lo, 1e-12)
    else:
        vs = logs / np.log1p(float(max_iter))
    return _masked_colormap(1.0 - np.clip(vs, 0.0, 1.0), nu <= 0.0, colormap)


def to_rgba8(rgba: np.ndarray) -> np.ndarray:
    """Quantize float RGBA in [0, 1] to uint8 — THE quantization step.

    Both the viewer's save path and the server's palette build go through
    this one function, which is what makes "server-rendered bytes ==
    viewer-rendered bytes" a theorem instead of a hope.
    """
    return (np.clip(np.asarray(rgba, float), 0.0, 1.0) * 255.0
            + 0.5).astype(np.uint8)


def value_lut(colormap: str = "jet") -> np.ndarray:
    """(256, 4) uint8 RGBA lookup table: LUT[v] is the rendered color of
    escape value ``v`` under :func:`value_to_rgba` + :func:`to_rgba8`.

    Built by pushing all 256 values through the float pipeline once, so
    ``LUT[tile]`` is elementwise identical to quantizing the viewer's
    full-tile render (matplotlib colormaps are pointwise).
    """
    with _LUT_LOCK:
        lut = _LUTS.get(colormap)
        if lut is None:
            values = np.arange(256, dtype=np.uint8)
            lut = to_rgba8(value_to_rgba(values, colormap)).reshape(256, 4)
            lut.setflags(write=False)
            _LUTS[colormap] = lut
        return lut


def render_tile_rgba8(values: np.ndarray,
                      colormap: str = "jet") -> np.ndarray:
    """Render flat or 2-D uint8 escape values to a uint8 RGBA image via
    the colormap LUT (the server's render path)."""
    square = _as_square(values)
    return value_lut(colormap)[square]


def _as_square(values: np.ndarray) -> np.ndarray:
    values = np.ascontiguousarray(values, dtype=np.uint8)
    if values.ndim == 1:
        side = int(round(values.size ** 0.5))
        if side * side != values.size:
            raise ValueError(f"cannot square-reshape {values.size} pixels")
        values = values.reshape((side, side))
    return values


def _png_chunk(tag: bytes, body: bytes) -> bytes:
    return (struct.pack(">I", len(body)) + tag + body
            + struct.pack(">I", zlib.crc32(tag + body)))


def render_tile_png(values: np.ndarray, colormap: str = "jet", *,
                    compress_level: int = 6) -> bytes:
    """Encode a tile as an 8-bit palette PNG (color type 3, filter 0).

    The index plane IS the escape-count tile; the PLTE is the colormap
    LUT's RGB (alpha is 255 everywhere by construction, so no tRNS).
    Deterministic: fixed filter, fixed zlib level, no ancillary chunks.
    """
    square = _as_square(values)
    height, width = square.shape
    lut = value_lut(colormap)
    # Each scanline is a filter byte (0 = None) then the raw indices.
    scanlines = np.zeros((height, width + 1), dtype=np.uint8)
    scanlines[:, 1:] = square
    ihdr = struct.pack(">IIBBBBB", width, height, 8, 3, 0, 0, 0)
    return (PNG_SIGNATURE
            + _png_chunk(b"IHDR", ihdr)
            + _png_chunk(b"PLTE", lut[:, :3].tobytes())
            + _png_chunk(b"IDAT", zlib.compress(scanlines.tobytes(),
                                                compress_level))
            + _png_chunk(b"IEND", b""))


def decode_rendered_png(data: bytes) -> np.ndarray:
    """Decode a :func:`render_tile_png` body back to uint8 RGBA.

    Intentionally narrow — palette PNGs with filter 0 only, i.e. exactly
    what this module emits — so the parity test and the loadgen's body
    validation don't need an imaging library.  Raises ``ValueError`` on
    anything else.
    """
    if not data.startswith(PNG_SIGNATURE):
        raise ValueError("not a PNG")
    pos = len(PNG_SIGNATURE)
    ihdr = None
    palette = None
    idat = bytearray()
    while pos + 8 <= len(data):
        (length,) = struct.unpack_from(">I", data, pos)
        tag = data[pos + 4:pos + 8]
        body = data[pos + 8:pos + 8 + length]
        pos += 12 + length  # length + tag + body + crc
        if tag == b"IHDR":
            ihdr = struct.unpack(">IIBBBBB", body)
        elif tag == b"PLTE":
            palette = np.frombuffer(body, np.uint8).reshape(-1, 3)
        elif tag == b"IDAT":
            idat += body
        elif tag == b"IEND":
            break
    if ihdr is None or palette is None or not idat:
        raise ValueError("missing IHDR/PLTE/IDAT chunk")
    width, height, depth, color_type, _, _, interlace = ihdr
    if (depth, color_type, interlace) != (8, 3, 0):
        raise ValueError(
            f"unsupported PNG shape: depth={depth} color={color_type} "
            f"interlace={interlace}")
    # Bounded inflate: IHDR fixes the decoded size, so cap decompression
    # there instead of letting a 200-byte deflate bomb expand to
    # gigabytes before the size check (same posture as the RLE codec's
    # bomb guard).
    expected = height * (width + 1)
    inflater = zlib.decompressobj()
    decoded = inflater.decompress(bytes(idat), expected)
    if not inflater.eof or inflater.unconsumed_tail \
            or inflater.decompress(b"", 1):
        raise ValueError(
            f"IDAT decodes past the {expected} bytes IHDR promises")
    raw = np.frombuffer(decoded, np.uint8)
    if raw.size != expected:
        raise ValueError(f"IDAT decodes to {raw.size} bytes, expected "
                         f"{expected}")
    scanlines = raw.reshape(height, width + 1)
    if np.any(scanlines[:, 0] != 0):
        raise ValueError("unsupported PNG filter (encoder emits 0 only)")
    indices = scanlines[:, 1:]
    rgba = np.empty((height, width, 4), dtype=np.uint8)
    rgba[..., :3] = palette[indices]
    rgba[..., 3] = 255
    return rgba
