"""Single-flight request coalescing.

N concurrent gateway requests for the same tile key must trigger exactly
one store read / one on-demand compute — the classic cache-stampede guard
every serving stack in front of an expensive backend needs (here the
backend is a whole worker farm computing a 16 Mpix tile).

The first caller for a key becomes the *leader*: its supplier runs in a
detached task, so a leader whose connection drops mid-flight does not
cancel the flight for the followers piled up behind it.  Everyone —
leader included — awaits the shared future; the result (or the exception)
fans out to all of them.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Hashable, Optional, TypeVar

from distributedmandelbrot_tpu.utils.metrics import Counters

T = TypeVar("T")


class SingleFlight:
    """Per-key coalescing of concurrent async suppliers (one event loop)."""

    def __init__(self, counters: Optional[Counters] = None) -> None:
        self.counters = counters if counters is not None else Counters()
        self._inflight: dict[Hashable, asyncio.Future] = {}
        self._tasks: set[asyncio.Task] = set()

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    def cancel_inflight(self) -> list[asyncio.Task]:
        """Cancel all running flights (shutdown); returns them to await."""
        tasks = list(self._tasks)
        for task in tasks:
            task.cancel()
        return tasks

    async def run(self, key: Hashable,
                  supplier: Callable[[], Awaitable[T]]) -> T:
        """Run ``supplier`` once per key across concurrent callers.

        Followers arriving while a flight is up await its result instead
        of starting their own.  A follower's cancellation only cancels
        that follower; the flight itself completes and serves the rest.
        """
        existing = self._inflight.get(key)
        if existing is not None:
            self.counters.inc("coalesce_followers")
            return await asyncio.shield(existing)
        self.counters.inc("coalesce_leaders")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        task = asyncio.create_task(self._fly(key, fut, supplier))
        # Keep a strong ref: the loop only weakly references tasks, and a
        # GC'd flight would strand every waiter.
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return await asyncio.shield(fut)

    async def _fly(self, key: Hashable, fut: asyncio.Future,
                   supplier: Callable[[], Awaitable[T]]) -> None:
        try:
            result = await supplier()
        except BaseException as e:
            # Unregister BEFORE resolving: a caller retrying the moment
            # the future settles must start a fresh flight, not join a
            # finished one.
            self._inflight.pop(key, None)
            if not fut.cancelled():
                fut.set_exception(e)
            if isinstance(e, asyncio.CancelledError):
                raise
        else:
            self._inflight.pop(key, None)
            if not fut.cancelled():
                fut.set_result(result)
