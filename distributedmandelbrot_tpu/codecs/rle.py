"""Run-length-encoding chunk codec — code 0x01.

Body is a sequence of records ``(run_length: uint32 LE, value: uint8)``
(reference: ``DistributedMandelbrot/DataChunkSerializer.cs:51-142``; the
viewer's decoder ``DistributedMandelbrotViewer.py:35-50`` reads the same
format).  Unlike the reference's byte-at-a-time loops, runs are found with
vectorized numpy (boundary detection + ``np.repeat``); an optional native
C++ fast path plugs in via :mod:`distributedmandelbrot_tpu.native`.
"""

from __future__ import annotations

import numpy as np

_REC_DTYPE = np.dtype([("count", "<u4"), ("value", "u1")])


def find_runs(data: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Return (counts uint32, values uint8) of the maximal runs in ``data``."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size == 0:
        return (np.empty(0, np.uint32), np.empty(0, np.uint8))
    boundaries = np.flatnonzero(data[1:] != data[:-1])
    starts = np.concatenate(([0], boundaries + 1))
    ends = np.concatenate((boundaries + 1, [data.size]))
    return (ends - starts).astype(np.uint32), data[starts]


def _native():
    """The native module when usable, else None (lazy; never raises)."""
    try:
        from distributedmandelbrot_tpu import native
        return native if native.native_supported() else None
    except Exception:  # pragma: no cover - import/build environment issues
        return None


class RleCodec:
    code = 0x01

    def encode(self, data: np.ndarray) -> bytes:
        native = _native()
        if native is not None:
            return native.rle_encode(data)
        return self._encode_py(data)

    @staticmethod
    def _encode_py(data: np.ndarray) -> bytes:
        """The pure-Python encoder (also exercised directly by the
        native-parity property test: both implementations must emit the
        same bytes, since a farm may mix hosts with and without g++)."""
        counts, values = find_runs(data)
        records = np.empty(counts.size, dtype=_REC_DTYPE)
        records["count"] = counts
        records["value"] = values
        return records.tobytes()

    def decode(self, body: bytes, expected_size: int) -> np.ndarray:
        native = _native()
        if native is not None:
            return native.rle_decode(body, expected_size)
        return self._decode_py(body, expected_size)

    def _decode_py(self, body: bytes, expected_size: int) -> np.ndarray:
        if len(body) % _REC_DTYPE.itemsize != 0:
            raise ValueError(
                f"RLE body length {len(body)} is not a multiple of "
                f"{_REC_DTYPE.itemsize}")
        records = np.frombuffer(body, dtype=_REC_DTYPE)
        counts = records["count"].astype(np.int64)
        if (counts == 0).any():
            raise ValueError("encountered RLE run of length 0")
        total = int(counts.sum())
        if total != expected_size:
            raise ValueError(
                f"RLE decodes to {total} bytes, expected {expected_size}")
        return np.repeat(records["value"], counts)

    def encoded_size(self, data: np.ndarray) -> int:
        native = _native()
        if native is not None:
            return native.rle_encoded_size(data)
        counts, _ = find_runs(data)
        return counts.size * _REC_DTYPE.itemsize


# Strided sample width for the histogram pre-filter below.  Coarse on
# purpose: the sample only has to distinguish "one escape count
# dominates" from "boundary soup", not count runs.
_SAMPLE_STRIDE = 64


def estimate_ratio(data: np.ndarray, min_ratio: float = 2.0) -> float:
    """Cheap estimate of ``data.size / rle_encoded_size`` for the wire tier.

    Two stages, both vectorized.  First an escape-count histogram over a
    1/64 strided sample: a compression ratio of ``min_ratio`` needs a
    mean run length of ``5 * min_ratio`` pixels, which forces some single
    value (in practice the interior's max-iter count) to hold a large
    share of the tile — if no value reaches half the sample, the tile is
    boundary soup and RLE cannot win, so bail out reporting 1.0 without
    touching the full 16 MiB.  Only plausible tiles pay for the exact
    run count (one boundary-detection pass).
    """
    flat = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if flat.size == 0:
        return 1.0
    sample = flat[::_SAMPLE_STRIDE]
    top_share = np.bincount(sample, minlength=256).max() / sample.size
    if top_share < 0.5:
        return 1.0
    boundaries = int(np.count_nonzero(flat[1:] != flat[:-1]))
    return flat.size / float((boundaries + 1) * _REC_DTYPE.itemsize)
