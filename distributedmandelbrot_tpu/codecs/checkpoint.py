"""Checkpoint blob wire structs — the on-disk format owned here.

Like the chunk codecs, this module owns one on-disk format in one
place (the wire-literal rule keeps struct formats out of everywhere
else).  The record layout and the encode/decode logic live in
``coordinator/recovery.py``; this module holds only the magic, the
version, and the precompiled :class:`struct.Struct` objects.

Layout (all little-endian, CRC32 trailer over everything before it):

    HEADER:  "DMCP" | version:u32 | generation:u64 | index_offset:u64 |
             cursor_pos:u64 | cursor_done:u8 | pad[3] |
             n_settings:u32 | n_completed:u32 | n_leases:u32 | n_retry:u32
    SETTING: level:u32 | max_iter:u32
    KEY:     level:u32 | re:u32 | im:u32
    LEASE:   level:u32 | re:u32 | im:u32 | max_iter:u32 | remaining:f64
    RETRY:   level:u32 | re:u32 | im:u32 | max_iter:u32
    CRC:     crc32:u32
"""

from __future__ import annotations

import struct

CHECKPOINT_MAGIC = b"DMCP"
CHECKPOINT_VERSION = 1

CHECKPOINT_HEADER = struct.Struct("<4sIQQQB3xIIII")
CHECKPOINT_SETTING = struct.Struct("<II")
CHECKPOINT_KEY = struct.Struct("<III")
CHECKPOINT_LEASE = struct.Struct("<IIIId")
CHECKPOINT_RETRY = struct.Struct("<IIII")
CHECKPOINT_CRC = struct.Struct("<I")
