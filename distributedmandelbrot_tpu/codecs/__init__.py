"""Chunk payload codecs (Raw 0x00, RLE 0x01) and the pick-smallest registry."""

from distributedmandelbrot_tpu.codecs import base
from distributedmandelbrot_tpu.codecs.base import (Codec, deserialize, get,
                                                   register, serialize)
from distributedmandelbrot_tpu.codecs.raw import RawCodec
from distributedmandelbrot_tpu.codecs.rle import RleCodec

RAW = RawCodec()
RLE = RleCodec()

if not base.all_codecs():
    register(RAW)
    register(RLE)

__all__ = ["Codec", "RawCodec", "RleCodec", "RAW", "RLE", "register", "get",
           "serialize", "deserialize"]
