"""Codec interface and registry for chunk pixel payloads.

A serialized chunk payload is one codec-code byte followed by the codec's
body (reference: ``DistributedMandelbrot/DataChunkSerializer.cs:8-27``).
The registry mirrors the reference's two-codec table
(``DataChunk.cs:163-167``): 0x00 Raw, 0x01 RLE.  Serialization picks the
codec with the smallest encoded size (``DataChunk.cs:173-206``) — done here
by costing each codec directly rather than via a counting stream.
"""

from __future__ import annotations

from typing import Protocol

import numpy as np


class Codec(Protocol):
    """Encodes/decodes a flat uint8 pixel array (codec body only, no code byte)."""

    code: int

    def encode(self, data: np.ndarray) -> bytes: ...

    def decode(self, body: bytes, expected_size: int) -> np.ndarray: ...

    def encoded_size(self, data: np.ndarray) -> int: ...


_REGISTRY: dict[int, Codec] = {}


def register(codec: Codec) -> Codec:
    if codec.code in _REGISTRY:
        raise ValueError(f"codec code {codec.code:#x} already registered")
    _REGISTRY[codec.code] = codec
    return codec


def get(code: int) -> Codec:
    try:
        return _REGISTRY[code]
    except KeyError:
        raise ValueError(f"unknown codec code {code:#x}") from None


def all_codecs() -> tuple[Codec, ...]:
    return tuple(_REGISTRY.values())


RAW_CODE = 0x00


def serialize(data: np.ndarray) -> bytes:
    """Encode ``data`` with whichever registered codec yields the fewest bytes.

    Returns the full payload: 1 code byte + body.  Raw (identity) is costed
    by ``data.size`` without materializing its 16 MiB body; every other
    codec is encoded exactly once and compared by actual body length, so the
    winning encoding is never computed twice.
    """
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    best_code, best_body = RAW_CODE, None
    best_size = data.size
    for codec in all_codecs():
        if codec.code == RAW_CODE:
            continue
        body = codec.encode(data)
        if len(body) < best_size:
            best_code, best_body, best_size = codec.code, body, len(body)
    if best_body is None:
        best_body = get(RAW_CODE).encode(data)
    return bytes([best_code]) + best_body


def deserialize(payload: bytes, expected_size: int) -> np.ndarray:
    """Decode a full payload (code byte + body) into a flat uint8 array."""
    if len(payload) < 1:
        raise ValueError("empty chunk payload")
    codec = get(payload[0])
    return codec.decode(payload[1:], expected_size)
