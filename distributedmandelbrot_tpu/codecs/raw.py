"""Raw (verbatim) chunk codec — code 0x00.

Body is the pixel bytes as-is (reference:
``DistributedMandelbrot/DataChunkSerializer.cs:29-49``).
"""

from __future__ import annotations

import numpy as np


class RawCodec:
    code = 0x00

    def encode(self, data: np.ndarray) -> bytes:
        return np.ascontiguousarray(data, dtype=np.uint8).tobytes()

    def decode(self, body: bytes, expected_size: int) -> np.ndarray:
        if len(body) != expected_size:
            raise ValueError(
                f"raw body must be exactly {expected_size} bytes, "
                f"got {len(body)}")
        return np.frombuffer(body, dtype=np.uint8).copy()

    def encoded_size(self, data: np.ndarray) -> int:
        return data.size
