"""Command-line entry points: coordinator, worker, viewer.

Covers the reference's configuration surface (``Program.cs:182-409``: level
spec, data directory, bind address/ports, per-channel log enables, socket
timeout toggle; worker/viewer connection prompts
``DistributedMandelbrotWorkerCUDA.py:178-184``,
``DistributedMandelbrotViewer.py:145-166``) with a standard argparse CLI:

    python -m distributedmandelbrot_tpu coordinator -l 4:256,10:1024
    python -m distributedmandelbrot_tpu worker --backend jax --batch-size 8
    python -m distributedmandelbrot_tpu viewer 4 1 2 --out tile.png
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
import time
from typing import Optional, Sequence

import numpy as np

from distributedmandelbrot_tpu.core.workload import parse_level_settings
from distributedmandelbrot_tpu.net import protocol as proto

logger = logging.getLogger("dmtpu.cli")


def _configure_logging(args: argparse.Namespace) -> None:
    level = logging.ERROR if args.quiet else (
        logging.DEBUG if args.verbose else logging.INFO)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    if getattr(args, "no_info_log", False):
        logging.getLogger("dmtpu").setLevel(logging.ERROR)


def _configure_channel_logging(args: argparse.Namespace) -> None:
    """Per-server info/error log enables (reference: -dli/-dle/-sli/-sle,
    ``Program.cs:305-325,362-381``): disabling info leaves errors; disabling
    errors silences the channel entirely (the reference's error callback is
    the last-resort channel, so 'false' means fully off)."""
    for chan, info, err in (
            ("dmtpu.distributer", args.distributer_log_info,
             args.distributer_log_error),
            ("dmtpu.dataserver", args.data_server_log_info,
             args.data_server_log_error)):
        log = logging.getLogger(chan)
        if err == "false":
            log.setLevel(logging.CRITICAL + 1)
        elif info == "false":
            log.setLevel(logging.ERROR)


def _add_common(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="debug logging")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="errors only")


_NP_DTYPES = {"f32": np.float32, "f64": np.float64}


def _span_f32_resolvable(cx: float, cy: float, span: float,
                         definition: int) -> bool:
    """One view -> one resolvability verdict: the single copy of the
    center/span -> TileSpec convention, so the dtype default and the
    deep auto-select can never disagree."""
    from distributedmandelbrot_tpu.core.geometry import (TileSpec,
                                                         spec_f32_resolvable)
    return spec_f32_resolvable(TileSpec(cx - span / 2, cy - span / 2,
                                        span, span, width=definition,
                                        height=definition))


def _view_f32_resolvable(args: argparse.Namespace,
                         center: tuple[float, float]) -> bool:
    """Whether the request's finest view resolves in f32 (min over both
    sweep ends: a zoom-OUT run starts at the small span — same rule as
    cmd_animate's family guard)."""
    span = min(getattr(args, "span", 4.0),
               getattr(args, "span_start", 4.0),
               getattr(args, "span_end", 4.0))
    return _span_f32_resolvable(center[0], center[1], span,
                                getattr(args, "definition", 1024))


def _resolve_dtype(args: argparse.Namespace,
                   center: tuple[float, float] | None = None,
                   can_perturb: bool = False):
    """--dtype default is mode-dependent: smooth rendering defaults to
    the f64 quality path, everything else to f32 (an explicit --dtype
    always wins — 'f32 --smooth' selects the fast smooth path).  An
    explicit --dtype selects the arithmetic WIDTH, not the algorithm:
    f32 views whose pixel pitch f32 cannot resolve directly still
    render through f32 *perturbation* (see _auto_deep) rather than
    produce a banded direct render.
    Anything that renders deep — explicit --deep, a sub-threshold span,
    or an animation sweeping past the threshold — defaults to f32 even
    with --smooth: there the view's precision comes from the bigint
    reference orbit and f32 deltas are the designed fast path (and a
    sweep must not change dtype mid-animation).

    ``center`` (resolved view center) enables the f32-resolution check:
    spans between the perturbation threshold and f32's pixel resolution
    (~1e-4 at 1024^2 near |c|=1) would render banded in f32 — adjacent
    pixel coordinates collapse to the same float.  Fractals with a
    perturbation path (``can_perturb``: Mandelbrot/Julia) render such
    views via f32 delta orbits — the TPU-native fast path — so the
    default stays f32; families without one (Multibrot/ship) upgrade to
    the f64 quality path, matching the reference worker's always-f64
    output (``DistributedMandelbrotWorkerCUDA.py:39``)."""
    if args.dtype is not None:
        return _NP_DTYPES[args.dtype]
    touches_deep = (
        getattr(args, "deep", False)
        or getattr(args, "span", 1.0) < DEEP_SPAN_THRESHOLD
        or getattr(args, "span_start", 1.0) < DEEP_SPAN_THRESHOLD
        or getattr(args, "span_end", 1.0) < DEEP_SPAN_THRESHOLD)
    if touches_deep:
        return np.float32
    if center is not None and not _view_f32_resolvable(args, center):
        # Smooth keeps its f64 quality promise (f64 resolves every span
        # above the perturbation threshold); integer renders take f32
        # perturbation when available, f64 otherwise.
        if getattr(args, "smooth", False) or not can_perturb:
            return np.float64
        return np.float32
    return np.float64 if getattr(args, "smooth", False) else np.float32


def _join_negative_values(argv: Sequence[str], flags: Sequence[str]) -> list:
    """Merge ``--flag -0.8,0.156`` into ``--flag=-0.8,0.156`` so argparse
    doesn't mistake the negative value for an option."""
    out, it = [], iter(list(argv))
    for tok in it:
        if tok in flags:
            val = next(it, None)
            if val is None:
                out.append(tok)
            else:
                out.append(f"{tok}={val}")
        else:
            out.append(tok)
    return out


def _resolve_bla(args: argparse.Namespace) -> bool | None:
    """--bla / --no-bla -> the perturbation layer's tri-state: force on,
    force off, or (neither) the per-orbit auto-probe
    (ops.perturbation._auto_bla)."""
    if getattr(args, "bla", False):
        return True
    if getattr(args, "no_bla", False):
        return False
    return None


# Below this span, float64 pixel coordinates alias and the renderer
# switches to the perturbation path (center at decimal-string precision).
DEEP_SPAN_THRESHOLD = 1e-12


def _pallas_first(kernel: str, /, *args, **kwargs):
    """Run the named ops.pallas_escape kernel on TPU, or return None when
    Pallas is unavailable or rejects the shape/budget (callers fall back
    to the XLA path).  The single copy of the f32 fast-path dispatch
    policy; only unavailability and the kernel's *intentional*
    PallasUnsupported rejections map to None — any other error (including
    a genuine kernel bug surfacing as ValueError) propagates rather than
    silently degrading to the XLA path."""
    from distributedmandelbrot_tpu.ops import pallas_escape
    if not pallas_escape.pallas_available():
        return None
    try:
        return getattr(pallas_escape, kernel)(*args, **kwargs)
    except pallas_escape.PallasUnsupported as e:
        logger.debug("pallas path declined %s: %s", kernel, e)
        return None


def _add_no_pallas(parser: argparse.ArgumentParser) -> None:
    """Shared by render and animate so the flag's contract can never
    diverge between them (same single-copy rule as _render_view)."""
    parser.add_argument("--no-pallas", action="store_true",
                        help="force the XLA/host-grid compute path even on "
                             "TPU: the Pallas f32 fast path generates its "
                             "pixel grid on device (start + i*step in f32), "
                             "which can differ from the host-linspace grid "
                             "at the last ulp; use this to reproduce "
                             "host-grid renders exactly")


def _auto_deep(span: float, cx: float, cy: float, definition: int,
               np_dtype) -> bool:
    """Whether a Mandelbrot/Julia view should render via perturbation:
    below the f64 threshold, OR at an f32 dtype whose pixel pitch the
    direct path cannot resolve (banded render) — delta orbits against
    the bigint reference orbit render both exactly, at f32 speed, far
    faster on TPU than the emulated-f64 direct path.  The single copy of
    the decision: _render_view's auto-select and cmd_animate's per-frame
    progress label must never disagree (families don't call this — they
    have no perturbation path)."""
    return span < DEEP_SPAN_THRESHOLD or (
        np_dtype == np.float32
        and not _span_f32_resolvable(cx, cy, span, definition))


def _resolve_deep(deep: bool | None, span: float, cx: float, cy: float,
                  definition: int, np_dtype,
                  family: tuple[int, bool] | None) -> bool:
    """The ONE resolution of the direct-vs-perturbation routing decision:
    families have no perturbation path, an explicit ``deep`` wins, and
    ``None`` auto-selects via :func:`_auto_deep`.  _render_view's
    dispatch, the packed supersample fast-path predicate, and cmd_render's
    --bla applicability guard all resolve here, so none of them can
    desynchronize from the path actually rendered (round-3 advisor: the
    guard compared the raw span threshold and wrongly rejected --bla on
    f32-unresolvable spans that _auto_deep routes to perturbation)."""
    if family is not None:
        return False
    if deep is None:
        return _auto_deep(span, cx, cy, definition, np_dtype)
    return bool(deep)


def _warn_if_deep_all_inset(plane, max_iter: int, span: float) -> None:
    """A deep view where EVERY pixel classifies in-set (value 0) is
    almost always an under-budgeted render, not a discovery: escape
    depths grow with zoom (measured at the seahorse Misiurewicz point:
    minimum escape ~3250 at span 1e-10, ~7060 at 1e-16), so a budget
    that resolved a shallow frame silently produces a uniform tile a
    few octaves deeper.  Say so instead of writing a flat image with no
    hint.  (Shallow interior views are legitimately all-in-set, hence
    deep-path only.)"""
    if not np.any(np.asarray(plane)):
        logger.warning(
            "deep view at span %g: no pixel escaped within max_iter=%d — "
            "the output is a uniform in-set tile.  Deep zooms need "
            "budgets that grow with depth; retry with a larger "
            "--max-iter.", span, max_iter)


# Mean-zero subpixel offsets (in pixel-pitch units): the sample cloud
# stays centered on the nominal pixel position, so supersampling never
# shifts the image, only averages across the pixel's footprint.
_SS_OFFSETS = {2: ((-0.25, -0.25), (0.25, 0.25)),
               4: ((-0.25, -0.25), (0.25, -0.25),
                   (-0.25, 0.25), (0.25, 0.25))}


def _render_supersampled(c_re: str, c_im: str, span: float, definition: int,
                         max_iter: int, *, supersample: int,
                         render_kwargs: dict) -> np.ndarray:
    """Anti-aliased render: ``supersample`` subpixel samples per output
    pixel, averaged in COLOR space (each sample colormapped first, so
    the in-set-black convention blends correctly at the set boundary).

    On TPU the integer f32 direct paths compute ALL samples in one
    interleaved packed-kernel pass (ops.pallas_escape
    compute_tiles_packed_pallas): identical same-window states are the
    packed kernel's ideal case, so 2-4x sampling costs ~1.6x a plain
    render, not 2-4x.  Every other path (smooth, deep/perturbation,
    XLA fallback) renders the samples sequentially — same output,
    linear cost."""
    from decimal import Decimal

    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.viewer import value_to_rgba

    offsets = _SS_OFFSETS[supersample]
    pitch = span / (definition - 1)

    kw = render_kwargs
    if (not kw.get("smooth") and not kw.get("no_pallas")
            and kw.get("np_dtype") == np.float32):
        # Packed fast path (integer f32, direct): one kernel pass for
        # all samples.  Falls through to the sequential path when
        # pallas is unavailable or declines the shape/budget.  Routing
        # MUST agree with _render_view's — both resolve via
        # _resolve_deep, the single copy of the decision.
        cx, cy = float(c_re), float(c_im)
        if not _resolve_deep(kw.get("deep"), span, cx, cy, definition,
                             np.float32, kw.get("family")):
            power, burning = kw.get("family") or (2, False)
            jc_pair = kw.get("julia_c")
            jc = (complex(float(jc_pair[0]), float(jc_pair[1]))
                  if jc_pair is not None else None)
            specs = [TileSpec(cx - span / 2 + dx * pitch,
                              cy - span / 2 + dy * pitch, span, span,
                              width=definition, height=definition)
                     for dx, dy in offsets]
            planes = _pallas_first(
                "compute_tiles_packed_pallas", specs,
                [max_iter] * supersample, power=power, burning=burning,
                julia_cs=[jc] * supersample if jc is not None else None)
            if planes is not None:
                acc = None
                for plane in planes:
                    rgba = value_to_rgba(np.asarray(plane),
                                         colormap=kw["colormap"])
                    acc = rgba if acc is None else acc + rgba
                return acc / supersample

    acc = None
    for dx, dy in offsets:
        # Decimal shift keeps deep-path center strings at full precision.
        sre = str(Decimal(c_re) + Decimal(repr(dx * pitch)))
        sim = str(Decimal(c_im) + Decimal(repr(dy * pitch)))
        rgba = _render_view(sre, sim, span, definition, max_iter, **kw)
        acc = rgba if acc is None else acc + rgba
    return acc / supersample


def _render_view(c_re: str, c_im: str, span: float, definition: int,
                 max_iter: int, *, smooth: bool, np_dtype, colormap: str,
                 deep: bool | None = None,
                 julia_c: tuple[str, str] | None = None,
                 family: tuple[int, bool] | None = None,
                 no_pallas: bool = False, normalize: bool = False,
                 supersample: int = 1, bla: bool | None = None):
    """One view -> RGBA (Mandelbrot, or Julia when ``julia_c`` is set, or
    a Multibrot/Burning-Ship view when ``family=(power, burning)``),
    choosing direct vs perturbation rendering.  Shared by the render and
    animate commands so their behavior can never diverge; ``deep=None``
    auto-selects below :data:`DEEP_SPAN_THRESHOLD`.

    ``no_pallas`` forces the XLA/host-grid path even on TPU.  Grid
    convention note: the Pallas kernel generates its pixel grid on
    device as ``start + index * step`` in f32, which differs from the
    XLA path's host float64 linspace (exact endpoint) by up to one ulp
    per coordinate — O(1) chaotic-boundary pixels per tile can land one
    iteration bucket apart.  ``no_pallas`` reproduces the host-grid
    output exactly (e.g. to re-render frames from a pre-Pallas build).
    """
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.viewer import smooth_to_rgba, value_to_rgba

    if supersample > 1:
        return _render_supersampled(
            c_re, c_im, span, definition, max_iter, supersample=supersample,
            render_kwargs=dict(smooth=smooth, np_dtype=np_dtype,
                               colormap=colormap, deep=deep, julia_c=julia_c,
                               family=family, no_pallas=no_pallas,
                               normalize=normalize, bla=bla))

    pallas_first = ((lambda *a, **k: None) if no_pallas else _pallas_first)

    if family is not None:
        # Extended families: direct rendering only (no perturbation
        # path — the command parsers reject sub-threshold spans).
        power, burning = family
        cx, cy = float(c_re), float(c_im)
        spec = TileSpec(cx - span / 2, cy - span / 2, span, span,
                        width=definition, height=definition)
        if smooth:
            nu = pallas_first("compute_tile_smooth_pallas", spec, max_iter,
                              power=power, burning=burning) \
                if np_dtype == np.float32 else None
            if nu is None:
                from distributedmandelbrot_tpu.ops.families import (
                    compute_tile_smooth_family)
                nu = compute_tile_smooth_family(spec, max_iter, power=power,
                                                burning=burning,
                                                dtype=np_dtype)
            return smooth_to_rgba(nu, max_iter, colormap=colormap,
                              normalize=normalize)
        values = pallas_first("compute_tile_family_pallas", spec, max_iter,
                              power=power, burning=burning) \
            if np_dtype == np.float32 else None
        if values is None:
            from distributedmandelbrot_tpu.ops import compute_tile_family
            values = compute_tile_family(spec, max_iter, power=power,
                                         burning=burning, dtype=np_dtype)
        return value_to_rgba(values.reshape(spec.height, spec.width),
                             colormap=colormap)

    deep = _resolve_deep(deep, span, float(c_re), float(c_im), definition,
                         np_dtype, family)
    if deep:
        from distributedmandelbrot_tpu.ops import (DeepTileSpec,
                                                   compute_smooth_perturb)
        # Center strings pass through verbatim: their precision is NOT
        # bounded by float64 (that's the point of the deep path).
        dspec = DeepTileSpec(c_re, c_im, span, width=definition,
                             height=definition)
        if smooth:
            nu, _ = compute_smooth_perturb(dspec, max_iter, dtype=np_dtype,
                                           julia_c=julia_c, bla=bla)
            _warn_if_deep_all_inset(nu, max_iter, span)
            return smooth_to_rgba(nu, max_iter, colormap=colormap,
                              normalize=normalize)
        # Warn on the RAW counts, not the scaled pixels: the uint8
        # encoding deliberately wraps counts in the top 1/256 band of
        # the budget to 0 (reference parity), which would read as
        # "in-set" here exactly in the near-under-budget regime the
        # warning targets.
        from distributedmandelbrot_tpu.ops import compute_counts_perturb
        from distributedmandelbrot_tpu.ops.escape_time import (
            scale_counts_to_uint8)
        counts, _ = compute_counts_perturb(dspec, max_iter,
                                           dtype=np_dtype,
                                           julia_c=julia_c, bla=bla)
        _warn_if_deep_all_inset(counts, max_iter, span)
        values = np.asarray(scale_counts_to_uint8(
            counts, max_iter=max_iter)).ravel()
        return value_to_rgba(values.reshape(definition, definition),
                             colormap=colormap)

    cx, cy = float(c_re), float(c_im)
    jc = (complex(float(julia_c[0]), float(julia_c[1]))
          if julia_c is not None else None)
    spec = TileSpec(cx - span / 2, cy - span / 2, span, span,
                    width=definition, height=definition)
    if smooth:
        # f32 smooth throughput path: Pallas on TPU, XLA otherwise
        # (Mandelbrot and Julia both ride the same kernel).
        nu = pallas_first("compute_tile_smooth_pallas", spec, max_iter,
                          julia_c=jc) if np_dtype == np.float32 else None
        if nu is None:
            from distributedmandelbrot_tpu.ops import compute_tile_smooth
            nu = compute_tile_smooth(spec, max_iter, dtype=np_dtype,
                                     julia_c=jc)
        return smooth_to_rgba(nu, max_iter, colormap=colormap,
                              normalize=normalize)
    if np_dtype == np.float32:
        # Integer f32 fast path, same Pallas-first policy.
        values = (pallas_first("compute_tile_pallas", spec, max_iter)
                  if jc is None else
                  pallas_first("compute_tile_julia_pallas", spec, jc,
                               max_iter))
        if values is not None:
            return value_to_rgba(values.reshape(spec.height, spec.width),
                                 colormap=colormap)
    if jc is not None:
        from distributedmandelbrot_tpu.ops import compute_tile_julia
        values = compute_tile_julia(spec, jc, max_iter, dtype=np_dtype)
    else:
        from distributedmandelbrot_tpu.ops import compute_tile
        values = compute_tile(spec, max_iter, dtype=np_dtype)
    return value_to_rgba(values.reshape(spec.height, spec.width),
                         colormap=colormap)


def _resolve_family(fractal: str, power: int | None
                    ) -> tuple[int, bool] | None:
    """(power, burning) for the extended families, None for the core
    fractals — with --power placement validation (shared by render and
    animate so their behavior can never diverge)."""
    if fractal == "ship":
        if power is not None:
            raise SystemExit("--power applies to multibrot only "
                             "(the burning ship is degree 2)")
        return (2, True)
    if fractal == "multibrot":
        p = 3 if power is None else power
        if p < 2:
            raise SystemExit("--power must be >= 2")
        return (p, False)
    if power is not None:
        raise SystemExit("--power applies to --fractal multibrot only")
    return None


def _save_png(path: str, rgba) -> None:
    import matplotlib
    matplotlib.use("Agg")
    from matplotlib import pyplot as plt
    plt.imsave(path, rgba)
    print(f"wrote {path} ({rgba.shape[1]}x{rgba.shape[0]})")


def cmd_coordinator(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu coordinator",
        description="Run the tile coordinator (Distributer + DataServer).")
    parser.add_argument("-l", "--levels", required=True,
                        help="level:max_iter[,level:max_iter...] "
                             "(e.g. 4:256,10:1024,20:1024)")
    parser.add_argument("-o", "--data-dir", default="",
                        help="parent directory for Data/ (default: cwd)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--distributer-port", type=int,
                        default=proto.DEFAULT_DISTRIBUTER_PORT)
    parser.add_argument("--dataserver-port", type=int,
                        default=proto.DEFAULT_DATASERVER_PORT)
    parser.add_argument("--lease-timeout", type=float,
                        default=proto.DEFAULT_LEASE_TIMEOUT,
                        help="seconds a worker has to return a tile")
    parser.add_argument("--sweep-period", type=float,
                        default=proto.DEFAULT_SWEEP_PERIOD,
                        help="seconds between expired-lease sweeps")
    parser.add_argument("--fsync-index", action="store_true",
                        help="fsync the tile index on every append")
    parser.add_argument("--read-timeout", type=float,
                        default=proto.DEFAULT_READ_TIMEOUT,
                        help="per-read socket deadline in seconds "
                             "(reference's toggleable receive timeout)")
    parser.add_argument("--no-read-timeout", action="store_true",
                        help="disable socket read deadlines "
                             "(reference: -t false)")
    parser.add_argument("--checkpoint-period", type=float, default=0.0,
                        help="write a durability checkpoint every N seconds "
                             "(0 disables; restart then replays the full "
                             "index instead of a suffix)")
    parser.add_argument("--stats-period", type=float, default=60.0,
                        help="seconds between progress/throughput log "
                             "lines (0 disables)")
    parser.add_argument("--exporter-port", type=int,
                        default=proto.DEFAULT_EXPORTER_PORT,
                        help="HTTP metrics port (/metrics, /varz, "
                             "/healthz); 0 = ephemeral, -1 disables")
    # Per-channel log toggles (reference: -dli/-dle/-sli/-sle,
    # Program.cs:305-325,362-381).
    parser.add_argument("--distributer-log-info", choices=["true", "false"],
                        default="true")
    parser.add_argument("--distributer-log-error", choices=["true", "false"],
                        default="true")
    parser.add_argument("--data-server-log-info", choices=["true", "false"],
                        default="true")
    parser.add_argument("--data-server-log-error", choices=["true", "false"],
                        default="true")
    parser.add_argument("--no-info-log", action="store_true")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)
    _configure_channel_logging(args)

    from distributedmandelbrot_tpu.coordinator import Coordinator
    from distributedmandelbrot_tpu.storage.ownership import LevelOwnedError
    from distributedmandelbrot_tpu.storage.store import DataDirError

    settings = parse_level_settings(args.levels)
    try:
        coordinator = Coordinator(
            settings, data_dir_parent=args.data_dir, host=args.host,
            distributer_port=args.distributer_port,
            dataserver_port=args.dataserver_port,
            lease_timeout=args.lease_timeout, sweep_period=args.sweep_period,
            read_timeout=None if args.no_read_timeout else args.read_timeout,
            fsync_index=args.fsync_index, stats_period=args.stats_period,
            checkpoint_period=args.checkpoint_period,
            exporter_port=(None if args.exporter_port < 0
                           else args.exporter_port))
    except (DataDirError, LevelOwnedError) as e:
        # Clean pre-start failures (reference: Program.cs:159-176 prints
        # and exits on an unwritable -o): no traceback, exit code 1.
        raise SystemExit(f"dmtpu coordinator: {e}")
    total = coordinator.scheduler.total_tiles
    done = coordinator.scheduler.completed_count
    print(f"coordinator: {len(settings)} level(s), {total} tiles "
          f"({done} already complete on disk)", flush=True)
    try:
        asyncio.run(coordinator.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_serve(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu serve",
        description="Run a serving coordinator: Distributer + DataServer + "
                    "tile gateway (cache, coalescing, compute-on-read, "
                    "admission control).")
    parser.add_argument("-l", "--levels", required=True,
                        help="level:max_iter[,level:max_iter...]")
    parser.add_argument("-o", "--data-dir", default="",
                        help="parent directory for Data/ (default: cwd)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--distributer-port", type=int,
                        default=proto.DEFAULT_DISTRIBUTER_PORT)
    parser.add_argument("--dataserver-port", type=int,
                        default=proto.DEFAULT_DATASERVER_PORT)
    parser.add_argument("--gateway-port", type=int,
                        default=proto.DEFAULT_GATEWAY_PORT)
    parser.add_argument("--lease-timeout", type=float,
                        default=proto.DEFAULT_LEASE_TIMEOUT)
    parser.add_argument("--sweep-period", type=float,
                        default=proto.DEFAULT_SWEEP_PERIOD)
    parser.add_argument("--fsync-index", action="store_true")
    parser.add_argument("--read-timeout", type=float,
                        default=proto.DEFAULT_READ_TIMEOUT)
    parser.add_argument("--no-read-timeout", action="store_true")
    parser.add_argument("--stats-period", type=float, default=60.0)
    parser.add_argument("--checkpoint-period", type=float, default=0.0,
                        help="write a durability checkpoint every N seconds "
                             "(0 disables)")
    parser.add_argument("--cache-tiles", type=int, default=256,
                        help="decoded-tile LRU capacity, in tiles")
    parser.add_argument("--render-cache-tiles", type=int, default=64,
                        help="rendered palette-PNG LRU capacity, in "
                             "entries (one per tile+colormap)")
    parser.add_argument("--max-queue-depth", type=int, default=1024,
                        help="max queries in service before shedding "
                             "with OVERLOADED")
    parser.add_argument("--rate", type=float, default=None,
                        help="token-bucket refill rate in queries/s "
                             "(default: unlimited)")
    parser.add_argument("--burst", type=float, default=256.0,
                        help="token-bucket capacity (burst size)")
    parser.add_argument("--ondemand-deadline", type=float,
                        default=proto.DEFAULT_ONDEMAND_DEADLINE,
                        help="seconds a miss may wait for the farm to "
                             "compute the tile before NOT_AVAILABLE")
    parser.add_argument("--exporter-port", type=int,
                        default=proto.DEFAULT_EXPORTER_PORT,
                        help="HTTP metrics port (/metrics, /varz, "
                             "/healthz); 0 = ephemeral, -1 disables")
    parser.add_argument("--sample-period", type=float, default=2.0,
                        help="seconds between /timeseries snapshots of "
                             "the registry")
    parser.add_argument("--history-window", type=float, default=600.0,
                        help="seconds of timeseries history kept in the "
                             "ring buffer")
    parser.add_argument("--no-info-log", action="store_true")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    from distributedmandelbrot_tpu.coordinator import Coordinator
    from distributedmandelbrot_tpu.storage.ownership import LevelOwnedError
    from distributedmandelbrot_tpu.storage.store import DataDirError

    settings = parse_level_settings(args.levels)
    try:
        coordinator = Coordinator(
            settings, data_dir_parent=args.data_dir, host=args.host,
            distributer_port=args.distributer_port,
            dataserver_port=args.dataserver_port,
            lease_timeout=args.lease_timeout, sweep_period=args.sweep_period,
            read_timeout=None if args.no_read_timeout else args.read_timeout,
            fsync_index=args.fsync_index, stats_period=args.stats_period,
            checkpoint_period=args.checkpoint_period,
            sample_period=args.sample_period,
            history_window=args.history_window,
            gateway_port=args.gateway_port,
            gateway_cache_tiles=args.cache_tiles,
            gateway_render_tiles=args.render_cache_tiles,
            gateway_max_queue_depth=args.max_queue_depth,
            gateway_rate=args.rate, gateway_burst=args.burst,
            ondemand_deadline=args.ondemand_deadline,
            exporter_port=(None if args.exporter_port < 0
                           else args.exporter_port))
    except (DataDirError, LevelOwnedError) as e:
        raise SystemExit(f"dmtpu serve: {e}")
    total = coordinator.scheduler.total_tiles
    done = coordinator.scheduler.completed_count
    print(f"serve: {len(settings)} level(s), {total} tiles "
          f"({done} already complete on disk); gateway on port "
          f"{args.gateway_port}", flush=True)
    try:
        asyncio.run(coordinator.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


def _make_backend(name: str, dtype: str | None, kernel: str = "auto",
                  definition: int | None = None):
    # dtype None = unpinned: auto picks per platform (native f64 on CPU,
    # Pallas f32 on TPU); the explicit backends keep their f32 default.
    np_dtype = _NP_DTYPES[dtype] if dtype is not None else np.float32
    kw = {} if definition is None else {"definition": definition}
    if name == "numpy":
        from distributedmandelbrot_tpu.worker import NumpyBackend
        return NumpyBackend(**kw)
    if name == "native":
        from distributedmandelbrot_tpu.worker import NativeBackend
        return NativeBackend(**kw)
    if name == "jax":
        from distributedmandelbrot_tpu.worker import JaxBackend
        return JaxBackend(dtype=np_dtype, **kw)
    if name == "pallas":
        if dtype != "f32":
            raise SystemExit(
                "--backend pallas is f32-only (the TPU throughput path); "
                "use --backend jax for f64")
        from distributedmandelbrot_tpu.worker import PallasBackend
        return PallasBackend(**kw)
    if name == "auto":
        from distributedmandelbrot_tpu.worker import auto_backend
        return auto_backend(
            dtype=None if dtype is None else np_dtype, **kw)
    if name == "mesh":
        from distributedmandelbrot_tpu.parallel import MeshBackend
        return MeshBackend(dtype=np_dtype, kernel=kernel, **kw)
    raise ValueError(f"unknown backend {name!r}")


def cmd_worker(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu worker",
        description="Run a stateless pull-loop compute worker.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=proto.DEFAULT_DISTRIBUTER_PORT)
    parser.add_argument("--backend",
                        choices=["auto", "jax", "pallas", "numpy", "native",
                                 "mesh"],
                        default="auto",
                        help="auto = Pallas TPU kernel when a TPU is live, "
                             "else the portable JAX path")
    parser.add_argument("--dtype", choices=["f32", "f64"], default=None,
                        help="pin output precision (f32 fast paths / f64 "
                             "bit-exact paths); default: best per "
                             "platform for --backend auto, f32 otherwise")
    parser.add_argument("--batch-size", type=int, default=0,
                        help="tiles leased per exchange "
                             "(default: device count for mesh, else 1)")
    parser.add_argument("--poll", type=float, default=0.0,
                        help="keep polling every N seconds after the "
                             "coordinator drains (default: exit)")
    parser.add_argument("--window", type=int, default=-1,
                        help="pipelined executor: max tiles leased-but-"
                             "unsubmitted across the lease/dispatch/"
                             "materialize/upload stages; 0 = classic "
                             "two-stage overlap (default: 2*depth per "
                             "local device for backends with per-tile "
                             "dispatch handles, else 0)")
    parser.add_argument("--depth", type=int, default=2,
                        help="pipelined executor: kernels in flight per "
                             "device (default: 2 — double-buffered)")
    parser.add_argument("--upload-lanes", type=int, default=0,
                        help="parallel upload threads, each holding one "
                             "persistent session to the coordinator "
                             "(default 0 = one per local device, capped "
                             "at 4)")
    parser.add_argument("--batch-tiles", type=int, default=0,
                        help="pipelined executor: queued leases fused "
                             "into one megakernel launch per device "
                             "(pallas backends only; capped at --depth; "
                             "default 0 = fuse up to depth)")
    parser.add_argument("--grant-batch", type=int, default=0,
                        help="batched lease grants per session round "
                             "trip (FRAME_LEASE_REQN; default 0 = "
                             "fill the whole --window from one round "
                             "trip; tune down to share a thin frontier "
                             "across many workers)")
    parser.add_argument("--no-session", action="store_true",
                        help="force the legacy connection-per-exchange "
                             "wire protocol even against a session-"
                             "capable coordinator")
    parser.add_argument("--stats-json", metavar="PATH", default="",
                        help="on a drained exit, dump the worker's counter "
                             "snapshot and pipeline stage stats to PATH as "
                             "JSON (how bench.py --farm-workers collects "
                             "per-subprocess wire/lane metrics)")
    parser.add_argument("--exporter-port", type=int, default=-1,
                        help="HTTP metrics port for this worker (/varz, "
                             "/timeseries); 0 = ephemeral, -1 (default) "
                             "disables — workers stay fleet-visible "
                             "through span-reported stats on their "
                             "shards' /varz either way")
    parser.add_argument("--reconnect", type=int, default=0, metavar="N",
                        help="redial the coordinator up to N times per "
                             "exchange on connection failure (capped "
                             "exponential backoff + jitter; 0 = fail fast). "
                             "Lets a farm ride out a coordinator restart.")
    parser.add_argument("--ring", metavar="RING_JSON", default=None,
                        help="multi-home against a sharded control plane: "
                             "one session per shard from this ring config, "
                             "leases round-robined across shards, uploads "
                             "routed by key (overrides --host/--port; "
                             "implies the pipelined executor)")
    parser.add_argument("--kernel", choices=["auto", "xla", "pallas"],
                        default="auto",
                        help="compute kernel for the mesh backend")
    parser.add_argument("--profile", metavar="DIR", default="",
                        help="capture a jax.profiler trace of the run into "
                             "DIR (view with TensorBoard / Perfetto)")
    parser.add_argument("--multihost", action="store_true",
                        help="slice-spanning SPMD worker: run the SAME "
                             "invocation on every process of a multi-host "
                             "slice; the primary process leases/uploads "
                             "over TCP, all processes compute over the "
                             "global device mesh (survey §5.8)")
    parser.add_argument("--mh-coordinator", default=None,
                        help="jax.distributed coordinator address "
                             "(default: Cloud TPU auto-detection)")
    parser.add_argument("--mh-processes", type=int, default=None)
    parser.add_argument("--mh-process-id", type=int, default=None)
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    from distributedmandelbrot_tpu.worker import DistributerClient, Worker

    if args.multihost:
        # The SPMD worker always computes on the global mesh; --kernel
        # picks the per-device compute (auto = Pallas when every rank
        # can run it, else XLA), but per-tile --backend does not apply.
        if args.backend != "auto":
            raise SystemExit("--multihost ignores --backend (it always "
                             "computes on the global mesh); use --kernel "
                             "to pick the device kernel")
        import jax

        from distributedmandelbrot_tpu.parallel import multihost

        multihost.initialize(coordinator_address=args.mh_coordinator,
                             num_processes=args.mh_processes,
                             process_id=args.mh_process_id)
        per_dev = max(1, -(-args.batch_size // jax.device_count())) \
            if args.batch_size > 0 else 1
        if args.batch_size > 0 and per_dev * jax.device_count() \
                != args.batch_size:
            logger.warning(
                "--batch-size %d rounded to %d (the SPMD batch must be a "
                "multiple of the %d global devices)", args.batch_size,
                per_dev * jax.device_count(), jax.device_count())
        profiling = False
        if args.profile:
            jax.profiler.start_trace(args.profile)
            profiling = True
        try:
            rounds = multihost.run_spmd_worker(
                args.host, args.port, batch_per_device=per_dev,
                poll=args.poll,
                dtype=_NP_DTYPES[args.dtype or "f32"],
                kernel=args.kernel)
        finally:
            if profiling:
                jax.profiler.stop_trace()
                print(f"profile trace written to {args.profile}",
                      flush=True)
        if multihost.is_primary():
            print(f"multihost worker: drained after {rounds} round(s) "
                  f"({jax.process_count()} processes, "
                  f"{jax.device_count()} devices)", flush=True)
        return 0

    backend = _make_backend(args.backend, args.dtype, args.kernel)
    batch_size = args.batch_size
    if batch_size <= 0:
        if args.backend == "mesh":
            import jax
            batch_size = jax.local_device_count()
        else:
            batch_size = 1
    ring = None
    host, port = args.host, args.port
    if args.ring is not None:
        from distributedmandelbrot_tpu.control.ring import (HashRing,
                                                            RingConfigError)
        try:
            ring = HashRing.load(args.ring)
        except RingConfigError as e:
            raise SystemExit(f"dmtpu worker: {e}")
        if args.no_session:
            raise SystemExit("dmtpu worker: --ring needs sessions "
                             "(drop --no-session)")
        # The classic client doubles as the declined-hello fallback;
        # point it at shard 0 so single-shard rings still degrade sanely.
        host = ring.shards[0].host
        port = ring.shards[0].distributer_port
    window = args.window
    if window < 0:
        # Auto: pipeline backends with per-tile dispatch handles (they
        # profit from all four overlaps); classic overlap otherwise —
        # the mesh backend already fuses its own device-chained batch.
        if hasattr(backend, "dispatch_tile"):
            window = 2 * args.depth * max(1, len(backend.devices()))
        else:
            window = 0
    if ring is not None and window == 0:
        # Multi-homing lives in the pipelined session path; give ring
        # mode a minimal window rather than silently ignoring the ring.
        window = max(2, 2 * args.depth)
    worker = Worker(DistributerClient(host, port,
                                      reconnect_attempts=args.reconnect),
                    backend,
                    batch_size=batch_size, window=window, depth=args.depth,
                    upload_lanes=args.upload_lanes,
                    batch_tiles=args.batch_tiles,
                    grant_batch=args.grant_batch,
                    use_session=not args.no_session,
                    ring=ring)
    exporter = None
    if args.exporter_port >= 0:
        from distributedmandelbrot_tpu.obs.exporter import ExporterThread
        from distributedmandelbrot_tpu.obs.timeseries import \
            TimeseriesSampler
        exporter = ExporterThread(
            worker.counters.registry,
            sampler=TimeseriesSampler(worker.counters.registry),
            varz_extra=lambda: {
                "role": "worker",
                "worker_id": format(worker.spans.worker_id, "016x")},
            port=args.exporter_port)
        exporter.start()
        print(f"worker exporter on port {exporter.port}", flush=True)
    profiling = False
    if args.profile:
        import jax
        jax.profiler.start_trace(args.profile)
        profiling = True
    try:
        if args.poll > 0:
            worker.run_forever(poll_interval=args.poll)
        else:
            rounds = worker.run_until_drained()
            stats = worker.counters.snapshot()
            print(f"worker: drained after {rounds} round(s); "
                  f"{stats.get('tiles_computed', 0)} tiles computed, "
                  f"{stats.get('results_accepted', 0)} accepted", flush=True)
            if worker.pipeline is not None:
                ss = worker.pipeline.stage_stats()
                occ = "  ".join(
                    f"{name}={s['occupancy']:.0%}"
                    for name, s in ss["stages"].items())
                print(f"pipeline stage occupancy: {occ} "
                      f"(window={worker.window}, depth={worker.depth})",
                      flush=True)
                fus = ss.get("fusion", {})
                if fus.get("launches"):
                    print(f"dispatch fusion: {fus['tiles']} tiles in "
                          f"{fus['launches']} launch(es), "
                          f"{fus['tiles_per_launch']:.1f} tiles/launch",
                          flush=True)
            if args.stats_json:
                import json
                payload = {"counters": stats, "rounds": rounds}
                if worker.pipeline is not None:
                    payload["stage_stats"] = worker.pipeline.stage_stats()
                with open(args.stats_json, "w", encoding="utf-8") as fh:
                    json.dump(payload, fh)
    except KeyboardInterrupt:
        pass
    except OSError as e:
        print(f"error: cannot reach coordinator at {args.host}:{args.port} "
              f"({e})", file=sys.stderr)
        return 1
    finally:
        if exporter is not None:
            exporter.stop()
        if profiling:
            import jax
            jax.profiler.stop_trace()
            print(f"profile trace written to {args.profile}", flush=True)
    return 0


def cmd_viewer(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu viewer",
        description="Fetch and render finished tiles.")
    parser.add_argument("level", type=int, nargs="?", default=None)
    parser.add_argument("index_real", type=int, nargs="?", default=None)
    parser.add_argument("index_imag", type=int, nargs="?", default=None)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=proto.DEFAULT_DATASERVER_PORT)
    parser.add_argument("--stitch", action="store_true",
                        help="fetch ALL chunks of the level into one image")
    parser.add_argument("--out", default=None,
                        help="write a PNG instead of opening a window")
    parser.add_argument("--colormap", default="jet")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    if args.level is None and args.stitch:
        parser.error("--stitch requires a level")
    if args.level is None:
        # No arguments: the reference viewer's interactive session
        # (DistributedMandelbrotViewer.py:147-152) — prompt for server
        # and chunk indices with the same prompts.  Closed stdin or
        # non-numeric answers exit with a clean usage error, not a
        # traceback.
        try:
            args.host = input("Server Addr> ") or args.host
            port_s = input("Server Port> ")
            args.port = int(port_s) if port_s else args.port
            args.level = int(input("Level> "))
            args.index_real = int(input("Index Re> "))
            args.index_imag = int(input("Index Im> "))
        except EOFError:
            parser.error("no arguments and no interactive input; "
                         "pass LEVEL [INDEX_RE INDEX_IM] (see --help)")
        except ValueError as e:
            parser.error(f"invalid numeric answer: {e}")

    from distributedmandelbrot_tpu.viewer import DataClient

    client = DataClient(args.host, args.port)
    try:
        return _viewer_fetch_and_render(parser, args, client)
    except OSError as e:
        print(f"error: cannot reach data server at {args.host}:{args.port} "
              f"({e})", file=sys.stderr)
        return 1


def _viewer_fetch_and_render(parser, args, client) -> int:
    from distributedmandelbrot_tpu.viewer import (FetchStatus, stitch_level,
                                                  value_to_rgba)

    if args.stitch:
        missing = []

        def fetch(i: int, j: int) -> Optional[np.ndarray]:
            pixels, status = client.fetch(args.level, i, j)
            if status is not FetchStatus.OK:
                missing.append((i, j))
                return None
            return pixels

        values = stitch_level(fetch, args.level)
        if missing:
            print(f"warning: {len(missing)} chunk(s) unavailable, "
                  f"rendered black: {missing[:8]}...", file=sys.stderr)
    else:
        if args.index_real is None or args.index_imag is None:
            parser.error("index_real and index_imag required unless --stitch")
        pixels, status = client.fetch(args.level, args.index_real,
                                      args.index_imag)
        if status is FetchStatus.NOT_AVAILABLE:
            print("Chunk isn't available")
            return 1
        if status is FetchStatus.REJECTED:
            print("Request was rejected (invalid indices)", file=sys.stderr)
            return 2
        values = pixels

    rgba = value_to_rgba(values, colormap=args.colormap)
    if args.out:
        _save_png(args.out, rgba)
    else:  # pragma: no cover - needs a display
        from distributedmandelbrot_tpu.viewer import show
        show(rgba)
    return 0


def cmd_render(argv: Sequence[str]) -> int:
    """Local (farm-less) rendering of any view — Mandelbrot or Julia,
    integer or smooth coloring.  Capability extension; the reference can
    only view farm-computed chunks."""
    parser = argparse.ArgumentParser(
        prog="dmtpu render",
        description="Render a view locally on the default JAX backend.")
    parser.add_argument("--fractal",
                        choices=["mandelbrot", "julia", "multibrot", "ship"],
                        default="mandelbrot")
    parser.add_argument("--power", type=int, default=None,
                        help="multibrot degree d in z^d + c (>= 2; "
                             "default 3; multibrot only)")
    parser.add_argument("--c", default="-0.8,0.156",
                        help="Julia constant as RE,IM")
    parser.add_argument("--center", default=None,
                        help="view center (default: -0.5,0 for mandelbrot, "
                             "0,0 for julia)")
    parser.add_argument("--span", type=float, default=3.0,
                        help="view side length in the complex plane")
    parser.add_argument("--definition", type=int, default=1024,
                        help="output pixels per side")
    parser.add_argument("--max-iter", type=int, default=256)
    parser.add_argument("--smooth", action="store_true",
                        help="band-free continuous coloring (defaults to "
                             "the f64 quality path; --dtype f32 selects "
                             "the fast path)")
    parser.add_argument("--deep", action="store_true",
                        help="perturbation deep zoom: center taken at "
                             "arbitrary decimal precision, valid at any "
                             "span (auto-selected below 1e-12)")
    parser.add_argument("--bla", action="store_true",
                        help="force the bilinear-approximation fast path "
                             "for deep renders, integer or --smooth "
                             "(ops/bla.py): skips orbit segments where "
                             "the delta recurrence is effectively linear "
                             "— up to ~10x on slow (parabolic / minibrot-"
                             "margin) deep views.  Approximate by "
                             "contract: escapes inside a skipped segment "
                             "are detected at its end; smooth freeze "
                             "values stay exact (the table's z_cap "
                             "guard).  Default (neither flag): a cheap "
                             "probe auto-enables BLA exactly where it "
                             "wins")
    parser.add_argument("--no-bla", action="store_true",
                        help="force the exact delta scan (disable the "
                             "BLA auto-probe)")
    parser.add_argument("--dtype", choices=["f32", "f64"], default=None,
                        help="arithmetic width (the algorithm still auto-selects: sub-f32-resolution f32 renders use f32 perturbation); default: f64 for --smooth, f32 otherwise")
    parser.add_argument("--colormap", default="jet")
    parser.add_argument("--normalize", action="store_true",
                        help="stretch the view's own escaped-value range "
                             "over the full colormap (--smooth only): "
                             "deep windows occupy a sliver of the "
                             "absolute scale and render near-flat "
                             "without it; not offered for animate, "
                             "where a per-frame stretch would flicker")
    parser.add_argument("--supersample", type=int, choices=[2, 4], default=1,
                        help="anti-aliasing: N subpixel samples per pixel, "
                             "averaged in color space.  On TPU the integer "
                             "f32 paths compute all samples in one "
                             "interleaved kernel pass (~1.6x a plain "
                             "render, not Nx); other paths sample "
                             "sequentially")
    _add_no_pallas(parser)
    parser.add_argument("--out", required=True, help="output PNG path")
    _add_common(parser)
    # argparse rejects negative-valued "--c -0.8,0.156" (looks like an
    # option); pre-join such pairs into "--c=-0.8,0.156".
    args = parser.parse_args(_join_negative_values(argv, ("--c", "--center")))
    _configure_logging(args)

    family = _resolve_family(args.fractal, args.power)
    if args.normalize and not args.smooth:
        raise SystemExit("--normalize applies to --smooth renders only "
                         "(integer output is already quantized upstream)")
    if family is not None:
        if args.deep:
            raise SystemExit(f"--fractal {args.fractal} has no perturbation "
                             "path (no --deep)")
        if args.span < DEEP_SPAN_THRESHOLD:
            raise SystemExit(f"--fractal {args.fractal} has no perturbation "
                             f"path; spans below {DEEP_SPAN_THRESHOLD} alias "
                             "float64 pixel coordinates")
    default_center = "0,0" if args.fractal == "julia" else "-0.5,0.0"
    center_str = args.center or default_center
    c_re, c_im = (s.strip() for s in center_str.split(","))
    julia_c = tuple(s.strip() for s in args.c.split(",")) \
        if args.fractal == "julia" else None
    np_dtype = _resolve_dtype(args, center=(float(c_re), float(c_im)),
                              can_perturb=family is None)
    # --bla applicability follows the ACTUAL routing decision (round-3
    # advisor: gating on the raw span threshold wrongly rejected views
    # that _auto_deep routes to f32 perturbation, e.g. span 1e-8 at
    # high definition).  Resolved ONCE here and passed down, so the
    # guard and the render agree by construction (same pattern as
    # cmd_animate's per-frame resolution).
    deep = _resolve_deep(True if args.deep else None, args.span,
                         float(c_re), float(c_im), args.definition,
                         np_dtype, family)
    if args.bla and args.no_bla:
        raise SystemExit("--bla and --no-bla are mutually exclusive")
    if args.bla and not deep:
        raise SystemExit("--bla applies to perturbation deep renders "
                         "(--deep, or a view the auto-selector routes "
                         "to perturbation); this view renders on the "
                         "direct kernels, which have no orbit to skip")
    rgba = _render_view(c_re, c_im, args.span, args.definition,
                        args.max_iter, smooth=args.smooth,
                        np_dtype=np_dtype,
                        colormap=args.colormap,
                        deep=deep,
                        julia_c=julia_c, family=family,
                        no_pallas=args.no_pallas,
                        normalize=args.normalize,
                        supersample=args.supersample,
                        bla=_resolve_bla(args))
    _save_png(args.out, rgba)
    return 0


def cmd_animate(argv: Sequence[str]) -> int:
    """Zoom animation: a geometric span sweep rendered frame by frame
    (the view-level shape of BASELINE config 5's 60-frame zoom).  Frames
    switch automatically from the direct kernels to perturbation once
    the span drops below float64's useful pixel pitch, so one animation
    can run from the full set down to ~1e-30 without banding or
    pixelation."""
    parser = argparse.ArgumentParser(
        prog="dmtpu animate",
        description="Render a zoom animation as numbered PNG frames.")
    parser.add_argument("--center", required=True,
                        help="zoom target as RE,IM (decimal strings — "
                             "precision beyond float64 is honored on "
                             "deep frames)")
    parser.add_argument("--fractal",
                        choices=["mandelbrot", "julia", "multibrot", "ship"],
                        default="mandelbrot")
    parser.add_argument("--power", type=int, default=None,
                        help="multibrot degree d in z^d + c (>= 2; "
                             "default 3; multibrot only)")
    parser.add_argument("--c", default="-0.8,0.156",
                        help="Julia constant as RE,IM")
    parser.add_argument("--span-start", type=float, default=4.0)
    parser.add_argument("--span-end", type=float, default=1e-6)
    parser.add_argument("--frames", type=int, default=60)
    parser.add_argument("--definition", type=int, default=512)
    parser.add_argument("--max-iter", type=int, default=1000)
    parser.add_argument("--max-iter-end", type=int, default=None,
                        help="budget for the LAST frame; per-frame "
                             "budgets interpolate geometrically from "
                             "--max-iter, matching the span sweep — "
                             "escape depths grow with zoom, so a flat "
                             "budget either starves the deep frames "
                             "(uniform in-set tiles) or overpays on "
                             "the shallow ones")
    parser.add_argument("--smooth", action="store_true",
                        help="band-free coloring on every frame")
    parser.add_argument("--dtype", choices=["f32", "f64"], default=None,
                        help="arithmetic width (the algorithm still auto-selects: sub-f32-resolution f32 renders use f32 perturbation); default: f64 for --smooth, f32 otherwise")
    parser.add_argument("--colormap", default="jet")
    parser.add_argument("--supersample", type=int, choices=[2, 4], default=1,
                        help="anti-aliasing per frame (see dmtpu render "
                             "--supersample); zoom animations flicker "
                             "visibly less with it")
    parser.add_argument("--bla", action="store_true",
                        help="force the BLA fast path for the deep "
                             "(perturbation) frames — see dmtpu render "
                             "--bla; direct-kernel frames are unaffected; "
                             "default: per-orbit auto-probe")
    parser.add_argument("--no-bla", action="store_true",
                        help="force the exact delta scan (disable the "
                             "BLA auto-probe)")
    _add_no_pallas(parser)
    parser.add_argument("--out-dir", required=True,
                        help="directory for frame_NNNN.png files")
    parser.add_argument("--gif", metavar="PATH", default=None,
                        help="additionally assemble the frames into an "
                             "animated GIF at PATH (PIL; no ffmpeg "
                             "needed)")
    parser.add_argument("--frame-ms", type=int, default=80,
                        help="GIF frame duration in milliseconds")
    _add_common(parser)
    args = parser.parse_args(
        _join_negative_values(argv, ("--center", "--c")))
    _configure_logging(args)
    if args.frames < 1:
        raise SystemExit("--frames must be >= 1")
    if args.span_end <= 0 or args.span_start <= 0:
        raise SystemExit("spans must be positive")
    if args.bla and args.no_bla:
        raise SystemExit("--bla and --no-bla are mutually exclusive")


    import os
    import time

    family = _resolve_family(args.fractal, args.power)
    if family is not None and min(args.span_start,
                                  args.span_end) < DEEP_SPAN_THRESHOLD:
        # min of both ends: a zoom-OUT run starts at the small span.
        raise SystemExit(f"--fractal {args.fractal} has no perturbation "
                         f"path; spans below {DEEP_SPAN_THRESHOLD} "
                         "would alias float64 pixel coordinates")

    os.makedirs(args.out_dir, exist_ok=True)
    c_re, c_im = (s.strip() for s in args.center.split(","))
    julia_c = tuple(s.strip() for s in args.c.split(",")) \
        if args.fractal == "julia" else None
    np_dtype = _resolve_dtype(args, center=(float(c_re), float(c_im)),
                              can_perturb=family is None)
    ratio = (args.span_end / args.span_start) ** (
        1.0 / max(1, args.frames - 1))
    if args.max_iter < 1:
        raise SystemExit("--max-iter must be >= 1")
    if args.max_iter_end is not None and args.max_iter_end < 1:
        raise SystemExit("--max-iter-end must be >= 1")
    mi_ratio = ((args.max_iter_end / args.max_iter) ** (
        1.0 / max(1, args.frames - 1))
        if args.max_iter_end is not None else 1.0)

    t0 = time.monotonic()
    for f in range(args.frames):
        span = args.span_start * ratio ** f
        max_iter = max(1, round(args.max_iter * mi_ratio ** f))
        # The decision is made once and passed down, so the progress
        # label can never disagree with the path actually rendered.
        deep = _resolve_deep(None, span, float(c_re), float(c_im),
                             args.definition, np_dtype, family)
        rgba = _render_view(c_re, c_im, span, args.definition,
                            max_iter, smooth=args.smooth,
                            np_dtype=np_dtype, colormap=args.colormap,
                            deep=deep, julia_c=julia_c, family=family,
                            no_pallas=args.no_pallas,
                            supersample=args.supersample,
                            bla=_resolve_bla(args))
        path = os.path.join(args.out_dir, f"frame_{f:04d}.png")
        _save_png(path, rgba)
        print(f"frame {f + 1}/{args.frames} span {span:.3g} "
              f"mi {max_iter}{' (deep)' if deep else ''} -> {path}",
              flush=True)
    dt = time.monotonic() - t0
    pixels = args.frames * args.definition * args.definition
    print(f"animation done: {args.frames} frames in {dt:.1f}s, "
          f"{pixels / dt / 1e6:.2f} Mpix/s end-to-end", flush=True)
    if args.gif:
        from PIL import Image

        def frame(f):
            return Image.open(
                os.path.join(args.out_dir, f"frame_{f:04d}.png")).convert(
                    "P", palette=Image.Palette.ADAPTIVE)

        # Stream the tail frames through a generator: a deep zoom runs to
        # hundreds of frames, and materializing them all (this command's
        # own use case) would hold gigabytes before the save.
        frame(0).save(args.gif, save_all=True,
                      append_images=(frame(f) for f in
                                     range(1, args.frames)),
                      duration=args.frame_ms, loop=0)
        print(f"wrote {args.gif} ({args.frames} frames @ "
              f"{args.frame_ms}ms)", flush=True)
    return 0


def cmd_compact(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu compact",
        description="Offline maintenance: rewrite the append-only tile "
                    "index with one (last-wins) entry per tile and delete "
                    "chunk files nothing references.  Claims every level "
                    "in the index, so it fails loudly if a coordinator is "
                    "running on the same data directory.")
    parser.add_argument("-o", "--data-dir", default="",
                        help="parent directory of Data/ (default: cwd)")
    parser.add_argument("--keep-orphans", action="store_true",
                        help="only rewrite the index; leave unreferenced "
                             "chunk files in place")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    from distributedmandelbrot_tpu.storage.index import CorruptIndexError
    from distributedmandelbrot_tpu.storage.ownership import LevelOwnedError
    from distributedmandelbrot_tpu.storage.store import DataDirError, compact

    try:
        stats = compact(args.data_dir,
                        remove_orphans=not args.keep_orphans)
    except (DataDirError, LevelOwnedError, CorruptIndexError,
            RuntimeError) as e:
        raise SystemExit(f"dmtpu compact: {e}")
    print(f"compacted: {stats['entries_before']} -> "
          f"{stats['entries_after']} entries, "
          f"{stats['orphans_removed']} orphan file(s) removed, "
          f"index now {stats['index_bytes']} bytes", flush=True)
    return 0


def _fetch_varz(host: str, port: int, timeout: float) -> dict:
    import json
    import urllib.request
    url = f"http://{host}:{port}/varz"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _print_varz(varz: dict) -> None:
    sched = varz.get("scheduler")
    if sched:
        print(f"progress: {sched.get('completed', 0)}/{sched.get('total', 0)}"
              f" tiles complete, {sched.get('outstanding_leases', 0)} leased,"
              f" frontier depth {sched.get('frontier_depth', 0)}")
    gauges = varz.get("gauges", {})
    if gauges:
        print("gauges:")
        for name in sorted(gauges):
            print(f"  {name:<40} {gauges[name]:.4g}")
    counters = varz.get("counters", {})
    if counters:
        print("counters:")
        for name in sorted(counters):
            print(f"  {name:<40} {counters[name]}")
    hists = varz.get("histograms", {})
    if hists:
        print(f"histograms:{'':<36} count      p50      p90      p99")
        for name in sorted(hists):
            h = hists[name]
            print(f"  {name:<40} {h.get('count', 0):>5}"
                  f" {h.get('p50', 0.0):>8.4f} {h.get('p90', 0.0):>8.4f}"
                  f" {h.get('p99', 0.0):>8.4f}")
    trace = varz.get("trace")
    if trace:
        skew = (trace.get("worker_skew") or {}).get("skew")
        print(f"trace: {trace.get('recorded', 0)} events "
              f"({trace.get('dropped', 0)} dropped), "
              f"{trace.get('complete_spans', 0)}/{trace.get('spans', 0)} "
              f"complete spans, worker skew "
              + (f"{skew:.2f}" if skew is not None else "n/a"))
        workers = (trace.get("worker_skew") or {}).get("workers") or {}
        for wid in sorted(workers):
            w = workers[wid]
            print(f"  {wid:<40} {w.get('tiles', 0)} tiles, "
                  f"{w.get('busy_s', 0.0):.3f}s busy "
                  f"({w.get('busy_source', 'lease')})")
    farm = varz.get("farm_trace")
    if farm and farm.get("tiles"):
        print(f"critical path ({farm['tiles']} tiles, "
              f"{farm.get('attributed_tiles', 0)} span-attributed):")
        for phase in ("queue", "compute", "d2h", "upload", "persist",
                      "other"):
            secs = farm.get(f"{phase}_s", 0.0)
            share = farm.get(f"{phase}_share", 0.0)
            print(f"  {phase:<10} {secs:>10.3f}s  {share * 100:5.1f}%")


def cmd_stats(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu stats",
        description="Fetch and pretty-print a running coordinator's /varz "
                    "(counters, gauges, histogram percentiles, trace "
                    "summary) from its metrics exporter.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=proto.DEFAULT_EXPORTER_PORT)
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="HTTP fetch timeout in seconds")
    parser.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                        help="refresh every SECS seconds until interrupted")
    parser.add_argument("--json", action="store_true",
                        help="dump raw /varz JSON instead of pretty text")
    args = parser.parse_args(argv)

    import json

    while True:
        try:
            varz = _fetch_varz(args.host, args.port, args.timeout)
        except OSError as e:
            raise SystemExit(
                f"dmtpu stats: cannot fetch http://{args.host}:{args.port}"
                f"/varz: {e}")
        if args.json:
            print(json.dumps(varz, indent=1, sort_keys=True), flush=True)
        else:
            _print_varz(varz)
        if args.watch <= 0:
            return 0
        print(flush=True)
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


def cmd_trace(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu trace",
        description="Dump a running coordinator's merged farm timeline "
                    "(coordinator lifecycle + clock-aligned worker spans) "
                    "as Chrome trace-event JSON from the metrics "
                    "exporter's /trace.json.  Load the file at "
                    "https://ui.perfetto.dev or chrome://tracing.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=proto.DEFAULT_EXPORTER_PORT)
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="HTTP fetch timeout in seconds")
    parser.add_argument("--out", default="-", metavar="PATH",
                        help="output path ('-' for stdout)")
    args = parser.parse_args(argv)

    import json
    import urllib.request
    url = f"http://{args.host}:{args.port}/trace.json"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            trace = json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError) as e:
        raise SystemExit(f"dmtpu trace: cannot fetch {url}: {e}")
    body = json.dumps(trace, indent=1)
    if args.out == "-":
        print(body, flush=True)
    else:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
        n = len(trace.get("traceEvents", []))
        print(f"wrote {n} trace events -> {args.out} "
              f"(load at https://ui.perfetto.dev)", flush=True)
    return 0


def cmd_postmortem(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu postmortem",
        description="Merge a directory of flight-recorder dumps (one "
                    "JSONL per process; DMTPU_FLIGHT_DIR made them) "
                    "into one causally-ordered cross-process timeline, "
                    "reconstruct the leases in flight when each process "
                    "died, and run the anomaly detectors (grant without "
                    "accept, lease ping-pong, redirect loops, double "
                    "commits, retry storms).  Corrupt dumps never abort "
                    "the assembly; bad lines are counted and a partial "
                    "timeline renders.")
    parser.add_argument("dump_dir", metavar="DIR",
                        help="directory of flight-*.jsonl dumps")
    fmt = parser.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true",
                     help="full timeline + anomalies as JSON")
    fmt.add_argument("--chrome", action="store_true",
                     help="Chrome trace-event JSON (ui.perfetto.dev)")
    parser.add_argument("--limit", type=int, default=200, metavar="N",
                        help="text mode: show the last N merged events "
                             "(default 200; 0 = all)")
    parser.add_argument("--out", default="-", metavar="PATH",
                        help="output path ('-' for stdout)")
    args = parser.parse_args(argv)

    import json

    from distributedmandelbrot_tpu.obs import postmortem

    pm = postmortem.assemble(args.dump_dir)
    if args.json:
        body = json.dumps(pm.to_dict(), indent=1, sort_keys=True)
    elif args.chrome:
        body = json.dumps(pm.to_chrome())
    else:
        body = pm.render_text(limit=args.limit or None)
    if args.out == "-":
        print(body, flush=True)
    else:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
        print(f"wrote postmortem of {len(pm.dumps)} dump(s), "
              f"{len(pm.timeline)} events, {len(pm.anomalies)} "
              f"anomalies -> {args.out}", flush=True)
    if not pm.dumps:
        print(f"dmtpu postmortem: no readable dumps in "
              f"{args.dump_dir}", file=sys.stderr)
        return 1
    return 0


def cmd_admin(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu admin",
        description="Administrative actions against a running "
                    "coordinator's metrics exporter.")
    parser.add_argument("action", choices=["checkpoint"],
                        help="checkpoint: write a durability checkpoint "
                             "now (POST /checkpoint) and print its stats")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int,
                        default=proto.DEFAULT_EXPORTER_PORT)
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="HTTP timeout in seconds (checkpoint writes "
                             "are fsync'd; allow for slow disks)")
    args = parser.parse_args(argv)

    import json
    import urllib.request
    url = f"http://{args.host}:{args.port}/checkpoint"
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            stats = json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError) as e:
        raise SystemExit(f"dmtpu admin checkpoint: cannot POST {url}: {e}")
    print(json.dumps(stats, indent=1, sort_keys=True), flush=True)
    return 0


def cmd_check(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu check",
        description="Run the project-native static analysis suite "
                    "(lock discipline incl. interprocedural propagation, "
                    "async hygiene, wire-format parity, protocol "
                    "conformance, resource lifecycle, metric-name "
                    "registration, JAX purity, wire-input taint tracking, "
                    "exception-path leaks, protocol state-machine "
                    "exploration) over the package.  Exits 0 "
                    "when clean, 1 when there are unsuppressed findings.")
    parser.add_argument("--json", action="store_true",
                        help="emit the versioned JSON report instead of text")
    parser.add_argument("--rules", nargs="+", metavar="RULE",
                        help="run only these rule ids or families; "
                             "space- or comma-separated "
                             "(e.g. --rules taint,exc or --rules proto res)")
    parser.add_argument("--severity", choices=("error", "warn", "warning"),
                        default=None,
                        help="report only findings at or above this "
                             "severity (error = errors only; warn/warning "
                             "= everything, the default)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: the checkout "
                             "containing the installed package)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: "
                             "<root>/tools/lint_baseline.json if present)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline file to grandfather "
                             "every current finding, then exit 0")
    parser.add_argument("--diff", metavar="GIT_REF", default=None,
                        help="report only findings introduced since the "
                             "given git ref (fingerprint-based; findings "
                             "already present at the ref are treated as "
                             "an ephemeral baseline) — fast pre-commit runs")
    parser.add_argument("--fsm-dump", metavar="DOT_PATH", default=None,
                        help="extract the protocol endpoint automata and "
                             "write them as Graphviz DOT to this path, "
                             "then exit (no rules are run)")
    parser.add_argument("--profile", action="store_true",
                        help="print per-rule-family wall-clock timings to "
                             "stderr after the run")
    args = parser.parse_args(argv)
    if args.rules:
        # --rules taint,exc and --rules taint exc are both accepted.
        args.rules = [tok for arg in args.rules
                      for tok in arg.split(",") if tok]

    # Imported lazily so `dmtpu coordinator` & co. never pay for it; the
    # analysis package itself never imports jax (gated by the tier-1 test).
    from distributedmandelbrot_tpu import analysis

    if args.list_rules:
        for rule in sorted(analysis.all_rules().values(),
                           key=lambda r: (r.family, r.id)):
            print(f"{rule.id:20} {rule.severity:8} [{rule.family}] "
                  f"{rule.doc}")
        return 0

    root = args.root or analysis.default_root()
    import os
    baseline_path = args.baseline or os.path.join(
        str(root), "tools", "lint_baseline.json")
    project = analysis.Project.from_root(root)

    if args.fsm_dump:
        from distributedmandelbrot_tpu.analysis import fsm
        pairs = fsm.build_pairs(project)
        with open(args.fsm_dump, "w", encoding="utf-8") as fh:
            fh.write(fsm.to_dot(pairs))
        print(f"dmtpu check: wrote {len(pairs)} exchange automaton "
              f"pair(s) -> {args.fsm_dump}")
        return 0

    timings: dict = {}
    try:
        if args.update_baseline:
            findings = analysis.check_project(project, args.rules)
            kept = [f for f in findings
                    if not (project.file(f.path) or _NO_FILE)
                    .is_suppressed(f.line, f.rule)]
            analysis.save_baseline(baseline_path, kept)
            print(f"dmtpu check: baseline rewritten with {len(kept)} "
                  f"finding(s) -> {baseline_path}")
            return 0
        baseline = (analysis.load_baseline(baseline_path)
                    if os.path.exists(baseline_path) else set())
        ref_fps: set = set()
        if args.diff:
            ref_fps = analysis.fingerprints_at_ref(root, args.diff,
                                                   args.rules)
        report = analysis.run_check(project, args.rules,
                                    baseline | ref_fps,
                                    timings=timings if args.profile
                                    else None)
        if ref_fps:
            # Ephemeral entries that no longer match are expected churn
            # (the point of --diff is that old findings went away or
            # moved), not stale committed-baseline entries.
            report.stale_baseline = [fp for fp in report.stale_baseline
                                     if fp not in ref_fps]
    except ValueError as e:
        print(f"dmtpu check: {e}", file=sys.stderr)
        return 2
    except KeyError as e:
        # Defensive: a fingerprint/file lookup on state that moved under
        # us (e.g. files deleted since a --diff ref) must degrade to a
        # diagnostic, not a traceback.
        print(f"dmtpu check: internal lookup failed for {e!s}",
              file=sys.stderr)
        return 2
    if args.severity == "error":
        report.findings = [f for f in report.findings
                           if f.severity == "error"]
    if args.profile:
        # stderr so the JSON report on stdout stays machine-parseable
        total = sum(timings.values())
        for fam, secs in sorted(timings.items(), key=lambda kv: -kv[1]):
            print(f"dmtpu check: {fam:14s} {secs:6.3f}s", file=sys.stderr)
        print(f"dmtpu check: {'total':14s} {total:6.3f}s", file=sys.stderr)
    print(analysis.render_json(report) if args.json
          else analysis.render_text(report))
    return 0 if report.clean else 1


def cmd_loadgen(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu loadgen",
        description="Open-loop storm harness for the gateway read path: "
                    "Poisson arrivals, Zipf tile popularity, scripted "
                    "flash-crowd phases, replica fleets over one shared "
                    "object store.  Reports p50/p99/p999, goodput vs "
                    "offered load, and the shed fraction.")
    parser.add_argument("--smoke", action="store_true",
                        help="self-check on a virtual clock against a "
                             "stub gateway — no sockets, no jax, no "
                             "matplotlib (CI-safe)")
    parser.add_argument("--phases",
                        default="steady:200x5,spike:1200x2,steady:200x3",
                        help="schedule spec: kind:rate[-hi]xduration "
                             "segments, comma-separated (kinds: steady, "
                             "spike, ramp; e.g. ramp:200-2000x5)")
    parser.add_argument("--level", type=int, default=8,
                        help="tile level whose keyspace the Zipf sampler "
                             "draws from")
    parser.add_argument("--zipf", type=float, default=1.1,
                        help="Zipf popularity exponent s (P(rank k) ~ "
                             "k**-s)")
    parser.add_argument("--seed", type=int, default=0,
                        help="schedule + sampler seed (same seed, same "
                             "storm)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="gateway replicas sharing one object store")
    parser.add_argument("--render", action="store_true",
                        help="issue rendered-tile queries (palette PNG "
                             "bodies) instead of raw codec payloads")
    parser.add_argument("--colormap", default="jet",
                        help="colormap for --render "
                             "(jet, viridis, plasma)")
    parser.add_argument("--rate", type=float, default=None,
                        help="per-replica admission token-bucket rate "
                             "(default: unlimited)")
    parser.add_argument("--burst", type=float, default=64.0,
                        help="per-replica token-bucket burst")
    parser.add_argument("--queue-depth", type=int, default=64,
                        help="per-replica cap on queries in service")
    parser.add_argument("--seed-tiles", type=int, default=16,
                        help="pre-seed the hottest N tiles into the "
                             "shared store before the storm")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="per-request client timeout (seconds)")
    parser.add_argument("--sessions", type=int, default=0, metavar="N",
                        help="interactive-session mode: deal arrivals "
                             "onto N panning sessions speaking the "
                             "session wire (trajectory tracking, "
                             "prefetch, per-session fairness)")
    parser.add_argument("--hot-share", type=float, default=0.0,
                        help="with --sessions: extra fraction of "
                             "arrivals routed to session 0 (the "
                             "flash-crowd fairness scenario)")
    parser.add_argument("--session-rate", type=float, default=None,
                        help="with --sessions: per-session admission "
                             "token rate (default: unlimited)")
    parser.add_argument("--session-burst", type=float, default=32.0,
                        help="with --sessions: per-session token burst")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    args = parser.parse_args(argv)

    # Lazy: the smoke path must work in the lint-only CI environment
    # (numpy + pytest, no jax/matplotlib), which the loadgen package and
    # the serve stack under it are built to allow.
    from distributedmandelbrot_tpu import loadgen

    try:
        phases = loadgen.parse_phases(args.phases)
    except ValueError as e:
        print(f"dmtpu loadgen: {e}", file=sys.stderr)
        return 2
    if args.sessions:
        schedule = loadgen.build_session_schedule(
            phases, level=args.level, sessions=args.sessions,
            seed=args.seed, zipf_s=args.zipf, hot_share=args.hot_share)
    else:
        sampler = loadgen.ZipfTiles(args.level, s=args.zipf,
                                    seed=args.seed)
        schedule = loadgen.build_schedule(phases, sampler, seed=args.seed)
    if not schedule:
        print("dmtpu loadgen: schedule is empty (rate 0?)", file=sys.stderr)
        return 2
    if args.sessions:
        return _loadgen_session_storm(args, phases, schedule,
                                      smoke=args.smoke)
    if args.smoke:
        return _loadgen_smoke(phases, schedule)
    return _loadgen_storm(args, phases, schedule)


def _loadgen_smoke(phases, schedule) -> int:
    """Virtual-clock self-check: stub gateway, deterministic, instant.

    The stub models a server with bounded concurrency: requests past its
    depth are shed immediately, admitted ones cost a fixed service time.
    The checks are consistency invariants, not performance numbers.
    """
    import asyncio

    from distributedmandelbrot_tpu import loadgen
    from distributedmandelbrot_tpu.loadgen import recorder as rec

    timebase = loadgen.VirtualTimebase()
    recorder = loadgen.StormRecorder()
    inflight = 0

    async def stub(level: int, i: int, j: int) -> tuple[str, int]:
        nonlocal inflight
        if inflight >= 64:
            return rec.OUTCOME_SHED, 0
        inflight += 1
        try:
            await timebase.sleep(0.1)  # 640/s capacity vs 1200/s spike
        finally:
            inflight -= 1
        return rec.OUTCOME_OK, 1024

    runner = loadgen.OpenLoopRunner(schedule, stub, recorder,
                                    timebase=timebase)

    async def drive() -> float:
        task = asyncio.ensure_future(runner.run())
        await timebase.drain(until=task)
        return task.result()

    duration = asyncio.run(drive())
    report = recorder.report(duration=duration,
                             offered=loadgen.schedule.offered_rate(schedule),
                             phases=[p.name for p in phases])
    issued = report["requests"]
    settled = (report["completed"] + report["shed"]
               + report["unavailable"] + report["errors"])
    problems = []
    if issued != len(schedule):
        problems.append(f"issued {issued} != scheduled {len(schedule)}")
    if settled != issued:
        problems.append(f"settled {settled} != issued {issued}")
    if report["completed"] == 0 or report["p50"] is None:
        problems.append("no completed requests / empty latency histogram")
    if problems:
        print("dmtpu loadgen --smoke FAILED: " + "; ".join(problems),
              file=sys.stderr)
        return 1
    print(f"loadgen smoke ok: {issued} arrivals over "
          f"{len(phases)} phase(s) in {duration:.1f} virtual s — "
          f"{report['completed']} completed, {report['shed']} shed, "
          f"p50 {report['p50']:.3f}s goodput {report['goodput']}/s")
    return 0


def _loadgen_storm(args, phases, schedule) -> int:
    """A real storm: threaded replica fleet over a shared in-memory
    object store, seeded with the Zipf head, driven open-loop."""
    import asyncio
    import json as json_mod

    import numpy as np

    from distributedmandelbrot_tpu import loadgen
    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
    from distributedmandelbrot_tpu.loadgen.driver import GatewayDriver
    from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet
    from distributedmandelbrot_tpu.net import protocol as proto
    from distributedmandelbrot_tpu.storage.backends import (
        MemoryObjectStore, ObjectStoreBackend)
    from distributedmandelbrot_tpu.storage.store import ChunkStore

    colormap_ids = {name: cid for cid, name in proto.COLORMAPS.items()}
    if args.render and args.colormap not in colormap_ids:
        print(f"dmtpu loadgen: unknown colormap {args.colormap!r} "
              f"(have {sorted(colormap_ids)})", file=sys.stderr)
        return 2

    kv = MemoryObjectStore()
    seeder = ChunkStore(backend=ObjectStoreBackend(kv))
    # RLE-friendly non-constant pixels: long runs, a few distinct values.
    pixels = np.repeat(np.arange(64, dtype=np.uint8) + 1,
                       CHUNK_PIXELS // 64)
    sampler = loadgen.ZipfTiles(args.level, s=args.zipf, seed=args.seed)
    for level, i, j in sampler.hottest(args.seed_tiles):
        seeder.save(Chunk(level, i, j, pixels))

    fleet = GatewayFleet(kv, replicas=args.replicas, rate=args.rate,
                         burst=args.burst,
                         max_queue_depth=args.queue_depth)
    with fleet:
        driver = GatewayDriver(fleet.addresses, render=args.render,
                               colormap_id=colormap_ids.get(args.colormap,
                                                            0),
                               timeout=args.timeout)
        recorder = loadgen.StormRecorder()
        runner = loadgen.OpenLoopRunner(schedule, driver, recorder)
        duration = asyncio.run(runner.run())
        report = recorder.report(
            duration=duration,
            offered=loadgen.schedule.offered_rate(schedule),
            phases=[p.name for p in phases])
        report["replicas"] = args.replicas
        report["gateway_overloaded"] = fleet.counter("gateway_overloaded")
        report["gateway_served"] = (fleet.counter("gateway_served")
                                    + fleet.counter(
                                        "gateway_render_served"))
    if args.json:
        print(json_mod.dumps(report, indent=2, sort_keys=True))
    else:
        for key in ("requests", "completed", "shed", "unavailable",
                    "errors", "offered_rate", "goodput", "shed_fraction",
                    "p50", "p99", "p999", "bytes", "replicas",
                    "gateway_overloaded", "gateway_served"):
            print(f"{key:20} {report[key]}")
        for phase, stats in (report.get("phases") or {}).items():
            print(f"  {phase:18} p50={stats['p50']} p99={stats['p99']} "
                  f"p999={stats['p999']}")
    return 0


def _loadgen_session_storm(args, phases, schedule, *,
                           smoke: bool = False) -> int:
    """Trajectory storm against a session-enabled fleet.

    The store is pre-seeded with the *whole* level grid — sessions pan
    everywhere, and a fully-warm store keeps the measurement about the
    session machinery (prediction, prefetch marks, fair admission)
    rather than store misses.  ``smoke`` runs the same storm on a
    virtual clock and turns the report into pass/fail checks: ids
    issued, predictions planned, prefetch marks consumed, every arrival
    settled — jax-free, so it runs in the lint-only CI job.
    """
    import asyncio
    import json as json_mod

    import numpy as np

    from distributedmandelbrot_tpu import loadgen
    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
    from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.storage.backends import (
        MemoryObjectStore, ObjectStoreBackend)
    from distributedmandelbrot_tpu.storage.store import ChunkStore

    class _IoVirtualTimebase(loadgen.VirtualTimebase):
        # Real sockets under the virtual clock: after each quiesce
        # burst, yield to the selector for a moment so cross-thread
        # socket IO can land.  The deadlock guard gets minutes of
        # grace (>= 1 ms per idle round) because the in-flight tail
        # completes on wall time, not the virtual clock — the driver's
        # per-request timeout still bounds a genuine hang.
        def __init__(self) -> None:
            super().__init__(max_idle_rounds=120_000)

        async def _quiesce(self) -> None:
            await super()._quiesce()
            await asyncio.sleep(0.001)

    kv = MemoryObjectStore()
    seeder = ChunkStore(backend=ObjectStoreBackend(kv))
    pixels = np.repeat(np.arange(64, dtype=np.uint8) + 1,
                       CHUNK_PIXELS // 64)
    for i in range(args.level):
        for j in range(args.level):
            seeder.save(Chunk(args.level, i, j, pixels))

    fleet = GatewayFleet(kv, replicas=args.replicas, rate=args.rate,
                         burst=args.burst,
                         max_queue_depth=args.queue_depth,
                         sessions=True, session_rate=args.session_rate,
                         session_burst=args.session_burst)
    with fleet:
        driver = loadgen.SessionDriver(fleet.addresses,
                                       timeout=args.timeout)
        recorder = loadgen.StormRecorder()
        if smoke:
            timebase = _IoVirtualTimebase()
            runner = loadgen.SessionRunner(schedule, driver, recorder,
                                           timebase=timebase)

            async def drive() -> float:
                task = asyncio.ensure_future(runner.run())
                await timebase.drain(until=task)
                return task.result()

            duration = asyncio.run(drive())
        else:
            runner = loadgen.SessionRunner(schedule, driver, recorder)
            duration = asyncio.run(runner.run())
        report = recorder.report(
            duration=duration,
            offered=loadgen.schedule.offered_rate(schedule),
            phases=[p.name for p in phases])
        report["replicas"] = args.replicas
        report["sessions"] = args.sessions
        report["session_opens"] = fleet.counter(obs_names.SESSION_OPENS)
        report["session_throttled"] = fleet.counter(
            obs_names.SESSION_THROTTLED)
        report["prefetch_planned"] = fleet.counter(
            obs_names.PREFETCH_PLANNED)
        hits = fleet.counter(obs_names.PREFETCH_HITS)
        misses = fleet.counter(obs_names.PREFETCH_MISSES)
        report["prefetch_hits"] = hits
        report["prefetch_misses"] = misses
        report["prefetch_hit_ratio"] = (
            round(hits / (hits + misses), 4) if hits + misses else None)
        ok_min, ok_max = loadgen.ok_spread(driver.ok_by_session,
                                           args.sessions)
        report["ok_min_session"] = ok_min
        report["ok_max_session"] = ok_max

    if smoke:
        issued = report["requests"]
        settled = (report["completed"] + report["shed"]
                   + report["unavailable"] + report["errors"])
        problems = []
        if issued != len(schedule):
            problems.append(f"issued {issued} != scheduled "
                            f"{len(schedule)}")
        if settled != issued:
            problems.append(f"settled {settled} != issued {issued}")
        if report["completed"] == 0:
            problems.append("no completed requests")
        if report["session_opens"] < 1:
            problems.append("no sessions opened on the wire")
        if report["prefetch_planned"] < 1:
            problems.append("predictor planned no prefetches")
        if report["prefetch_hits"] < 1:
            problems.append("no query consumed a prefetch mark")
        if problems:
            print("dmtpu loadgen --smoke FAILED: "
                  + "; ".join(problems), file=sys.stderr)
            return 1
        print(f"loadgen session smoke ok: {issued} arrivals, "
              f"{report['sessions']} sessions, "
              f"{report['session_opens']} opened, "
              f"prefetch hit ratio {report['prefetch_hit_ratio']}, "
              f"ok spread {report['ok_min_session']}.."
              f"{report['ok_max_session']}")
        return 0

    if args.json:
        print(json_mod.dumps(report, indent=2, sort_keys=True))
    else:
        for key in ("requests", "completed", "shed", "unavailable",
                    "errors", "offered_rate", "goodput", "shed_fraction",
                    "p50", "p99", "p999", "bytes", "replicas",
                    "sessions", "session_opens", "session_throttled",
                    "prefetch_planned", "prefetch_hits",
                    "prefetch_misses", "prefetch_hit_ratio",
                    "ok_min_session", "ok_max_session"):
            print(f"{key:20} {report[key]}")
        for phase, stats in (report.get("phases") or {}).items():
            print(f"  {phase:18} p50={stats['p50']} p99={stats['p99']} "
                  f"p999={stats['p999']}")
    return 0


def cmd_coord(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu coord",
        description="Run ONE shard of the sharded control plane: the "
                    "full Distributer/DataServer stack restricted to the "
                    "consistent-hash slice --shard K/N owns, over a data "
                    "dir shared with the other N-1 shards.")
    parser.add_argument("--shard", required=True, metavar="K/N",
                        help="this shard's slice: index K of N shards "
                             "(e.g. 0/4)")
    parser.add_argument("--ring", default=None, metavar="RING_JSON",
                        help="ring config naming all N shard endpoints; "
                             "optional — ownership needs only K/N, so "
                             "ephemeral-port launches may start ringless "
                             "and publish bound ports afterwards")
    parser.add_argument("--ring-version", type=int, default=1,
                        help="ring version to advertise when launching "
                             "without --ring (skew detector on the wire)")
    parser.add_argument("-l", "--levels", required=True,
                        help="level:max_iter[,level:max_iter...] — must "
                             "be identical across the fleet")
    parser.add_argument("-o", "--data-dir", default="",
                        help="parent directory for the SHARED Data/ "
                             "(default: cwd)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--distributer-port", type=int, default=0,
                        help="0 = ephemeral (default: shards usually "
                             "co-locate, so fixed ports would collide)")
    parser.add_argument("--dataserver-port", type=int, default=0)
    parser.add_argument("--lease-timeout", type=float,
                        default=proto.DEFAULT_LEASE_TIMEOUT)
    parser.add_argument("--sweep-period", type=float,
                        default=proto.DEFAULT_SWEEP_PERIOD)
    parser.add_argument("--fsync-index", action="store_true")
    parser.add_argument("--checkpoint-period", type=float, default=0.0,
                        help="durability checkpoint every N seconds "
                             "(0 disables)")
    parser.add_argument("--stats-period", type=float, default=60.0)
    parser.add_argument("--exporter-port", type=int, default=0,
                        help="HTTP metrics port; 0 = ephemeral, "
                             "-1 disables")
    parser.add_argument("--sample-period", type=float, default=2.0,
                        help="seconds between /timeseries snapshots of "
                             "the registry")
    parser.add_argument("--history-window", type=float, default=600.0,
                        help="seconds of timeseries history kept in the "
                             "ring buffer")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    from distributedmandelbrot_tpu.control import ShardedCoordinator
    from distributedmandelbrot_tpu.control.ring import (RingConfigError,
                                                        parse_shard_spec)
    from distributedmandelbrot_tpu.storage.ownership import LevelOwnedError
    from distributedmandelbrot_tpu.storage.store import DataDirError

    settings = parse_level_settings(args.levels)
    try:
        shard, n_shards = parse_shard_spec(args.shard)
        coordinator = ShardedCoordinator(
            settings, shard, n_shards,
            ring_path=args.ring, ring_version=args.ring_version,
            data_dir_parent=args.data_dir, host=args.host,
            distributer_port=args.distributer_port,
            dataserver_port=args.dataserver_port,
            lease_timeout=args.lease_timeout,
            sweep_period=args.sweep_period,
            fsync_index=args.fsync_index,
            checkpoint_period=args.checkpoint_period,
            stats_period=args.stats_period,
            sample_period=args.sample_period,
            history_window=args.history_window,
            exporter_port=(None if args.exporter_port < 0
                           else args.exporter_port))
    except (RingConfigError, DataDirError, LevelOwnedError) as e:
        raise SystemExit(f"dmtpu coord: {e}")
    sched = coordinator.scheduler
    print(f"coord shard {shard}/{n_shards}: owns {sched.owned_tiles} of "
          f"{sched.total_tiles} tiles across {len(settings)} level(s) "
          f"({sched.completed_count} already complete on disk)",
          flush=True)
    try:
        asyncio.run(coordinator.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_chaos(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu chaos",
        description="Run one chaos scenario against a live sharded farm "
                    "(real subprocesses, real sockets, real numpy "
                    "compute): kill coordinators and workers on a "
                    "schedule, then audit exactly-once completion, ring "
                    "ownership, numpy-golden parity, and the "
                    "restart-to-first-grant blip.")
    parser.add_argument("scenario", nargs="?", default="coord-kill",
                        help="catalogue entry (see --list); default: "
                             "coord-kill")
    parser.add_argument("--list", action="store_true",
                        help="list the scenario catalogue and exit")
    parser.add_argument("--smoke", action="store_true",
                        help="CI-sized run: shallower tiles, one worker, "
                             "one parity sample")
    parser.add_argument("--levels", default=None,
                        help="override the scenario's level spec")
    parser.add_argument("--workers", type=int, default=None,
                        help="override the scenario's worker count")
    parser.add_argument("--shards", type=int, default=None,
                        help="override the scenario's shard count")
    parser.add_argument("--deadline", type=float, default=None,
                        help="override the completion deadline (seconds)")
    parser.add_argument("--workdir", default=None,
                        help="keep farm state + per-process logs here "
                             "(default: throwaway temp dir)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    import dataclasses as dc

    # Lazy: the chaos package must import in the lint-only CI
    # environment (numpy + pytest, no jax) — workers are numpy-only.
    from distributedmandelbrot_tpu.chaos import SCENARIOS, ChaosRunner

    if args.list:
        for name in sorted(SCENARIOS):
            sc = SCENARIOS[name]
            print(f"{name:18} {sc.description}")
        return 0
    if args.scenario not in SCENARIOS:
        print(f"dmtpu chaos: unknown scenario {args.scenario!r}; have "
              f"{sorted(SCENARIOS)}", file=sys.stderr)
        return 2
    scenario = SCENARIOS[args.scenario]
    if args.smoke:
        scenario = dc.replace(scenario, levels="3:2", n_workers=1,
                              parity_samples=1, deadline=180.0)
    overrides = {}
    if args.levels is not None:
        overrides["levels"] = args.levels
    if args.workers is not None:
        overrides["n_workers"] = args.workers
    if args.shards is not None:
        overrides["n_shards"] = args.shards
    if args.deadline is not None:
        overrides["deadline"] = args.deadline
    if overrides:
        scenario = dc.replace(scenario, **overrides)

    runner = ChaosRunner(scenario, workdir=args.workdir,
                         log=None if args.quiet else print)
    report = runner.run()
    if args.json:
        print(report.to_json())
    else:
        print(f"chaos {report.scenario}: "
              f"{'OK' if report.ok else 'FAILED'} — "
              f"{report.tiles_on_disk}/{report.expected_tiles} tiles, "
              f"{report.duplicate_entries} duplicates, "
              f"{report.misowned_entries} misowned, "
              f"parity {report.parity_checked - report.parity_failures}/"
              f"{report.parity_checked}, {report.kills} kills, "
              f"{report.restarts} restarts, "
              f"first-grant blips {report.restart_to_first_grant_s} "
              f"in {report.duration_s:.1f}s")
        for failure in report.failures:
            print(f"  FAIL: {failure}")
    return 0 if report.ok else 1


def _parse_peer_args(args: argparse.Namespace) -> list:
    """--peers/--ring -> ``[role@]host:port`` peer specs for the
    aggregator (shared by cmd_top's direct mode and the smoke farm)."""
    peers: list = []
    for chunk in args.peers or []:
        peers.extend(s for s in (p.strip() for p in chunk.split(","))
                     if s)
    if args.ring:
        from distributedmandelbrot_tpu.control.ring import (HashRing,
                                                            RingConfigError)
        try:
            ring = HashRing.load(args.ring)
        except RingConfigError as e:
            raise SystemExit(f"dmtpu top: {e}")
        for info in ring.shards:
            if info.exporter_port:
                peers.append(f"shard@{info.host}:{info.exporter_port}")
    return peers


def _fetch_fleet_doc(url: str, timeout: float = 5.0) -> dict:
    """One /fleet document from a running FleetService/exporter."""
    import json as _json

    from distributedmandelbrot_tpu.obs.fleet import ScrapeError, http_fetch
    base = url if "://" in url else "http://" + url
    body = http_fetch(base.rstrip("/") + "/fleet", timeout)
    doc = _json.loads(body.decode("utf-8", errors="replace"))
    if not isinstance(doc, dict):
        raise ScrapeError(f"/fleet returned {type(doc).__name__}")
    return doc


def _top_smoke(args) -> int:
    """Throwaway jax-free farm (2 shards + 2 numpy workers via the
    chaos driver, 1 in-process gateway replica), one dashboard frame
    against it, and hard assertions that every role reports: the CI
    proof that the whole observability plane is wired end to end."""
    import json as _json
    import shutil
    import subprocess
    import tempfile

    from distributedmandelbrot_tpu.control.ring import HashRing, ShardInfo
    from distributedmandelbrot_tpu.loadgen.driver import GatewayDriver
    from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet
    from distributedmandelbrot_tpu.obs.fleet import FleetAggregator
    from distributedmandelbrot_tpu.obs.top import render_top
    from distributedmandelbrot_tpu.storage.backends import MemoryObjectStore

    n_shards, n_workers, levels = 2, 2, "3:2"
    root = tempfile.mkdtemp(prefix="dmtpu-top-smoke-")
    procs: list = []
    fleet_gw = None
    failures: list[str] = []
    try:
        data_dir = f"{root}/farm"
        ring_path = f"{root}/ring.json"
        port_files = [f"{root}/shard-{k}.ports" for k in range(n_shards)]
        for k in range(n_shards):
            cmd = [sys.executable, "-m",
                   "distributedmandelbrot_tpu.chaos.driver", "shard",
                   data_dir, port_files[k], levels, str(k), str(n_shards),
                   "--lease-timeout", "30", "--sweep-period", "0.5",
                   "--checkpoint-period", "0.5"]
            with open(f"{root}/shard-{k}.log", "w", encoding="utf-8") as lf:
                procs.append(subprocess.Popen(cmd, stdout=lf, stderr=lf))
        deadline = time.monotonic() + 30.0
        infos = []
        for k in range(n_shards):
            while True:
                try:
                    with open(port_files[k], "r", encoding="utf-8") as f:
                        infos.append(_json.load(f))
                    break
                except (OSError, ValueError):
                    if time.monotonic() > deadline:
                        raise SystemExit(
                            f"dmtpu top --smoke: shard {k} never "
                            f"published its ports (see {root})")
                    time.sleep(0.05)
        HashRing([ShardInfo("127.0.0.1",
                            distributer_port=i["distributer"],
                            dataserver_port=i["dataserver"],
                            exporter_port=i["exporter"])
                  for i in infos], version=1).save(ring_path)
        for _ in range(n_workers):
            cmd = [sys.executable, "-m",
                   "distributedmandelbrot_tpu.chaos.driver", "worker",
                   ring_path, "--poll-interval", "0.2"]
            with open(f"{root}/worker.log", "a", encoding="utf-8") as lf:
                procs.append(subprocess.Popen(cmd, stdout=lf, stderr=lf))
        # The read tier: one exporter-bearing gateway replica over an
        # (empty) shared object store — misses still count queries and
        # time the request histogram, which is all the dashboard needs.
        fleet_gw = GatewayFleet(MemoryObjectStore(), replicas=1,
                                exporter=True).start()
        gw_peer = f"gateway@127.0.0.1:{fleet_gw.exporter_ports[0]}"
        agg = FleetAggregator(
            [f"shard@127.0.0.1:{i['exporter']}" for i in infos]
            + [gw_peer], rate_window=30.0)
        driver = GatewayDriver(fleet_gw.addresses)

        async def _storm(n: int) -> None:
            for i in range(n):
                await driver(3, i % 8, (i // 8) % 8)

        snap: dict = {}
        probe_deadline = time.monotonic() + 60.0
        while time.monotonic() < probe_deadline:
            asyncio.run(_storm(6))
            agg.scrape_once()
            snap = agg.snapshot()
            roles = snap.get("roles", {})
            if (snap["totals"]["grants_per_s"] > 0
                    and snap["totals"]["queries_per_s"] > 0
                    and snap.get("workers")
                    and "shard" in roles and "gateway" in roles):
                break
            time.sleep(1.0)
        print(render_top(snap, color=False), flush=True)

        roles = snap.get("roles", {})
        totals = snap.get("totals", {})
        if roles.get("shard", {}).get("healthy", 0) != n_shards:
            failures.append(f"expected {n_shards} healthy shard peers, "
                            f"got {roles.get('shard')}")
        if roles.get("gateway", {}).get("healthy", 0) < 1:
            failures.append(f"no healthy gateway peer: "
                            f"{roles.get('gateway')}")
        if not snap.get("workers"):
            failures.append("no span-reported worker rows")
        if not totals.get("grants_per_s", 0) > 0:
            failures.append(f"zero grant rate: {totals}")
        if not totals.get("queries_per_s", 0) > 0:
            failures.append(f"zero gateway query rate: {totals}")
        if not any(g.get("queries_per_s", 0) > 0
                   for g in snap.get("gateways", [])):
            failures.append("no gateway row with a nonzero query rate")
        for failure in failures:
            print(f"SMOKE FAIL: {failure}", file=sys.stderr)
        print(f"top smoke: {'OK' if not failures else 'FAILED'} — "
              f"{len(snap.get('peers', []))} peers, "
              f"{len(snap.get('workers', []))} workers, "
              f"{totals.get('grants_per_s')} grants/s, "
              f"{totals.get('queries_per_s')} q/s", flush=True)
        return 0 if not failures else 1
    finally:
        if fleet_gw is not None:
            fleet_gw.stop()
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)


def cmd_top(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dmtpu top",
        description="Live fleet dashboard: scrape every exporter "
                    "(--peers / --ring), or read a running /fleet "
                    "endpoint (--fleet-url), and render per-role rates, "
                    "SLO burn, and straggler flags.")
    parser.add_argument("--peers", action="append", metavar="SPECS",
                        help="comma-separated [role@]host:port exporter "
                             "endpoints (repeatable)")
    parser.add_argument("--ring", default=None, metavar="RING_JSON",
                        help="scrape the exporter ports named in this "
                             "ring config")
    parser.add_argument("--fleet-url", default=None, metavar="URL",
                        help="read an existing fleet aggregator's /fleet "
                             "instead of scraping peers directly")
    parser.add_argument("--once", action="store_true",
                        help="print one frame and exit (CI mode)")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw /fleet snapshot as JSON "
                             "instead of the dashboard")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between frames (and scrapes)")
    parser.add_argument("--window", type=float, default=60.0,
                        help="trailing rate window in seconds")
    parser.add_argument("--no-color", action="store_true",
                        help="plain text (auto when stdout is not a tty)")
    parser.add_argument("--smoke", action="store_true",
                        help="spawn a throwaway jax-free farm (2 shards, "
                             "2 numpy workers, 1 gateway), render one "
                             "frame against it, and assert every role "
                             "reports — the CI end-to-end check")
    _add_common(parser)
    args = parser.parse_args(argv)
    _configure_logging(args)

    from distributedmandelbrot_tpu.obs.fleet import (FleetAggregator,
                                                     ScrapeError)
    from distributedmandelbrot_tpu.obs.top import render_frame

    if args.smoke:
        return _top_smoke(args)

    color = not args.no_color and sys.stdout.isatty()
    interval = max(0.2, args.interval)

    if args.fleet_url:
        def take_snapshot() -> dict:
            return _fetch_fleet_doc(args.fleet_url)
    else:
        peers = _parse_peer_args(args)
        if not peers:
            parser.error("need --peers, --ring, or --fleet-url "
                         "(or --smoke)")
        agg = FleetAggregator(peers, rate_window=args.window)

        def take_snapshot() -> dict:
            agg.scrape_once()
            return agg.snapshot()

    try:
        if args.once and not args.fleet_url:
            # Rates need two scrape points: one warmup round, a beat,
            # then the rendered snapshot.
            take_snapshot()
            time.sleep(min(interval, 1.0))
        while True:
            try:
                snap = take_snapshot()
            except (ScrapeError, OSError, ValueError) as e:
                if args.once:
                    raise SystemExit(f"dmtpu top: {e}")
                snap = {"peers": [], "error": str(e)}
            if args.json:
                import json as _json
                print(_json.dumps(snap, indent=1, sort_keys=True))
            else:
                sys.stdout.write(render_frame(snap, color=color,
                                              clear=not args.once))
                sys.stdout.flush()
            if args.once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


class _NoFile:
    """Stand-in for findings on unparseable files (no suppressions)."""

    @staticmethod
    def is_suppressed(line: int, rule: str) -> bool:
        return False


_NO_FILE = _NoFile()


COMMANDS = {"coordinator": cmd_coordinator, "worker": cmd_worker,
            "serve": cmd_serve, "viewer": cmd_viewer, "render": cmd_render,
            "animate": cmd_animate, "compact": cmd_compact,
            "stats": cmd_stats, "trace": cmd_trace, "admin": cmd_admin,
            "check": cmd_check, "loadgen": cmd_loadgen,
            "coord": cmd_coord, "chaos": cmd_chaos, "top": cmd_top,
            "postmortem": cmd_postmortem}


def _enable_compile_cache() -> None:
    """Default-on persistent XLA compilation cache for every CLI command.

    Measured on the dev rig (round 5): a cold six-frame 1e-8 -> 1e-16
    deep-zoom animation is ~100% XLA compile time + backend init — the
    per-frame STEADY-STATE cost is 0.08-0.12 s (in-process warm), so the
    ~25-30 s end-to-end was 27 executable compilations, not dispatch
    work.  With this cache populated, a fresh process renders the same
    six frames in ~16 s (~9 s of which is the tunnel's backend-init
    floor).  Set ``DMTPU_COMPILE_CACHE=0`` to disable, or to a path to
    relocate.  Env vars only take effect if jax is not yet imported; a
    site hook (the dev rig's backend registration) may import it before
    main() runs, in which case the flags go through jax.config.update —
    the env path is kept so device-less commands never pay a jax import
    here."""
    import os
    knob = os.environ.get("DMTPU_COMPILE_CACHE", "")
    if knob == "0":
        return
    ambient = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if ambient:
        if not knob or os.path.abspath(knob) == os.path.abspath(ambient):
            return  # ambient setting already does what was asked
        # An explicit DMTPU knob outranks an inherited ambient setting —
        # silently ignoring the more specific instruction cost a round-5
        # operator a cold cache.
        print(f"dmtpu: DMTPU_COMPILE_CACHE={knob} overrides ambient "
              f"JAX_COMPILATION_CACHE_DIR={ambient}", file=sys.stderr)
    path = knob or os.path.join(os.path.expanduser("~"), ".cache",
                                "dmtpu", "xla")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return  # unwritable home (sandbox): cache is only an optimization
    os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    # Deep-zoom scans compile in the 0.3-3 s range; the default 1 s
    # threshold would skip caching half of them.
    min_secs = os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
    if "jax" in sys.modules:  # env defaults frozen at jax import
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_secs))
        except Exception:
            pass  # an optimization, never a startup failure


def main(argv: Optional[Sequence[str]] = None) -> int:
    _enable_compile_cache()
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m distributedmandelbrot_tpu "
              "{coordinator|coord|worker|serve|viewer|render|animate|"
              "compact|stats|trace|admin|check|loadgen|chaos|top} "
              "[options]\n"
              "Run each subcommand with -h for its options.")
        return 0 if argv else 2
    cmd = argv[0]
    if cmd not in COMMANDS:
        print(f"unknown command {cmd!r}; expected one of "
              f"{sorted(COMMANDS)}", file=sys.stderr)
        return 2
    return COMMANDS[cmd](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
