"""Synchronous client for the Distributer protocol (worker side).

Speaks the same wire protocol as the reference worker
(``DistributedMandelbrotWorkerCUDA.py:102-176``): one connection per
exchange, purpose byte first.  Adds the batched request/response exchanges
(one connection for a whole batch) used to feed a device mesh.
"""

from __future__ import annotations

import socket
from typing import Optional, Sequence

import numpy as np

from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.protocol import WORKLOAD_WIRE_SIZE


class DistributerClient:
    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    # -- job acquisition --------------------------------------------------

    def request(self) -> Optional[Workload]:
        """Pull one workload; None when the coordinator has nothing to hand out."""
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_REQUEST)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return None
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            return Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))

    def request_batch(self, max_count: int) -> list[Workload]:
        """Pull up to ``max_count`` workloads in one exchange."""
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_REQUEST)
            framing.send_u32(sock, max_count)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return []
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            n = framing.recv_u32(sock)
            return [Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))
                for _ in range(n)]

    # -- result submission ------------------------------------------------

    @staticmethod
    def _pixel_bytes(pixels: np.ndarray) -> bytes:
        arr = np.ascontiguousarray(pixels, dtype=np.uint8).ravel()
        if arr.size != CHUNK_PIXELS:
            raise ValueError(
                f"result must have {CHUNK_PIXELS} pixels, got {arr.size}")
        return arr.tobytes()

    def submit(self, workload: Workload, pixels: np.ndarray) -> bool:
        """Push one result; returns True if the coordinator accepted it."""
        data = self._pixel_bytes(pixels)
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_RESPONSE)
            framing.send_all(sock, workload.to_wire())
            status = framing.recv_byte(sock)
            if status == proto.RESPONSE_REJECT:
                return False
            if status != proto.RESPONSE_ACCEPT:
                raise framing.ProtocolError(
                    f"unexpected acceptance code {status:#x}")
            framing.send_all(sock, data)
            return True

    def submit_batch(self, results: Sequence[tuple[Workload, np.ndarray]]
                     ) -> list[bool]:
        """Push several results over one connection; per-item accept flags."""
        if not results:
            return []
        encoded = [(w, self._pixel_bytes(p)) for w, p in results]
        accepted: list[bool] = []
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_RESPONSE)
            framing.send_u32(sock, len(encoded))
            for w, data in encoded:
                framing.send_all(sock, w.to_wire())
                status = framing.recv_byte(sock)
                if status == proto.RESPONSE_ACCEPT:
                    framing.send_all(sock, data)
                    accepted.append(True)
                elif status == proto.RESPONSE_REJECT:
                    accepted.append(False)
                else:
                    raise framing.ProtocolError(
                        f"unexpected acceptance code {status:#x}")
        return accepted
