"""Synchronous client for the Distributer protocol (worker side).

Speaks the same wire protocol as the reference worker
(``DistributedMandelbrotWorkerCUDA.py:102-176``): one connection per
exchange, purpose byte first.  Adds the batched request/response exchanges
(one connection for a whole batch) used to feed a device mesh, and an
opt-in reconnect policy (capped exponential backoff + jitter) so a
coordinator restart — now survivable server-side thanks to
checkpoint/restore — no longer kills the farm run from the client side.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

import numpy as np

from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.protocol import WORKLOAD_WIRE_SIZE
from distributedmandelbrot_tpu.obs import names as obs_names

# Span stage name (obs/names.py) -> one-byte wire code, pipeline order.
_STAGE_CODES = {
    obs_names.SPAN_PREFETCH: proto.SPAN_STAGE_PREFETCH,
    obs_names.SPAN_DISPATCH: proto.SPAN_STAGE_DISPATCH,
    obs_names.SPAN_COMPUTE: proto.SPAN_STAGE_COMPUTE,
    obs_names.SPAN_D2H: proto.SPAN_STAGE_D2H,
    obs_names.SPAN_UPLOAD: proto.SPAN_STAGE_UPLOAD,
}


class DistributerClient:
    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0,
                 reconnect_attempts: int = 0,
                 reconnect_base: float = 0.05,
                 reconnect_cap: float = 5.0,
                 counters=None, rng: Optional[random.Random] = None) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        # Latched the first time a span push fails: a legacy coordinator
        # drops the connection on the unknown 0x04 purpose byte, and
        # retrying every upload would just spam its error log.
        self.span_push_disabled = False
        # Reconnect policy: up to ``reconnect_attempts`` redials per
        # exchange on connection-level failure (OSError), sleeping
        # min(cap, base * 2^n) scaled by jitter in [0.5, 1.0) between
        # tries.  Only transport errors retry — a ProtocolError means the
        # peer is speaking garbage and redialing it would loop forever.
        # The default (0) preserves the historical fail-fast behavior.
        self.reconnect_attempts = reconnect_attempts
        self.reconnect_base = reconnect_base
        self.reconnect_cap = reconnect_cap
        self.counters = counters
        self._rng = rng if rng is not None else random.Random()
        self._sleep = time.sleep  # injectable for deterministic tests

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _with_reconnect(self, op: Callable[[], T]) -> T:
        """Run one exchange, redialing on transport failure.

        Retried exchanges are safe to repeat: request/request_batch at
        worst leases extra tiles (their leases expire), and a submit
        whose accept byte was lost is re-claimed server-side, where
        completion dedup rejects any duplicate.
        """
        attempt = 0
        while True:
            try:
                return op()
            except OSError:
                if attempt >= self.reconnect_attempts:
                    raise
                delay = min(self.reconnect_cap,
                            self.reconnect_base * (2 ** attempt))
                delay *= 0.5 + 0.5 * self._rng.random()
                attempt += 1
                if self.counters is not None:
                    self.counters.inc(obs_names.WORKER_RECONNECTS)
                self._sleep(delay)

    # -- job acquisition --------------------------------------------------

    def request(self) -> Optional[Workload]:
        """Pull one workload; None when the coordinator has nothing to hand out."""
        return self._with_reconnect(self._request_once)

    def _request_once(self) -> Optional[Workload]:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_REQUEST)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return None
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            return Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))

    def request_batch(self, max_count: int) -> list[Workload]:
        """Pull up to ``max_count`` workloads in one exchange."""
        return self._with_reconnect(lambda: self._request_batch_once(max_count))

    def _request_batch_once(self, max_count: int) -> list[Workload]:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_REQUEST)
            framing.send_u32(sock, max_count)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return []
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            # The coordinator grants at most what we asked for; a larger
            # count is a corrupt frame or an impostor, not a bonus.
            n = proto.validate_count(framing.recv_u32(sock), max_count,
                                     "grant count")
            return [Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))
                for _ in range(n)]

    # -- span push (0x04 extension) ---------------------------------------

    def push_spans(self, worker_id: int, syncs, spans) -> bool:
        """Best-effort batched span report after an upload.

        ``syncs`` are (key, t_req, t_recv) clock samples; ``spans`` are
        (stage, key, t0, t1, device, seq) records — both the tuple
        shapes obs/spans.py drains.  Returns False and permanently
        disables the push when the coordinator does not speak 0x04
        (EOF/reset instead of ``SPANS_ACCEPT``); never raises.
        """
        if self.span_push_disabled:
            return False
        buf = bytearray()
        buf += proto.SPANS_HEADER.pack(worker_id, len(syncs), len(spans))
        for key, t_req, t_recv in syncs:
            buf += proto.SPAN_SYNC.pack(*key, t_req, t_recv)
        for stage, key, t0, t1, device, seq in spans:
            buf += proto.SPAN_RECORD.pack(*key, _STAGE_CODES[stage],
                                          device, seq, t0, t1)
        try:
            with self._connect() as sock:
                framing.send_byte(sock, proto.PURPOSE_SPANS)
                framing.send_all(sock, bytes(buf))
                status = framing.recv_byte(sock)
                if status != proto.SPANS_ACCEPT:
                    raise framing.ProtocolError(
                        f"unexpected span ack {status:#x}")
            return True
        except (OSError, framing.ProtocolError):
            self.span_push_disabled = True
            return False

    # -- result submission ------------------------------------------------

    @staticmethod
    def _pixel_bytes(pixels: np.ndarray) -> bytes:
        arr = np.ascontiguousarray(pixels, dtype=np.uint8).ravel()
        if arr.size != CHUNK_PIXELS:
            raise ValueError(
                f"result must have {CHUNK_PIXELS} pixels, got {arr.size}")
        return arr.tobytes()

    def submit(self, workload: Workload, pixels: np.ndarray) -> bool:
        """Push one result; returns True if the coordinator accepted it."""
        data = self._pixel_bytes(pixels)
        return self._with_reconnect(lambda: self._submit_once(workload, data))

    def _submit_once(self, workload: Workload, data: bytes) -> bool:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_RESPONSE)
            framing.send_all(sock, workload.to_wire())
            status = framing.recv_byte(sock)
            if status == proto.RESPONSE_REJECT:
                return False
            if status != proto.RESPONSE_ACCEPT:
                raise framing.ProtocolError(
                    f"unexpected acceptance code {status:#x}")
            framing.send_all(sock, data)
            return True

    def submit_batch(self, results: Sequence[tuple[Workload, np.ndarray]]
                     ) -> list[bool]:
        """Push several results over one connection; per-item accept flags."""
        if not results:
            return []
        encoded = [(w, self._pixel_bytes(p)) for w, p in results]
        return self._with_reconnect(lambda: self._submit_batch_once(encoded))

    def _submit_batch_once(self, encoded: Sequence[tuple[Workload, bytes]]
                           ) -> list[bool]:
        accepted: list[bool] = []
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_RESPONSE)
            framing.send_u32(sock, len(encoded))
            for w, data in encoded:
                framing.send_all(sock, w.to_wire())
                status = framing.recv_byte(sock)
                if status == proto.RESPONSE_ACCEPT:
                    framing.send_all(sock, data)
                    accepted.append(True)
                elif status == proto.RESPONSE_REJECT:
                    accepted.append(False)
                else:
                    raise framing.ProtocolError(
                        f"unexpected acceptance code {status:#x}")
        return accepted
