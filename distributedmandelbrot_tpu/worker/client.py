"""Synchronous client for the Distributer protocol (worker side).

Speaks the same wire protocol as the reference worker
(``DistributedMandelbrotWorkerCUDA.py:102-176``): one connection per
exchange, purpose byte first.  Adds the batched request/response exchanges
(one connection for a whole batch) used to feed a device mesh, and an
opt-in reconnect policy (capped exponential backoff + jitter) so a
coordinator restart — now survivable server-side thanks to
checkpoint/restore — no longer kills the farm run from the client side.

:class:`DistributerSession` is the persistent alternative: one
``PURPOSE_SESSION`` (0x05) hello upgrades a single connection to a
long-lived framed stream carrying lease grants, pipelined result
uploads (with lease-request piggybacking on the acks — one round trip
per tile steady-state), optional RLE-compressed tile bodies, and
fire-and-forget span reports.  Against a legacy coordinator the hello
EOFs and callers fall back to the connection-per-exchange client above.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

import numpy as np

from distributedmandelbrot_tpu.codecs.rle import RleCodec, estimate_ratio
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.protocol import WORKLOAD_WIRE_SIZE
from distributedmandelbrot_tpu.obs import names as obs_names

# A tile ships RLE only when the estimated (then exact) compression
# ratio clears this bar — marginal wins don't pay for the decode.
MIN_WIRE_RATIO = 2.0

# Span stage name (obs/names.py) -> one-byte wire code, pipeline order.
_STAGE_CODES = {
    obs_names.SPAN_PREFETCH: proto.SPAN_STAGE_PREFETCH,
    obs_names.SPAN_DISPATCH: proto.SPAN_STAGE_DISPATCH,
    obs_names.SPAN_COMPUTE: proto.SPAN_STAGE_COMPUTE,
    obs_names.SPAN_D2H: proto.SPAN_STAGE_D2H,
    obs_names.SPAN_UPLOAD: proto.SPAN_STAGE_UPLOAD,
}


class DistributerClient:
    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0,
                 reconnect_attempts: int = 0,
                 reconnect_base: float = 0.05,
                 reconnect_cap: float = 5.0,
                 counters=None, rng: Optional[random.Random] = None) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        # Latched the first time a span push fails: a legacy coordinator
        # drops the connection on the unknown 0x04 purpose byte, and
        # retrying every upload would just spam its error log.
        self.span_push_disabled = False
        # Reconnect policy: up to ``reconnect_attempts`` redials per
        # exchange on connection-level failure (OSError), sleeping
        # min(cap, base * 2^n) scaled by jitter in [0.5, 1.0) between
        # tries.  Only transport errors retry — a ProtocolError means the
        # peer is speaking garbage and redialing it would loop forever.
        # The default (0) preserves the historical fail-fast behavior.
        self.reconnect_attempts = reconnect_attempts
        self.reconnect_base = reconnect_base
        self.reconnect_cap = reconnect_cap
        self.counters = counters
        self._rng = rng if rng is not None else random.Random()
        self._sleep = time.sleep  # injectable for deterministic tests

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _with_reconnect(self, op: Callable[[], T]) -> T:
        """Run one exchange, redialing on transport failure.

        Retried exchanges are safe to repeat: request/request_batch at
        worst leases extra tiles (their leases expire), and a submit
        whose accept byte was lost is re-claimed server-side, where
        completion dedup rejects any duplicate.
        """
        attempt = 0
        while True:
            try:
                return op()
            except OSError:
                if attempt >= self.reconnect_attempts:
                    raise
                delay = min(self.reconnect_cap,
                            self.reconnect_base * (2 ** attempt))
                delay *= 0.5 + 0.5 * self._rng.random()
                attempt += 1
                if self.counters is not None:
                    self.counters.inc(obs_names.WORKER_RECONNECTS)
                self._sleep(delay)

    # -- job acquisition --------------------------------------------------

    def request(self) -> Optional[Workload]:
        """Pull one workload; None when the coordinator has nothing to hand out."""
        return self._with_reconnect(self._request_once)

    def _request_once(self) -> Optional[Workload]:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_REQUEST)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return None
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            return Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))

    def request_batch(self, max_count: int) -> list[Workload]:
        """Pull up to ``max_count`` workloads in one exchange."""
        return self._with_reconnect(lambda: self._request_batch_once(max_count))

    def _request_batch_once(self, max_count: int) -> list[Workload]:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_REQUEST)
            framing.send_u32(sock, max_count)
            status = framing.recv_byte(sock)
            if status == proto.WORKLOAD_NOT_AVAILABLE:
                return []
            if status != proto.WORKLOAD_AVAILABLE:
                raise framing.ProtocolError(
                    f"unexpected availability code {status:#x}")
            # The coordinator grants at most what we asked for; a larger
            # count is a corrupt frame or an impostor, not a bonus.
            n = proto.validate_count(framing.recv_u32(sock), max_count,
                                     "grant count")
            return [Workload.from_wire(
                framing.recv_exact(sock, WORKLOAD_WIRE_SIZE))
                for _ in range(n)]

    # -- span push (0x04 extension) ---------------------------------------

    def push_spans(self, worker_id: int, syncs, spans) -> bool:
        """Best-effort batched span report after an upload.

        ``syncs`` are (key, t_req, t_recv) clock samples; ``spans`` are
        (stage, key, t0, t1, device, seq) records — both the tuple
        shapes obs/spans.py drains.  Returns False and permanently
        disables the push when the coordinator does not speak 0x04
        (EOF/reset instead of ``SPANS_ACCEPT``); never raises.
        """
        if self.span_push_disabled:
            return False
        buf = bytearray()
        buf += proto.SPANS_HEADER.pack(worker_id, len(syncs), len(spans))
        for key, t_req, t_recv in syncs:
            buf += proto.SPAN_SYNC.pack(*key, t_req, t_recv)
        for stage, key, t0, t1, device, seq in spans:
            buf += proto.SPAN_RECORD.pack(*key, _STAGE_CODES[stage],
                                          device, seq, t0, t1)
        try:
            with self._connect() as sock:
                framing.send_byte(sock, proto.PURPOSE_SPANS)
                framing.send_all(sock, bytes(buf))
                status = framing.recv_byte(sock)
                if status != proto.SPANS_ACCEPT:
                    raise framing.ProtocolError(
                        f"unexpected span ack {status:#x}")
            return True
        except (OSError, framing.ProtocolError):
            self.span_push_disabled = True
            return False

    # -- result submission ------------------------------------------------

    @staticmethod
    def _pixel_bytes(pixels: np.ndarray):
        """Flat byte buffer of one result tile, zero-copy when possible.

        A C-contiguous uint8 array is handed to the socket as a
        memoryview over its own buffer — ``tobytes()`` here used to copy
        every 16 MiB tile once per upload.  Anything else (wrong dtype,
        strided slice) pays one normalizing copy, as before.
        """
        arr = pixels
        if not (isinstance(arr, np.ndarray) and arr.dtype == np.uint8
                and arr.flags["C_CONTIGUOUS"]):
            arr = np.ascontiguousarray(pixels, dtype=np.uint8)
        if arr.size != CHUNK_PIXELS:
            raise ValueError(
                f"result must have {CHUNK_PIXELS} pixels, got {arr.size}")
        return memoryview(arr).cast("B")

    def submit(self, workload: Workload, pixels: np.ndarray) -> bool:
        """Push one result; returns True if the coordinator accepted it."""
        data = self._pixel_bytes(pixels)
        return self._with_reconnect(lambda: self._submit_once(workload, data))

    def _submit_once(self, workload: Workload, data: bytes) -> bool:
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_RESPONSE)
            framing.send_all(sock, workload.to_wire())
            status = framing.recv_byte(sock)
            if status == proto.RESPONSE_REJECT:
                return False
            if status != proto.RESPONSE_ACCEPT:
                raise framing.ProtocolError(
                    f"unexpected acceptance code {status:#x}")
            framing.send_all(sock, data)
            return True

    def submit_batch(self, results: Sequence[tuple[Workload, np.ndarray]]
                     ) -> list[bool]:
        """Push several results over one connection; per-item accept flags."""
        if not results:
            return []
        encoded = [(w, self._pixel_bytes(p)) for w, p in results]
        return self._with_reconnect(lambda: self._submit_batch_once(encoded))

    def _submit_batch_once(self, encoded: Sequence[tuple[Workload, bytes]]
                           ) -> list[bool]:
        accepted: list[bool] = []
        with self._connect() as sock:
            framing.send_byte(sock, proto.PURPOSE_BATCH_RESPONSE)
            framing.send_u32(sock, len(encoded))
            for w, data in encoded:
                framing.send_all(sock, w.to_wire())
                status = framing.recv_byte(sock)
                if status == proto.RESPONSE_ACCEPT:
                    framing.send_all(sock, data)
                    accepted.append(True)
                elif status == proto.RESPONSE_REJECT:
                    accepted.append(False)
                else:
                    raise framing.ProtocolError(
                        f"unexpected acceptance code {status:#x}")
        return accepted


class DistributerSession:
    """One persistent multiplexed session (``PURPOSE_SESSION``, 0x05).

    Owned by a single thread (a pipeline upload lane or the lease
    stage); nothing here is locked.  All methods raise ``OSError`` /
    ``framing.ProtocolError`` when the session breaks — the owner
    closes it and falls back to its legacy :class:`DistributerClient`.
    """

    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0,
                 compress: bool = True, grantn: bool = True,
                 shard: bool = False, counters=None) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.compress_wanted = compress
        # Batched lease grants (FRAME_LEASE_REQN): capability-flagged so
        # a legacy one-grant coordinator negotiates the bit away and
        # request_batchn transparently degrades to request_batch.
        self.grantn_wanted = grantn
        # Sharded control plane (FRAME_RING_REQ / FRAME_REDIRECT):
        # against a pre-shard coordinator the bit negotiates away and
        # misrouted uploads come back as plain REJECT acks.
        self.shard_wanted = shard
        self.counters = counters
        self.flags = 0  # negotiated capability bits after connect()
        # result index -> authoritative shard, from the REDIRECT acks of
        # the last submit_pipelined (SESSION_FLAG_SHARD sessions only).
        self.last_redirects: dict[int, int] = {}
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._codec = RleCodec()

    # -- lifecycle ---------------------------------------------------------

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def connect(self) -> bool:
        """Dial and run the hello.  False means the coordinator is
        legacy (dropped the unknown 0x05 purpose byte) — the caller
        should fall back to connection-per-exchange, not retry."""
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            upgraded = self._hello(sock)
        except BaseException:
            sock.close()
            raise
        if not upgraded:
            sock.close()
            self._inc(obs_names.WORKER_SESSION_FALLBACKS)
            return False
        self._sock = sock
        self._seq = 0
        self._inc(obs_names.WORKER_SESSIONS_OPENED)
        return True

    def _hello(self, sock: socket.socket) -> bool:
        want = (proto.SESSION_FLAG_RLE if self.compress_wanted else 0) \
            | (proto.SESSION_FLAG_GRANTN if self.grantn_wanted else 0) \
            | (proto.SESSION_FLAG_SHARD if self.shard_wanted else 0)
        framing.send_byte(sock, proto.PURPOSE_SESSION)
        framing.send_all(sock, proto.SESSION_HELLO.pack(want))
        try:
            status = framing.recv_byte(sock)
        except ConnectionError:
            return False  # legacy coordinator: EOF on the unknown purpose
        if status != proto.SESSION_ACCEPT:
            raise framing.ProtocolError(
                f"unexpected session hello reply {status:#x}")
        (self.flags,) = proto.SESSION_HELLO.unpack(
            framing.recv_exact(sock, proto.SESSION_HELLO_WIRE_SIZE))
        return True

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _inc(self, name: str, n: int = 1) -> None:
        if self.counters is not None:
            self.counters.inc(name, n)

    # -- framing -----------------------------------------------------------

    def _next_seq(self) -> int:
        seq = self._seq
        self._seq = (self._seq + 1) & proto.MAX_SESSION_SEQ
        return seq

    def _send_frame(self, frame_type: int, parts: Sequence) -> int:
        seq = self._next_seq()
        total = sum(len(p) for p in parts)
        framing.send_parts(self._sock, [
            proto.SESSION_FRAME.pack(frame_type, seq, total), *parts])
        return seq

    def _recv_frame_header(self, want_type: int, want_seq: int) -> int:
        """Validated payload length of the expected reply frame."""
        _, length = self._recv_frame_header_any((want_type,), want_seq)
        return length

    def _recv_frame_header_any(self, want_types: Sequence[int],
                               want_seq: int) -> tuple[int, int]:
        """(frame_type, payload length) when the reply may legally be
        one of several frames (an upload ack or its REDIRECT stand-in)."""
        frame_type, seq, length = proto.SESSION_FRAME.unpack(
            framing.recv_exact(self._sock, proto.SESSION_FRAME_WIRE_SIZE))
        if frame_type not in want_types:
            raise framing.ProtocolError(
                f"unexpected session frame type "
                f"{proto.frame_name(frame_type)} (wanted one of "
                f"{[proto.frame_name(t) for t in want_types]})")
        proto.validate_session_seq(seq, want_seq)
        return frame_type, proto.validate_payload_length(length)

    def _recv_grants(self, length: int, bound: int) -> list[Workload]:
        """Grant list payload: u32 n + n workloads, cross-checked
        against the frame header's declared length."""
        n = proto.validate_count(framing.recv_u32(self._sock), bound,
                                 "session grant count")
        if length != 4 + n * WORKLOAD_WIRE_SIZE:
            raise framing.ProtocolError(
                f"grant frame length {length} disagrees with count {n}")
        return [Workload.from_wire(
            framing.recv_exact(self._sock, WORKLOAD_WIRE_SIZE))
            for _ in range(n)]

    # -- exchanges ---------------------------------------------------------

    def request_batch(self, max_count: int) -> list[Workload]:
        """Pull up to ``max_count`` workloads in one session round trip."""
        seq = self._next_seq()
        framing.send_all(self._sock, proto.SESSION_FRAME.pack(
            proto.FRAME_LEASE_REQ, seq, 4))
        framing.send_u32(self._sock, max_count)
        length = self._recv_frame_header(proto.FRAME_LEASE_GRANT, seq)
        grants = self._recv_grants(length, max_count)
        self._inc(obs_names.WORKER_WIRE_RTTS)
        return grants

    def request(self) -> Optional[Workload]:
        grants = self.request_batch(1)
        return grants[0] if grants else None

    def request_batchn(self, max_count: int,
                       batch_width: int = 0) -> list[Workload]:
        """Pull up to ``max_count`` workloads, grouped by the coordinator
        into batches no wider than ``batch_width`` (default: one group).

        The grouping matches the dispatch coalescer's fusion width, so a
        full grant batch feeds whole megakernel launches without
        re-slicing.  On a session that did not negotiate
        ``SESSION_FLAG_GRANTN`` this degrades to a flat
        :meth:`request_batch` — same tiles, one group.
        """
        if not self.flags & proto.SESSION_FLAG_GRANTN:
            return self.request_batch(max_count)
        return self._request_batchn(max_count, batch_width)

    def _request_batchn(self, max_count: int,
                        batch_width: int) -> list[Workload]:
        width = min(batch_width or max_count, max_count)
        seq = self._next_seq()
        framing.send_parts(self._sock, [
            proto.SESSION_FRAME.pack(proto.FRAME_LEASE_REQN, seq,
                                     proto.LEASE_REQN_WIRE_SIZE),
            proto.LEASE_REQN.pack(max_count, width)])
        length = self._recv_frame_header(proto.FRAME_LEASE_GRANTN, seq)
        n_batches, n_tiles = proto.LEASE_GRANTN.unpack(framing.recv_exact(
            self._sock, proto.LEASE_GRANTN_WIRE_SIZE))
        n_batches = proto.validate_count(n_batches, max_count,
                                         "grant batch count")
        n_tiles = proto.validate_count(n_tiles, max_count,
                                       "batched grant total")
        if length != (proto.LEASE_GRANTN_WIRE_SIZE + 4 * n_batches
                      + n_tiles * WORKLOAD_WIRE_SIZE):
            raise framing.ProtocolError(
                f"batched grant frame length {length} disagrees with "
                f"{n_batches} groups / {n_tiles} tiles")
        grants: list[Workload] = []
        for _ in range(n_batches):
            n = proto.validate_count(framing.recv_u32(self._sock), n_tiles,
                                     "grant group width")
            grants.extend(Workload.from_wire(
                framing.recv_exact(self._sock, WORKLOAD_WIRE_SIZE))
                for _ in range(n))
        if len(grants) != n_tiles:
            raise framing.ProtocolError(
                f"batched grant groups sum to {len(grants)}, header "
                f"declared {n_tiles}")
        self._inc(obs_names.WORKER_WIRE_RTTS)
        return grants

    def ring_info(self, client_version: int = 0) -> tuple[int, int, int]:
        """One ring exchange: ``(ring_version, shard, n_shards)`` of the
        peer.  ``client_version`` is the version of the config this
        worker loaded — the coordinator counts a mismatch as skew, the
        worker's cue to reload ``ring.json``.  Requires a session that
        negotiated ``SESSION_FLAG_SHARD``."""
        if not self.flags & proto.SESSION_FLAG_SHARD:
            raise framing.ProtocolError(
                "ring exchange on a session without SESSION_FLAG_SHARD")
        seq = self._send_frame(proto.FRAME_RING_REQ, [
            proto.RING_REQ.pack(client_version)])
        length = self._recv_frame_header(proto.FRAME_RING_INFO, seq)
        if length != proto.RING_INFO_WIRE_SIZE:
            raise framing.ProtocolError(
                f"ring info frame length {length} != "
                f"{proto.RING_INFO_WIRE_SIZE}")
        version, shard, n_shards = proto.RING_INFO.unpack(
            framing.recv_exact(self._sock, proto.RING_INFO_WIRE_SIZE))
        proto.validate_shard(shard, n_shards)
        self._inc(obs_names.WORKER_WIRE_RTTS)
        return version, shard, n_shards

    def submit_pipelined(self, results: Sequence[tuple[Workload, np.ndarray]],
                         want_lease: int = 0
                         ) -> tuple[list[bool], list[Workload]]:
        """Send every result, then collect the acks.

        All uploads go out before the first ack is awaited, so the batch
        costs one round trip; the last upload asks its ack to piggyback
        up to ``want_lease`` fresh grants, which replaces the separate
        lease round trip in steady state.

        On a ``SESSION_FLAG_SHARD`` session a misrouted result's ack is
        a ``FRAME_REDIRECT`` naming the authoritative shard: the item
        reads as not-accepted and lands in :attr:`last_redirects` for
        the caller (the multi-homed session group) to re-route.
        """
        if not results:
            return [], []
        self.last_redirects = {}
        seqs = []
        for i, (w, pixels) in enumerate(results):
            body, codec = self._encode_body(pixels)
            want = want_lease if i == len(results) - 1 else 0
            seqs.append(self._send_frame(proto.FRAME_UPLOAD, [
                w.to_wire(), proto.UPLOAD_HEADER.pack(codec, want), body]))
        accepted: list[bool] = []
        grants: list[Workload] = []
        ack_types = (proto.FRAME_UPLOAD_ACK, proto.FRAME_REDIRECT) \
            if self.flags & proto.SESSION_FLAG_SHARD \
            else (proto.FRAME_UPLOAD_ACK,)
        for i, seq in enumerate(seqs):
            frame_type, length = self._recv_frame_header_any(ack_types, seq)
            if frame_type == proto.FRAME_REDIRECT:
                if length != proto.REDIRECT_WIRE_SIZE:
                    raise framing.ProtocolError(
                        f"redirect frame length {length} != "
                        f"{proto.REDIRECT_WIRE_SIZE}")
                owner, _ring_version = proto.REDIRECT.unpack(
                    framing.recv_exact(self._sock,
                                       proto.REDIRECT_WIRE_SIZE))
                self.last_redirects[i] = owner
                self._inc(obs_names.WORKER_REDIRECTS)
                accepted.append(False)
                continue
            flag = framing.recv_byte(self._sock)
            if flag not in (proto.RESPONSE_ACCEPT, proto.RESPONSE_REJECT):
                raise framing.ProtocolError(
                    f"unexpected acceptance code {flag:#x}")
            accepted.append(flag == proto.RESPONSE_ACCEPT)
            grants.extend(self._recv_grants(length - 1, want_lease))
        self._inc(obs_names.WORKER_WIRE_RTTS)
        return accepted, grants

    def push_spans(self, worker_id: int, syncs, spans) -> bool:
        """Span report as a fire-and-forget session frame.

        No fresh connection and no ack round trip — and the clock-sync
        samples inside it came from this session's own lease/ack round
        trips, so span alignment costs nothing extra on this path.
        """
        buf = bytearray()
        buf += proto.SPANS_HEADER.pack(worker_id, len(syncs), len(spans))
        for key, t_req, t_recv in syncs:
            buf += proto.SPAN_SYNC.pack(*key, t_req, t_recv)
        for stage, key, t0, t1, device, seq in spans:
            buf += proto.SPAN_RECORD.pack(*key, _STAGE_CODES[stage],
                                          device, seq, t0, t1)
        self._send_frame(proto.FRAME_SPANS, [bytes(buf)])
        return True

    def _encode_body(self, pixels: np.ndarray) -> tuple:
        """(body, codec) for one tile, applying the compression bar."""
        data = DistributerClient._pixel_bytes(pixels)
        if self.flags & proto.SESSION_FLAG_RLE:
            arr = np.frombuffer(data, dtype=np.uint8)
            if estimate_ratio(arr, MIN_WIRE_RATIO) > MIN_WIRE_RATIO:
                body = self._codec.encode(arr)
                if len(body) * MIN_WIRE_RATIO <= len(data):
                    self._inc(obs_names.WIRE_COMPRESSED_BYTES, len(body))
                    return body, proto.WIRE_CODEC_RLE
        self._inc(obs_names.WIRE_RAW_BYTES, len(data))
        return data, proto.WIRE_CODEC_RAW


class ShardedSessionGroup:
    """Multi-homed session: one :class:`DistributerSession` per shard.

    Satisfies the pipeline's duck-typed session contract (connect /
    close / connected / flags / request_batch / request_batchn /
    submit_pipelined / push_spans), so a ``session_factory`` returning
    one of these multi-homes every lane with zero pipeline changes.

    Routing policy: lease prefetch round-robins REQN across shards (the
    first shard with grants answers; a run is dry only when every shard
    is), uploads are grouped by the ring owner of each key, and a
    ``FRAME_REDIRECT`` ack re-routes its result to the authoritative
    shard with a :data:`~distributedmandelbrot_tpu.net.protocol
    .MAX_REDIRECT_HOPS` budget — an exceeded budget (or a shard
    redirecting to itself) is a ring split-brain signature, counted in
    ``worker_redirect_loops`` and surfaced as a rejected result rather
    than an infinite loop.

    ``ring`` is duck-typed (``shards`` with host/distributer_port,
    ``owner_of(key)``, ``version``) so this module never imports the
    control package; callers hand it a ``control.ring.HashRing``.
    """

    def __init__(self, ring, *, timeout: Optional[float] = 30.0,
                 compress: bool = True, grantn: bool = True,
                 counters=None) -> None:
        self.ring = ring
        self.counters = counters
        self.sessions = [
            DistributerSession(s.host, s.distributer_port, timeout=timeout,
                               compress=compress, grantn=grantn, shard=True,
                               counters=counters)
            for s in ring.shards]
        self._rr = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def connected(self) -> bool:
        return bool(self.sessions) \
            and all(s.connected for s in self.sessions)

    @property
    def flags(self) -> int:
        """Intersection of the per-shard negotiated bits: a capability
        is usable group-wide only when every shard speaks it."""
        flags = self.sessions[0].flags if self.sessions else 0
        for s in self.sessions[1:]:
            flags &= s.flags
        return flags

    def connect(self) -> bool:
        """Dial every shard; all-or-nothing (one legacy shard that
        declines the session hello fails the group — the caller falls
        back to its connection-per-exchange client)."""
        for s in self.sessions:
            if not s.connect():
                self.close()
                return False
        # Skew probe: one ring exchange per SHARD-negotiated session.
        # A shard still speaking the pre-shard protocol negotiated the
        # bit away; key-routing still lands its uploads correctly.
        for s in self.sessions:
            if s.flags & proto.SESSION_FLAG_SHARD:
                s.ring_info(getattr(self.ring, "version", 0))
        return True

    def close(self) -> None:
        for s in self.sessions:
            s.close()

    def _inc(self, name: str, n: int = 1) -> None:
        if self.counters is not None:
            self.counters.inc(name, n)

    # -- lease prefetch ----------------------------------------------------

    def _rotate(self, op: Callable[["DistributerSession"], list[Workload]]
                ) -> list[Workload]:
        n = len(self.sessions)
        for k in range(n):
            s = self.sessions[(self._rr + k) % n]
            grants = op(s)
            if grants:
                self._rr = (self._rr + k + 1) % n
                return grants
        self._rr = (self._rr + 1) % n
        return []

    def request_batch(self, max_count: int) -> list[Workload]:
        return self._rotate(lambda s: s.request_batch(max_count))

    def request_batchn(self, max_count: int,
                       batch_width: int = 0) -> list[Workload]:
        return self._rotate(
            lambda s: s.request_batchn(max_count, batch_width))

    def request(self) -> Optional[Workload]:
        grants = self.request_batch(1)
        return grants[0] if grants else None

    # -- uploads -----------------------------------------------------------

    def submit_pipelined(self, results: Sequence[tuple[Workload, np.ndarray]],
                         want_lease: int = 0
                         ) -> tuple[list[bool], list[Workload]]:
        """Route each result to the shard the ring says owns its key;
        accept flags come back in request order, and the piggybacked
        lease ask rides the last group (grants from any shard feed the
        same pipeline window)."""
        if not results:
            return [], []
        groups: dict[int, list[int]] = {}
        for i, (w, _) in enumerate(results):
            groups.setdefault(self.ring.owner_of(w.key), []).append(i)
        accepted = [False] * len(results)
        grants: list[Workload] = []
        items = list(groups.items())
        for gi, (shard, idxs) in enumerate(items):
            want = want_lease if gi == len(items) - 1 else 0
            acc, g = self._submit_to(shard, [results[i] for i in idxs],
                                     want, proto.MAX_REDIRECT_HOPS)
            grants.extend(g)
            for i, ok in zip(idxs, acc):
                accepted[i] = ok
        return accepted, grants

    def _submit_to(self, shard: int, items, want_lease: int,
                   hops: int) -> tuple[list[bool], list[Workload]]:
        if not 0 <= shard < len(self.sessions):
            raise framing.ProtocolError(
                f"redirect names shard {shard} outside the "
                f"{len(self.sessions)}-shard ring")
        session = self.sessions[shard]
        accepted, grants = session.submit_pipelined(items,
                                                    want_lease=want_lease)
        redirects = dict(session.last_redirects)
        if not redirects:
            return accepted, grants
        if hops <= 0:
            self._inc(obs_names.WORKER_REDIRECT_LOOPS, len(redirects))
            return accepted, grants
        by_owner: dict[int, list[int]] = {}
        for i, owner in redirects.items():
            if owner == shard:
                # Redirected back at the shard that just refused it:
                # a split-brain ring, not a routing error to chase.
                self._inc(obs_names.WORKER_REDIRECT_LOOPS)
                continue
            by_owner.setdefault(owner, []).append(i)
        for owner, idxs in by_owner.items():
            sub_acc, sub_g = self._submit_to(
                owner, [items[i] for i in idxs], 0, hops - 1)
            grants.extend(sub_g)
            for i, ok in zip(idxs, sub_acc):
                accepted[i] = ok
        return accepted, grants

    # -- spans -------------------------------------------------------------

    def push_spans(self, worker_id: int, syncs, spans) -> bool:
        """Fire-and-forget on the cursor shard's socket — span reports
        are advisory, any shard's SpanStore is an acceptable sink."""
        return self.sessions[self._rr % len(self.sessions)].push_spans(
            worker_id, syncs, spans)
