"""Pipelined multi-device worker executor.

``Worker.run_once`` overlaps exactly two things: the previous batch's
upload rides a background thread while the next batch computes.  Every
round still pays the lease round-trip serially, materializes the whole
batch before the first byte uploads, and drives only the default device
outside the mesh backend.  BENCH_r05 put the cost at 58% of the device
rate (1461 Mpix/s chained vs 610 Mpix/s end-to-end).

This module replaces that two-stage overlap with a bounded in-flight
window across four stages, one thread each, coupled by queues::

    lease ──> dispatch ──> materialize ──> upload lane 0..K-1
      │           │             │             │
      │           └ round-robins tiles over every local device,
      │             at most ``depth`` in flight per device
      ├ acquires batch N+1 while batch N computes (the round-trip
      │ hides behind device time), never holding more than ``window``
      │ tiles leased-but-unsubmitted (no lease hoarding)
      │                         ├ D2H of tile k overlaps compute of k+1
      │                         │ (one-step ``copy_to_host_async``
      │                         │ lookahead), and drops the device
      │                         │ reference immediately, so the
      │                         │ allocator recycles at most ``depth``
      │                         │ output buffers per chip
      │                                       └ ``upload_lanes`` threads,
      │                                         one queue each, fed
      │                                         round-robin; each owns
      │                                         one persistent session
      │                                         (one TCP connect per
      │                                         lane per run) when the
      │                                         coordinator speaks
      │                                         PURPOSE_SESSION

    With a ``session_factory`` the upload lanes pipeline their batch
    over a persistent session and piggyback a lease request on the last
    upload's ack; granted tiles are counted into the window *before*
    the uploaded batch retires (so the cap never undercounts) and
    funneled through ``_grant_q`` back to the lease thread, which stays
    the sole producer of the dispatch queue (keeping end-of-stream
    ordering trivial).  Steady state then pays one round trip per tile
    and ``upload_lanes + 1`` TCP connects per run; against a legacy
    coordinator every session falls back to the shared
    connection-per-exchange client and behavior is exactly the old
    single-upload-thread pipeline, minus nothing.

    A crash in any stage stops the pipeline, flows shutdown sentinels
    through the queues, and re-raises from :meth:`PipelineExecutor.run`
    with the in-flight account drained to zero (abandoned tiles simply
    expire coordinator-side and are re-leased).

Per-stage service-time histograms and end-of-run occupancy/bubble
gauges land in the worker's metrics registry (obs/names.py pipeline
section), which is what ``bench.py --farm`` prints as the stage
breakdown and ``dmtpu stats`` serves.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional, Protocol, Sequence

import numpy as np

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.spans import SpanRecorder, flush_spans
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.worker.client import DistributerClient

logger = logging.getLogger("dmtpu.worker.pipeline")

# Shutdown sentinel flowed through every stage queue; each stage's
# ``finally`` forwards it downstream no matter how the stage exited, so
# joins never deadlock on a dead neighbour.
_EOS = object()

# Slice width for interruptible blocking waits (semaphore acquire, poll
# sleep): long waits are chopped so a stop/error elsewhere is noticed
# within this many seconds.
_WAIT_SLICE_S = 0.1


class TileDispatcher(Protocol):
    """How the pipeline drives a backend, one tile at a time."""

    label: str

    def devices(self) -> list:
        """Placement targets for round-robin; opaque to the pipeline."""
        ...

    def dispatch(self, workload: Workload, device):
        """Enqueue one tile's compute; returns a handle."""
        ...

    def materialize(self, handle) -> np.ndarray:
        """Resolve a handle to flat uint8 pixels (blocks on the device)."""
        ...


class DeviceDispatcher:
    """Adapter over a backend with per-tile dispatch handles
    (``dispatch_tile``/``materialize_tile``/``devices`` — the
    PallasBackend shape)."""

    def __init__(self, backend) -> None:
        self._backend = backend
        self.label = type(backend).__name__
        # Fused launches are opt-in per backend: expose the batched
        # entry point only when the backend has one, so the pipeline's
        # getattr gate keeps per-tile dispatch for everything else.
        if hasattr(backend, "dispatch_many"):
            self.dispatch_many = backend.dispatch_many

    @property
    def mesh_width(self) -> int:
        """Devices one fused launch spans (1 = no mesh route): forwarded
        from the backend so the executor sizes fusion and accounts
        dispatch permits per device, not per launch."""
        return int(getattr(self._backend, "mesh_width", 1) or 1)

    def devices(self) -> list:
        return list(self._backend.devices()) or [None]

    def dispatch(self, workload: Workload, device):
        return self._backend.dispatch_tile(workload, device=device)

    def materialize(self, handle) -> np.ndarray:
        return self._backend.materialize_tile(handle)


class SyncDispatcher:
    """Adapter over any plain :class:`ComputeBackend`: one pseudo-device,
    compute happens synchronously in the dispatch stage, materialize is a
    pass-through.  The pipeline still hides the lease round-trip and the
    upload behind compute — the two overlaps a synchronous backend can
    profit from."""

    def __init__(self, backend) -> None:
        self._backend = backend
        self.label = type(backend).__name__

    def devices(self) -> list:
        return [None]

    def dispatch(self, workload: Workload, device):
        return self._backend.compute_batch([workload])[0]

    def materialize(self, handle) -> np.ndarray:
        return handle


def as_dispatcher(backend) -> TileDispatcher:
    """The dispatcher for a backend: native per-tile handles when the
    backend exposes them, the synchronous wrapper otherwise."""
    if hasattr(backend, "dispatch_tile") \
            and hasattr(backend, "materialize_tile"):
        return DeviceDispatcher(backend)
    return SyncDispatcher(backend)


class _StageStats:
    """Busy-time account for one stage thread (single writer; readers
    tolerate a torn float — gauges are advisory)."""

    __slots__ = ("name", "busy_s", "items")

    def __init__(self, name: str) -> None:
        self.name = name
        self.busy_s = 0.0
        self.items = 0

    def add(self, seconds: float, items: int = 1) -> None:
        self.busy_s += seconds
        self.items += items


class PipelineExecutor:
    """Bounded-window staged executor over one coordinator endpoint.

    With a ``session_factory``, the lease thread and each of the
    ``upload_lanes`` lane threads hold one persistent session apiece
    (``upload_lanes + 1`` TCP connects for the whole run); otherwise all
    exchanges ride the shared connection-per-exchange ``client``.

    ``window`` caps tiles leased-but-unsubmitted across the whole
    pipeline (the lease stage's prefetch credit — what keeps one fat
    worker from hoarding leases a second worker could run).  ``depth``
    caps kernels in flight per device.  ``batch_size`` is the wire
    granularity for lease and submit exchanges.

    ``batch_tiles`` caps how many already-queued leases the dispatch
    stage coalesces into one fused launch when the dispatcher exposes
    ``dispatch_many`` (the PallasBackend megakernel); 0 means "up to
    ``depth`` per device the launch spans".  A fused launch holds one
    device-depth permit per tile ON THE DEVICE whose shard carries it
    (materialize releases them one by one), so the effective fusion
    width is ``min(batch_tiles or depth*mesh, depth*mesh)`` where
    ``mesh`` is the dispatcher's ``mesh_width`` (1 without the mesh
    route) — raise ``depth`` to fuse wider.  A mesh launch spans every
    local device, so its permits spread over all of them instead of
    charging one device for the whole launch.

    ``grant_batch`` sizes batched lease requests (FRAME_LEASE_REQN) when
    the session negotiated ``SESSION_FLAG_GRANTN``: 0 auto-sizes to
    ``window`` so one grant round trip fills the whole prefetch window
    (the reply arrives grouped to the fusion width either way); always
    capped by ``window``.  Tune it down to share a thin frontier across
    many workers.  Against a legacy coordinator the capability bit never
    negotiates and the knob is inert.

    ``clock`` is the time source for stage accounting (injectable so the
    virtual-clock tests measure overlap deterministically); it never
    drives real blocking waits.
    """

    def __init__(self, client: DistributerClient,
                 dispatcher: TileDispatcher, *,
                 window: int = 8, depth: int = 2, batch_size: int = 1,
                 upload_lanes: int = 1, batch_tiles: int = 0,
                 grant_batch: int = 0,
                 counters: Optional[Counters] = None,
                 clock: Callable[[], float] = time.monotonic,
                 spans: Optional[SpanRecorder] = None,
                 session_factory: Optional[Callable[[], object]] = None) \
            -> None:
        if window < 1:
            raise ValueError("window must be >= 1")
        if depth < 1:
            raise ValueError("depth must be >= 1")
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if upload_lanes < 1:
            raise ValueError("upload_lanes must be >= 1")
        if batch_tiles < 0:
            raise ValueError("batch_tiles must be >= 0")
        if grant_batch < 0:
            raise ValueError("grant_batch must be >= 0")
        self.client = client
        self.dispatcher = dispatcher
        self.window = window
        self.depth = depth
        self.batch_size = batch_size
        self.upload_lanes = upload_lanes
        self.batch_tiles = batch_tiles
        # Zero-arg callable yielding an UNCONNECTED DistributerSession
        # (or duck-type); each upload lane and the lease thread open
        # their own.  None keeps every exchange on ``client``.
        self.session_factory = session_factory
        self.counters = counters if counters is not None else Counters()
        self.registry = self.counters.registry
        self._hist_labels = {"backend": dispatcher.label}
        # Cross-process spans (obs/spans.py).  Span timestamps always
        # come from the recorder's own clock, never ``clock`` — stage
        # accounting may run on a virtual clock in tests, but spans must
        # stay comparable with the coordinator's monotonic timeline.
        self.spans = spans

        # Stage queues are deliberately unbounded: total in-flight work
        # is already capped at ``window`` by the _cond accounting below,
        # so no queue can ever hold more than ``window`` items — a
        # maxsize would add a second, redundant blocking point.
        self._dispatch_q: queue.Queue = queue.Queue()  # dmtpu: ignore[res-queue-unbounded]
        self._mat_q: queue.Queue = queue.Queue()  # dmtpu: ignore[res-queue-unbounded]
        # One queue per upload lane, fed round-robin by the materialize
        # stage: with a shared queue a burst of tiles (a batched grant
        # landing at once) was coalesced entirely by whichever lane woke
        # first, starving the others.
        self._upload_qs: list[queue.Queue] = [
            queue.Queue()  # dmtpu: ignore[res-queue-unbounded]
            for _ in range(upload_lanes)]
        # Piggybacked lease grants parked for the lease thread — the
        # dispatch queue keeps exactly one producer, so the lease
        # stage's end-of-stream sentinel still trails every workload.
        self._grant_q: queue.Queue = queue.Queue()  # dmtpu: ignore[res-queue-unbounded]
        # _cond guards the window account and the error list; every
        # blocking queue/semaphore/client call happens OUTSIDE it.
        self._cond = threading.Condition()
        self._in_flight = 0
        self._errors: list[BaseException] = []
        self._stop = threading.Event()
        self._rounds = 0
        self._stats = {name: _StageStats(name)
                       for name in obs_names.PIPELINE_STAGES}
        # Fused-launch account (dispatch thread is the single writer;
        # stage_stats readers tolerate a torn int — advisory, like the
        # stage gauges).  Registry counters for the same events live in
        # the backend's dispatch_many, so these stay plain ints.
        self._disp_launches = 0
        self._disp_fused_launches = 0
        self._disp_mesh_launches = 0
        self._disp_tiles = 0
        # Upload busy time is accounted per lane (one writer each);
        # the STAGE_UPLOAD entry above stays zero and readers sum these.
        self._lane_stats = [_StageStats(f"{obs_names.STAGE_UPLOAD}[{i}]")
                            for i in range(upload_lanes)]
        self._t_start: Optional[float] = None
        self._t_end: Optional[float] = None
        self.clock = clock
        # Created here, not in a stage thread: both the dispatch and the
        # materialize stages use them from their first item on.
        self._devices = list(dispatcher.devices()) or [None]
        self._dev_sems = [threading.Semaphore(self.depth)
                          for _ in self._devices]
        # Batched-grant sizing (FRAME_LEASE_REQN): how many leases one
        # round trip asks for when the session negotiated GRANTN.  The
        # default fills the whole prefetch window from a single grant —
        # the window is already the anti-hoarding cap, so asking for
        # less only costs round trips; the reply still arrives grouped
        # to the fusion width, so every device's fusion launch fills
        # regardless of the count.  Tune DOWN (``grant_batch`` /
        # ``--grant-batch``) to share a thin frontier across workers.
        # A mesh launch spans mesh_width devices, each with its own
        # ``depth`` window, so the fusion cap scales with the span (a
        # 1-wide mesh reduces to the old min(batch_tiles or depth,
        # depth)).  Permits are still held per device — see
        # _dispatch_loop's shard-aligned spread.
        self._mesh_width = (int(getattr(dispatcher, "mesh_width", 1) or 1)
                            if hasattr(dispatcher, "dispatch_many") else 1)
        cap = self.depth * max(1, self._mesh_width)
        self._fusion_width = min(self.batch_tiles or cap, cap)
        self.grant_batch = min(self.window, grant_batch or self.window)

    # -- window + error accounting -----------------------------------------

    @property
    def in_flight(self) -> int:
        """Tiles leased but not yet submitted (or abandoned); 0 after
        :meth:`run` returns, crash or not."""
        with self._cond:
            return self._in_flight

    def _retire(self, n: int) -> None:
        with self._cond:
            self._in_flight -= n
            self._cond.notify_all()

    def _abandon(self, n: int) -> None:
        """Account tiles dropped on shutdown/error; their leases expire
        coordinator-side and the scheduler re-issues them."""
        if n:
            self.counters.inc(obs_names.PIPELINE_TILES_ABANDONED, n)
            self._retire(n)

    def _fail(self, err: BaseException) -> None:
        logger.error("pipeline stage failed: %r", err)
        self._stop.set()
        with self._cond:
            self._errors.append(err)
            self._cond.notify_all()

    def _stopping(self, stop: Optional[threading.Event] = None) -> bool:
        return self._stop.is_set() \
            or (stop is not None and stop.is_set())

    # -- stages ------------------------------------------------------------

    def _open_session(self, role: str):
        """One persistent session for a stage thread, or None to stay on
        the legacy client (no factory, or the coordinator declined the
        hello).  Dial errors propagate — a dead coordinator fails the
        legacy path identically, and the worker's reconnect loop owns
        that case."""
        if self.session_factory is None:
            return None
        session = self.session_factory()
        if session.connect():
            logger.debug("%s: persistent session open", role)
            return session
        logger.info("%s: coordinator declined session hello; "
                    "using legacy exchanges", role)
        return None

    @staticmethod
    def _grantn(session) -> bool:
        """True when this session negotiated batched lease grants."""
        return (session is not None and session.connected
                and bool(getattr(session, "flags", 0)
                         & proto.SESSION_FLAG_GRANTN))

    def _session_retry(self, session, role: str, op):
        """One session exchange, re-dialing once on a dead socket.

        The coordinator drops sessions idle past its read deadline by
        design (a slow backend can out-wait it between batches), and the
        documented contract is that the worker re-dials.  Safe to replay:
        a re-requested lease that was granted into the void sweeps back,
        and a replayed upload of an already-saved tile is rejected as
        stale while the chunk stays saved — at-least-once either way."""
        try:
            return op()
        except ConnectionError:
            session.close()
            if not session.connect():
                raise  # coordinator went legacy mid-run: surface it
            self.counters.inc(obs_names.WORKER_SESSION_REDIALS)
            logger.info("%s: re-dialed session after disconnect", role)
            return op()

    def _acquire(self, want: int, session=None) -> list[Workload]:
        if self._grantn(session):
            return self._session_retry(
                session, "lease",
                lambda: session.request_batchn(want, self._fusion_width))
        if session is not None and session.connected:
            return self._session_retry(
                session, "lease", lambda: session.request_batch(want))
        if want == 1:
            w = self.client.request()
            return [w] if w is not None else []
        return self.client.request_batch(want)

    def _forward_grants(self) -> int:
        """Move piggybacked grants into the dispatch queue (lease thread
        only).  Their window slots were taken by the upload lane that
        received them, so this is pure hand-off."""
        n = 0
        while True:
            try:
                w = self._grant_q.get_nowait()
            except queue.Empty:
                return n
            self._dispatch_q.put(w)
            n += 1

    def _drain_wait(self, stop: Optional[threading.Event]) -> bool:
        """The coordinator's frontier came up empty, but upload lanes may
        still be landing piggybacked grants.  Park until either a grant
        shows up (False: keep leasing) or every in-flight tile retired
        with none pending (True: the run is over).  A queued grant holds
        a window slot, so ``in_flight == 0`` implies the grant queue is
        empty — the extra check is belt and braces."""
        while not self._stopping(stop):
            if self._forward_grants():
                return False
            with self._cond:
                if self._in_flight == 0 and self._grant_q.empty():
                    return True
                self._cond.wait(timeout=_WAIT_SLICE_S)
        return True

    def _lease_loop(self, poll_interval: float,
                    stop: Optional[threading.Event]) -> None:
        st = self._stats[obs_names.STAGE_LEASE]
        session = self._open_session("lease")
        try:
            self._lease_loop_inner(poll_interval, stop, st, session)
        finally:
            if session is not None:
                session.close()

    def _lease_loop_inner(self, poll_interval: float,
                          stop: Optional[threading.Event],
                          st: _StageStats, session) -> None:
        while not self._stopping(stop):
            self._forward_grants()
            with self._cond:
                while self._in_flight >= self.window \
                        and not self._stopping(stop):
                    # Sliced so an EXTERNAL stop event (which notifies
                    # nothing) is still noticed promptly; piggybacked
                    # grants notify and are forwarded on wake-up.
                    if not self._grant_q.empty():
                        break
                    self._cond.wait(timeout=_WAIT_SLICE_S)
                if self._stopping(stop):
                    return
                room = self.window - self._in_flight
            if room <= 0:
                continue  # woken to forward grants, not to lease
            # Lease outside the lock: only this thread and the upload
            # lanes *add* to the window, and lanes net-shrink it (grants
            # never exceed the batch they retire), so ``room`` can only
            # have grown meanwhile and the prefetch can never exceed
            # ``window`` leases outstanding.
            cap = self.grant_batch if self._grantn(session) \
                else self.batch_size
            want = min(cap, room)
            s0 = self.spans.clock() if self.spans is not None else 0.0
            t0 = self.clock()
            got = self._acquire(want, session)
            dt = self.clock() - t0
            if self.spans is not None and got:
                # The lease round trip doubles as the clock-sync sample
                # the coordinator aligns this worker's spans with.
                self.spans.note_grant([w.key for w in got], s0,
                                      self.spans.clock())
            st.add(dt)
            self.counters.inc(obs_names.WORKER_LEASE_US, int(dt * 1e6))
            self.counters.inc(obs_names.PIPELINE_LEASE_EXCHANGES)
            self.registry.observe(
                obs_names.HIST_PIPELINE_STAGE_SECONDS, dt,
                labels={"stage": obs_names.STAGE_LEASE})
            if not got:
                if poll_interval <= 0:
                    if self._drain_wait(stop):
                        return  # coordinator drained; window flushed
                    continue  # piggybacked grants arrived; keep going
                waited = 0.0
                while waited < poll_interval and not self._stopping(stop):
                    slice_s = min(_WAIT_SLICE_S, poll_interval - waited)
                    if (stop.wait(slice_s) if stop is not None
                            else self._stop.wait(slice_s)):
                        return
                    waited += slice_s
                continue
            self._rounds += 1
            flight.note(obs_events.WKR_STAGE,
                        stage=obs_names.STAGE_LEASE, tiles=len(got))
            with self._cond:
                self._in_flight += len(got)
            for w in got:
                self._dispatch_q.put(w)

    def _dispatch_loop(self) -> None:
        st = self._stats[obs_names.STAGE_DISPATCH]
        devices = self._devices
        sems = self._dev_sems
        fuse = getattr(self.dispatcher, "dispatch_many", None)
        limit = self._fusion_width if fuse is not None else 1
        mesh_w = self._mesh_width if fuse is not None else 1
        i = 0
        saw_eos = False
        while not saw_eos:
            item = self._dispatch_q.get()
            if item is _EOS:
                return
            if self._stop.is_set():
                self._abandon(1)
                continue
            # Coalesce whatever is ALREADY queued (up to the fusion
            # limit) into one launch.  Never wait for more: an empty
            # queue means the lease stage is the bottleneck, and a
            # single-tile launch beats an idle device.
            batch = [item]
            while len(batch) < limit:
                try:
                    more = self._dispatch_q.get_nowait()
                except queue.Empty:
                    break
                if more is _EOS:
                    saw_eos = True
                    break
                batch.append(more)
            # Device assignment.  A mesh launch (fused batch, mesh route
            # live) spans every local device — the backend shards the
            # batch over the tiles axis in contiguous blocks — so the
            # dispatch permits are charged per DEVICE, one permit on the
            # device whose shard carries each tile, not ``len(batch)``
            # permits on one chip.  Everything else keeps the
            # round-robin.
            if mesh_w > 1 and len(batch) > 1:
                k_loc = -(-len(batch) // len(devices))
                dev_for = [min(j // k_loc, len(devices) - 1)
                           for j in range(len(batch))]
                launch_dev = None  # the mesh route places the shards
            else:
                d = i % len(devices)
                i += 1
                dev_for = [d] * len(batch)
                launch_dev = devices[d]
            held = 0
            while held < len(batch) and not self._stop.is_set():
                if sems[dev_for[held]].acquire(timeout=_WAIT_SLICE_S):
                    held += 1
            if self._stop.is_set():
                # May hold permits here; the run is over either way,
                # and permits die with the executor.
                self._abandon(len(batch))
                continue
            s0 = self.spans.clock() if self.spans is not None else 0.0
            t0 = self.clock()
            try:
                if len(batch) == 1:
                    handles = [self.dispatcher.dispatch(batch[0],
                                                        launch_dev)]
                else:
                    handles = fuse(batch, launch_dev)
            except BaseException:
                for dj in dev_for[:held]:
                    sems[dj].release()
                self._abandon(len(batch))
                raise
            dt = self.clock() - t0
            st.add(dt, len(batch))
            flight.note(obs_events.WKR_STAGE, key=batch[0].key,
                        stage=obs_names.STAGE_DISPATCH, tiles=len(batch),
                        mesh=launch_dev is None and len(batch) > 1)
            self._disp_launches += 1
            self._disp_tiles += len(batch)
            if len(batch) > 1:
                self._disp_fused_launches += 1
                if launch_dev is None:
                    self._disp_mesh_launches += 1
            if self.spans is not None:
                s1 = self.spans.clock()
                for w, dj in zip(batch, dev_for):
                    self.spans.record(obs_names.SPAN_DISPATCH, w.key,
                                      s0, s1, device=dj)
            self.registry.observe(
                obs_names.HIST_PIPELINE_STAGE_SECONDS, dt,
                labels={"stage": obs_names.STAGE_DISPATCH})
            for w, handle, dj in zip(batch, handles, dev_for):
                self._mat_q.put((w, dj, handle, t0, s0))

    @staticmethod
    def _start_host_copy(handle) -> None:
        start = getattr(handle, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:
                # Best-effort prefetch; materialize still copies.
                logger.debug("copy_to_host_async prefetch failed",
                             exc_info=True)

    def _materialize_loop(self) -> None:
        st = self._stats[obs_names.STAGE_MATERIALIZE]
        sems = self._dev_sems
        nxt = None
        lane = 0  # round-robin cursor over the upload lanes
        while True:
            item = nxt if nxt is not None else self._mat_q.get()
            nxt = None
            if item is _EOS:
                return
            workload, d, handle, t_disp, s_disp = item
            # One-step lookahead: start the NEXT tile's D2H before
            # blocking on this one, so transfer overlaps compute.
            self._start_host_copy(handle)
            try:
                nxt = self._mat_q.get_nowait()
            except queue.Empty:
                nxt = None
            if nxt is not None and nxt is not _EOS:
                self._start_host_copy(nxt[2])
            if self._stop.is_set():
                sems[d].release()
                self._abandon(1)
                continue
            s0 = self.spans.clock() if self.spans is not None else 0.0
            t0 = self.clock()
            try:
                pixels = self.dispatcher.materialize(handle)
            except BaseException:
                self._abandon(1)
                raise
            finally:
                sems[d].release()
            dt = self.clock() - t0
            st.add(dt)
            if self.spans is not None:
                s1 = self.spans.clock()
                # d2h = the materialize call (device wait + D2H copy);
                # compute = the tile's whole device residency, dispatch
                # start -> materialized, so d2h nests inside it.
                self.spans.record(obs_names.SPAN_D2H, workload.key,
                                  s0, s1, device=d)
                self.spans.record(obs_names.SPAN_COMPUTE, workload.key,
                                  s_disp, s1, device=d)
            tile_s = self.clock() - t_disp
            flight.note(obs_events.WKR_STAGE, key=workload.key,
                        stage=obs_names.STAGE_MATERIALIZE)
            self.counters.inc(obs_names.WORKER_TILES_COMPUTED)
            self.counters.inc(obs_names.WORKER_COMPUTE_US,
                              int(tile_s * 1e6))
            self.registry.observe(
                obs_names.HIST_PIPELINE_STAGE_SECONDS, dt,
                labels={"stage": obs_names.STAGE_MATERIALIZE})
            self.registry.observe(obs_names.HIST_WORKER_COMPUTE_SECONDS,
                                  tile_s, labels=self._hist_labels)
            self._upload_qs[lane].put((workload, pixels))
            lane = (lane + 1) % len(self._upload_qs)

    def _admit_grants(self, grants: Sequence[Workload], s0: float,
                      reserved: int = 0) -> None:
        """Count piggybacked grants into the window BEFORE the batch that
        earned them retires (the cap may transiently read high, never
        low), then park them for the lease thread to forward.
        ``reserved`` slots were pre-charged when the want was sized past
        the retiring batch; settle the difference here (fewer grants
        than reserved releases the surplus)."""
        if not grants and not reserved:
            return
        if self.spans is not None and grants:
            # The ack round trip is a clock-sync sample exactly like a
            # lease exchange — no extra connect needed.
            self.spans.note_grant([w.key for w in grants], s0,
                                  self.spans.clock())
        with self._cond:
            self._in_flight += len(grants) - reserved
        for w in grants:
            self._grant_q.put(w)
        with self._cond:
            self._cond.notify_all()  # wake the parked lease thread

    def _submit(self, results: Sequence[tuple[Workload, np.ndarray]],
                lane: int, session) -> None:
        st = self._lane_stats[lane]
        s0 = self.spans.clock() if self.spans is not None else 0.0
        t0 = self.clock()
        if session is not None and session.connected:
            # Pipelined: all uploads on the wire before the first ack is
            # read, lease request piggybacked on the last one's ack.
            want = len(results)
            reserve = 0
            if self._grantn(session):
                # Piggyback the NEXT batch: ask past the retiring tiles
                # up to the grant batch, pre-charging the extra against
                # the window so the cap never undercounts while the ack
                # is in flight.
                with self._cond:
                    budget = self.window - self._in_flight + len(results)
                    want = min(self.grant_batch,
                               max(len(results), budget))
                    reserve = max(0, want - len(results))
                    self._in_flight += reserve
            try:
                accepted, grants = self._session_retry(
                    session, f"upload[{lane}]",
                    lambda: session.submit_pipelined(
                        results, want_lease=want))
            except BaseException:
                if reserve:
                    self._retire(reserve)
                raise
            self._admit_grants(grants, s0, reserved=reserve)
        elif len(results) == 1:
            accepted = [self.client.submit(*results[0])]
        else:
            accepted = self.client.submit_batch(results)
        dt = self.clock() - t0
        st.add(dt, len(results))
        flight.note(obs_events.WKR_STAGE, key=results[0][0].key,
                    stage=obs_names.STAGE_UPLOAD, tiles=len(results),
                    accepted=sum(1 for a in accepted if a))
        if self.spans is not None:
            s1 = self.spans.clock()
            for w, _ in results:
                self.spans.record(obs_names.SPAN_UPLOAD, w.key, s0, s1)
            # Push rides the upload lane thread — off the compute path.
            # Over a session it shares the lane's socket (and its clock
            # sync); legacy keeps the separate PURPOSE_SPANS exchange.
            flush_spans(self.spans,
                        session if session is not None
                        and session.connected else self.client,
                        self.counters)
        self.counters.inc(obs_names.WORKER_UPLOAD_US, int(dt * 1e6))
        self.registry.observe(
            obs_names.HIST_PIPELINE_STAGE_SECONDS, dt,
            labels={"stage": obs_names.STAGE_UPLOAD})
        self.registry.observe(obs_names.HIST_UPLOAD_LANE_BUSY_SECONDS, dt,
                              labels={"lane": str(lane)})
        self.registry.observe(obs_names.HIST_WORKER_UPLOAD_SECONDS, dt,
                              labels=self._hist_labels)
        n_ok = sum(accepted)
        self.counters.inc(obs_names.WORKER_RESULTS_ACCEPTED, n_ok)
        self.counters.inc(obs_names.WORKER_RESULTS_REJECTED,
                          len(accepted) - n_ok)
        if n_ok < len(accepted):
            logger.info("%d of %d results rejected (stale leases)",
                        len(accepted) - n_ok, len(accepted))

    def _upload_lane(self, lane: int) -> None:
        """One of ``upload_lanes`` workers, each draining its own queue
        (fed round-robin by the materialize stage).  The end-of-stream
        sentinel is fanned out to every lane queue, so each lane's own
        _EOS terminates it."""
        q = self._upload_qs[lane]
        session = self._open_session(f"upload[{lane}]")
        try:
            while True:
                item = q.get()
                if item is _EOS:
                    return
                if self._stop.is_set():
                    self._abandon(1)
                    continue
                batch = [item]
                saw_eos = False
                while len(batch) < self.batch_size:
                    try:
                        more = q.get_nowait()
                    except queue.Empty:
                        break
                    if more is _EOS:
                        saw_eos = True
                        break
                    batch.append(more)
                try:
                    self._submit(batch, lane, session)
                except BaseException:
                    self._abandon(len(batch))
                    raise
                self._retire(len(batch))
                if saw_eos:
                    return
        finally:
            if session is not None:
                session.close()

    # -- orchestration -----------------------------------------------------

    def _run_stage(self, fn, downstream) -> None:
        """``downstream`` is the next stage's queue, a list of queues
        (the materialize stage fans its sentinel out to every upload
        lane), or None for a terminal stage."""
        try:
            fn()
        except BaseException as e:  # re-raised from run()
            self._fail(e)
        finally:
            if isinstance(downstream, list):
                for q in downstream:
                    q.put(_EOS)
            elif downstream is not None:
                downstream.put(_EOS)
            else:
                # Terminal stage gone: nothing will retire tiles anymore;
                # wake the lease stage so it can notice the stop.
                with self._cond:
                    self._cond.notify_all()

    def _stage_busy(self, name: str) -> tuple[float, int, float]:
        """(busy_s, items, capacity) for a stage — capacity is how many
        threads serve it, so occupancy stays a 0..1 fraction with
        parallel upload lanes."""
        if name == obs_names.STAGE_UPLOAD:
            return (sum(ls.busy_s for ls in self._lane_stats),
                    sum(ls.items for ls in self._lane_stats),
                    float(self.upload_lanes))
        st = self._stats[name]
        return st.busy_s, st.items, 1.0

    def _register_gauges(self) -> None:
        def occupancy_fn(name: str) -> Callable[[], float]:
            def read() -> float:
                end = self._t_end if self._t_end is not None \
                    else self.clock()
                wall = max(1e-9, end - (self._t_start or end))
                busy, _, capacity = self._stage_busy(name)
                return min(1.0, busy / (wall * capacity))
            return read

        for name in obs_names.PIPELINE_STAGES:
            self.registry.gauge(obs_names.GAUGE_PIPELINE_STAGE_OCCUPANCY,
                                labels={"stage": name},
                                fn=occupancy_fn(name))
        self.registry.gauge(obs_names.GAUGE_PIPELINE_WINDOW_FILL,
                            fn=lambda: self.in_flight / self.window)

    def run(self, *, poll_interval: float = 0.0,
            stop: Optional[threading.Event] = None) -> int:
        """Run the pipeline until the coordinator drains (or, with
        ``poll_interval > 0``, until ``stop`` is set), flushing every
        in-flight tile; returns the number of non-empty lease exchanges.
        The first stage error is re-raised after shutdown completes."""
        self._register_gauges()
        self._t_start = self.clock()
        self._t_end = None
        threads = [
            threading.Thread(
                target=self._run_stage,
                args=(lambda: self._lease_loop(poll_interval, stop),
                      self._dispatch_q),
                name="dmtpu-pipe-lease", daemon=True),
            threading.Thread(
                target=self._run_stage, args=(self._dispatch_loop,
                                              self._mat_q),
                name="dmtpu-pipe-dispatch", daemon=True),
            threading.Thread(
                target=self._run_stage, args=(self._materialize_loop,
                                              self._upload_qs),
                name="dmtpu-pipe-materialize", daemon=True),
        ] + [
            threading.Thread(
                target=self._run_stage,
                args=(lambda i=i: self._upload_lane(i), None),
                name=f"dmtpu-pipe-upload-{i}", daemon=True)
            for i in range(self.upload_lanes)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._t_end = self.clock()
        # Residual accounting: anything still sitting in a queue after a
        # crash is a leased tile the pipeline abandoned (a stranded
        # piggyback grant in _grant_q holds a window slot too).
        for q in (self._dispatch_q, self._mat_q, self._grant_q,
                  *self._upload_qs):
            while True:
                try:
                    leftover = q.get_nowait()
                except queue.Empty:
                    break
                if leftover is not _EOS:
                    self._abandon(1)
        with self._cond:
            errors = list(self._errors)
        if errors:
            raise errors[0]
        return self._rounds

    def stage_stats(self) -> dict:
        """Occupancy/bubble per stage over the last run — what the farm
        bench prints.  ``bubble`` is the fraction of the run the stage
        thread spent NOT servicing items (waiting on its neighbours)."""
        end = self._t_end if self._t_end is not None else self.clock()
        wall = max(1e-9, end - (self._t_start if self._t_start is not None
                                else end))
        stages = {}
        for name in obs_names.PIPELINE_STAGES:
            busy, items, capacity = self._stage_busy(name)
            occ = min(1.0, busy / (wall * capacity))
            stages[name] = {"busy_s": round(busy, 6),
                            "items": items,
                            "occupancy": round(occ, 4),
                            "bubble": round(1.0 - occ, 4)}
        lanes = [{"busy_s": round(ls.busy_s, 6),
                  "items": ls.items,
                  "occupancy": round(min(1.0, ls.busy_s / wall), 4)}
                 for ls in self._lane_stats]
        launches = self._disp_launches
        fusion = {
            "launches": launches,
            "fused_launches": self._disp_fused_launches,
            "mesh_launches": self._disp_mesh_launches,
            "mesh_width": self._mesh_width,
            "tiles": self._disp_tiles,
            "tiles_per_launch": round(self._disp_tiles / launches, 4)
            if launches else 0.0,
            "fused_fraction": round(self._disp_fused_launches / launches,
                                    4) if launches else 0.0,
        }
        return {"wall_s": round(wall, 6), "stages": stages,
                "lanes": lanes, "fusion": fusion}
