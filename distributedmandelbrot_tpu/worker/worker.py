"""The stateless pull-loop worker.

Same lifecycle as the reference worker (``DistributedMandelbrotWorkerCUDA.py:
111-184``): request -> compute -> submit, repeating until the coordinator
reports no work (or forever with polling, for long-running farms — workers
can join or leave at any time; all state lives coordinator-side).

TPU-first extensions:

- *batched dispatch*: lease up to ``batch_size`` tiles per exchange and hand
  the whole batch to the backend in one call, so a mesh backend computes
  all of them in a single device dispatch
- *compute/IO overlap*: while batch N uploads on a background thread, batch
  N+1 is already computing — the moral equivalent of the reference farm's
  many concurrent worker processes, folded into one fat worker
- *pipelined executor* (``window > 0``): the loops delegate to
  :class:`~distributedmandelbrot_tpu.worker.pipeline.PipelineExecutor`,
  which overlaps all four stages (lease-prefetch / per-device dispatch /
  materialize / upload) under a bounded in-flight window instead of the
  two-stage overlap above.  ``run_once`` stays the single-round
  primitive; anything loop-shaped should run pipelined.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Sequence

import numpy as np

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.spans import SpanRecorder, flush_spans
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.worker.backends import ComputeBackend
from distributedmandelbrot_tpu.worker.client import DistributerClient

logger = logging.getLogger("dmtpu.worker")


class Worker:
    def __init__(self, client: DistributerClient, backend: ComputeBackend, *,
                 batch_size: int = 1, overlap_io: bool = True,
                 counters: Optional[Counters] = None,
                 window: int = 0, depth: int = 2,
                 upload_lanes: int = 0, batch_tiles: int = 0,
                 grant_batch: int = 0,
                 use_session: bool = True,
                 ring=None) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if window < 0:
            raise ValueError("window must be >= 0 (0 = classic overlap)")
        if depth < 1:
            raise ValueError("depth must be >= 1")
        if upload_lanes < 0:
            raise ValueError("upload_lanes must be >= 0 (0 = auto)")
        if batch_tiles < 0:
            raise ValueError("batch_tiles must be >= 0 (0 = depth)")
        if grant_batch < 0:
            raise ValueError("grant_batch must be >= 0 (0 = auto)")
        self.client = client
        self.backend = backend
        self.batch_size = batch_size
        self.overlap_io = overlap_io
        self.window = window
        self.depth = depth
        # 0 = auto: one lane per local device, capped at 4 (lanes hide
        # upload latency behind each other; past the device count they
        # only add idle sockets).  Only the pipelined path (window > 0)
        # uses lanes.
        self.upload_lanes = upload_lanes
        # Fused-launch width for the pipelined dispatch stage (0 = fuse
        # up to ``depth``); only backends exposing dispatch_many fuse.
        self.batch_tiles = batch_tiles
        # Batched lease grants per session round trip (0 = auto-size to
        # the fusion width × device count); pipelined path only.
        self.grant_batch = grant_batch
        self.use_session = use_session
        # Sharded control plane: a duck-typed control.ring.HashRing
        # multi-homes every pipeline lane via ShardedSessionGroup (one
        # session per shard, leases round-robined, uploads routed by
        # key).  Ring mode is a pipelined-session feature: the classic
        # run_once path keeps talking to ``client`` alone.
        self.ring = ring
        self.counters = counters if counters is not None else Counters()
        self.registry = self.counters.registry
        # A client constructed without its own counters adopts the
        # worker's, so reconnect metrics land in the same scrape as
        # compute/upload (one Counters per worker process).
        if getattr(client, "counters", None) is None:
            client.counters = self.counters
        # Backends that keep their own phase instruments adopt the
        # worker's registry, so one scrape sees the whole picture.
        bind = getattr(backend, "bind_registry", None)
        if bind is not None:
            bind(self.registry)
        # Per-stage spans, pushed to the coordinator after each upload
        # (obs/spans.py).  A backend that can time its own per-tile
        # compute/D2H phases adopts the recorder and owns those stages;
        # otherwise run_once records batch-granularity compute spans.
        self.spans = SpanRecorder()
        # Flight recorder: the worker names the process and stamps its
        # span worker id into the dump header, which is the join key
        # postmortem uses against coordinator-dump clock offsets.
        rec = flight.ensure("worker", registry=self.registry)
        if rec is not None and rec.worker_id is None:
            rec.worker_id = format(self.spans.worker_id, "016x")
        bind_spans = getattr(backend, "bind_spans", None)
        self._backend_spans = bind_spans is not None
        if bind_spans is not None:
            bind_spans(self.spans)
        # Histograms are labeled by backend class so a mixed farm's
        # artifacts separate Pallas tiles from the numpy control.
        self._hist_labels = {"backend": type(backend).__name__}
        self._upload_thread: Optional[threading.Thread] = None
        self._upload_error: Optional[BaseException] = None
        self.pipeline = None  # last PipelineExecutor (stage stats)

    # -- single round -----------------------------------------------------

    def _acquire(self) -> list[Workload]:
        if self.batch_size == 1:
            w = self.client.request()
            return [w] if w is not None else []
        return self.client.request_batch(self.batch_size)

    def _submit(self, results: Sequence[tuple[Workload, np.ndarray]]) -> None:
        t0 = time.monotonic()
        if len(results) == 1:
            accepted = [self.client.submit(*results[0])]
        else:
            accepted = self.client.submit_batch(results)
        t1 = time.monotonic()
        for w, _ in results:
            self.spans.record(obs_names.SPAN_UPLOAD, w.key, t0, t1)
        # Push runs on whichever thread submitted (the overlap-IO thread
        # when enabled) — span traffic stays off the compute path.
        flush_spans(self.spans, self.client, self.counters)
        # Timed here so both the inline and the overlap-IO thread path
        # feed the same counter (bench_farm's phase breakdown).
        # Microsecond units: sub-ms loopback events would floor to zero
        # in ms and hide exactly the overheads the breakdown exposes.
        upload_s = t1 - t0
        self.counters.inc(obs_names.WORKER_UPLOAD_US, int(upload_s * 1e6))
        self.registry.observe(obs_names.HIST_WORKER_UPLOAD_SECONDS,
                              upload_s, labels=self._hist_labels)
        n_ok = sum(accepted)
        self.counters.inc(obs_names.WORKER_RESULTS_ACCEPTED, n_ok)
        self.counters.inc(obs_names.WORKER_RESULTS_REJECTED,
                          len(accepted) - n_ok)
        if n_ok < len(accepted):
            logger.info("%d of %d results rejected (stale leases)",
                        len(accepted) - n_ok, len(accepted))

    def _join_upload(self) -> None:
        if self._upload_thread is not None:
            self._upload_thread.join()
            self._upload_thread = None
            if self._upload_error is not None:
                err, self._upload_error = self._upload_error, None
                raise err

    def _start_upload(self, results: list[tuple[Workload, np.ndarray]]) -> None:
        def run() -> None:
            try:
                self._submit(results)
            except BaseException as e:  # surfaced on next join
                self._upload_error = e

        self._upload_thread = threading.Thread(target=run, daemon=True)
        self._upload_thread.start()

    def run_once(self) -> bool:
        """One pull/compute/submit round; False when no work was available."""
        t_lease = time.monotonic()
        workloads = self._acquire()
        t_grant = time.monotonic()
        self.counters.inc(obs_names.WORKER_LEASE_US,
                          int((t_grant - t_lease) * 1e6))
        if not workloads:
            self._join_upload()
            return False
        # The lease round trip doubles as the clock-sync sample the
        # coordinator aligns this worker's spans with (obs/spans.py).
        self.spans.note_grant([w.key for w in workloads], t_lease, t_grant)
        t0 = time.monotonic()
        pixels = self.backend.compute_batch(workloads)
        t_done = time.monotonic()
        compute_s = t_done - t0
        if not self._backend_spans:
            # Batch granularity: without backend phase timing every tile
            # in the batch shares the dispatch->materialize interval.
            for w in workloads:
                self.spans.record(obs_names.SPAN_COMPUTE, w.key, t0, t_done)
        self.counters.inc(obs_names.WORKER_TILES_COMPUTED, len(workloads))
        self.counters.inc(obs_names.WORKER_COMPUTE_US, int(compute_s * 1e6))
        self.registry.observe(obs_names.HIST_WORKER_COMPUTE_SECONDS,
                              compute_s, labels=self._hist_labels)
        logger.info("computed %d tiles in %.2fs", len(workloads), compute_s)
        results = list(zip(workloads, pixels))
        self._join_upload()  # previous batch must land before the next starts
        if self.overlap_io:
            self._start_upload(results)
        else:
            self._submit(results)
        return True

    # -- loops ------------------------------------------------------------

    def _device_count(self) -> int:
        devices = getattr(self.backend, "devices", None)
        if devices is None:
            return 1
        try:
            return max(1, len(list(devices())))
        except Exception:
            logger.debug("backend device probe failed; assuming 1 device",
                         exc_info=True)
            return 1

    def _session_factory(self):
        """A zero-arg DistributerSession builder targeting the client's
        coordinator, or None when sessions are off or the client is a
        test double without an address."""
        if not self.use_session:
            return None
        timeout = getattr(self.client, "timeout", 30.0)
        if self.ring is not None:
            from distributedmandelbrot_tpu.worker.client import \
                ShardedSessionGroup
            ring = self.ring

            def make_group() -> ShardedSessionGroup:
                return ShardedSessionGroup(ring, timeout=timeout,
                                           counters=self.counters)
            return make_group
        host = getattr(self.client, "host", None)
        port = getattr(self.client, "port", None)
        if host is None or port is None:
            return None
        from distributedmandelbrot_tpu.worker.client import \
            DistributerSession

        def make() -> DistributerSession:
            return DistributerSession(host, port, timeout=timeout,
                                      counters=self.counters)
        return make

    def _run_pipelined(self, *, poll_interval: float = 0.0,
                       stop: Optional[threading.Event] = None) -> int:
        from distributedmandelbrot_tpu.worker.pipeline import (
            PipelineExecutor, as_dispatcher)
        lanes = self.upload_lanes or min(4, self._device_count())
        pipe = PipelineExecutor(self.client, as_dispatcher(self.backend),
                                window=self.window, depth=self.depth,
                                batch_size=self.batch_size,
                                upload_lanes=lanes,
                                batch_tiles=self.batch_tiles,
                                grant_batch=self.grant_batch,
                                counters=self.counters, spans=self.spans,
                                session_factory=self._session_factory())
        self.pipeline = pipe
        return pipe.run(poll_interval=poll_interval, stop=stop)

    def run_until_drained(self) -> int:
        """Work until the coordinator has nothing to hand out; returns rounds
        (non-empty lease exchanges)."""
        if self.window > 0:
            return self._run_pipelined()
        rounds = 0
        while self.run_once():
            rounds += 1
        self._join_upload()
        return rounds

    def run_forever(self, poll_interval: float = 5.0,
                    stop: Optional[threading.Event] = None) -> None:
        """Work, then keep polling — the elastic-farm mode (workers may join
        while other workers' leases are still pending expiry)."""
        if self.window > 0:
            self._run_pipelined(poll_interval=poll_interval, stop=stop)
            return
        try:
            while stop is None or not stop.is_set():
                if not self.run_once():
                    # The in-flight upload must land BEFORE the poll sleep
                    # — stated here, not inherited from run_once's empty-
                    # lease path, so a computed batch can never sit
                    # unsubmitted across a full poll_interval however the
                    # round above it is restructured.
                    self._join_upload()
                    if stop is not None and stop.wait(poll_interval):
                        return
                    if stop is None:
                        time.sleep(poll_interval)
        finally:
            # Never abandon an in-flight overlap-IO upload (dropping it
            # would strand a computed batch until lease expiry) or swallow
            # an error the upload thread already recorded.
            self._join_upload()
