"""Stateless pull-loop workers and their compute backends."""

from distributedmandelbrot_tpu.worker.backends import (ComputeBackend,
                                                       JaxBackend,
                                                       NativeBackend,
                                                       NumpyBackend,
                                                       PallasBackend,
                                                       auto_backend)
from distributedmandelbrot_tpu.worker.client import DistributerClient
from distributedmandelbrot_tpu.worker.pipeline import (PipelineExecutor,
                                                       as_dispatcher)
from distributedmandelbrot_tpu.worker.worker import Worker

__all__ = ["ComputeBackend", "JaxBackend", "NativeBackend", "NumpyBackend",
           "PallasBackend", "auto_backend", "DistributerClient", "Worker",
           "PipelineExecutor", "as_dispatcher"]
