"""Compute backends: how a worker turns a workload into uint8 pixels.

The reference worker's compute is a Numba-CUDA kernel
(``DistributedMandelbrotWorkerCUDA.py:39-100``); here the same contract —
``Workload -> 16,777,216 uint8 pixels in real-fastest order`` — has three
interchangeable implementations:

- :class:`NumpyBackend` — the bit-exact golden path (slow; parity anchor)
- :class:`JaxBackend` — single-device ``jit`` kernel, f32 fast / f64 exact-ish
- :class:`PallasBackend` — the TPU throughput path (block-early-exit
  Pallas kernel, f32); selected automatically on TPU by
  :func:`auto_backend`
- the sharded mesh backend lives in
  :mod:`distributedmandelbrot_tpu.parallel` (batch pmap/shard_map)

Backends expose batch compute so mesh backends can fuse a whole lease batch
into one device dispatch; scalar backends just loop.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Protocol, Sequence

import numpy as np

from distributedmandelbrot_tpu.core.geometry import (CHUNK_WIDTH,
                                                     TileSpec,
                                                     spec_f32_resolvable)
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Registry
try:
    from distributedmandelbrot_tpu.ops import escape_time
except ImportError:  # no jax: NumpyBackend/NativeBackend still work
    escape_time = None
from distributedmandelbrot_tpu.ops import reference as ref_ops

logger = logging.getLogger("dmtpu.worker.backends")


class ComputeBackend(Protocol):
    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        """Flat uint8 pixel arrays, one per workload, real-fastest order."""
        ...


def _spec_for(workload: Workload, definition: int) -> TileSpec:
    return TileSpec.for_chunk(workload.level, workload.index_real,
                              workload.index_imag, definition=definition)


class NumpyBackend:
    """Golden-reference compute: float64 numpy, bit-identical semantics."""

    def __init__(self, definition: int = CHUNK_WIDTH) -> None:
        self.definition = definition

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        out = []
        for w in workloads:
            spec = _spec_for(w, self.definition)
            cr, ci = spec.grid_2d()
            counts = ref_ops.escape_counts(cr, ci, w.max_iter)
            out.append(ref_ops.scale_counts_to_uint8(counts, w.max_iter)
                       .ravel())
        return out


class NativeBackend:
    """C++ CPU compute: bit-identical to the golden, with per-pixel early
    exit and multithreading — the fast parity-anchor path (the reference's
    'CPU Calc path' equivalent, BASELINE.md config 1)."""

    def __init__(self, definition: int = CHUNK_WIDTH,
                 n_threads: int = 0, clamp: bool = False) -> None:
        from distributedmandelbrot_tpu import native as native_mod
        if not native_mod.native_supported():
            raise RuntimeError(
                "native library unavailable (no g++? DMTPU_NATIVE=0?)")
        self._native = native_mod
        self.definition = definition
        self.n_threads = n_threads
        self.clamp = clamp

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        out = []
        for w in workloads:
            cr, ci = _spec_for(w, self.definition).grid_flat()
            out.append(self._native.escape_pixels(
                cr, ci, w.max_iter, clamp=self.clamp,
                n_threads=self.n_threads))
        return out


class JaxBackend:
    """Single-device JAX compute (CPU or one TPU core)."""

    def __init__(self, definition: int = CHUNK_WIDTH,
                 dtype: np.dtype = np.float32,
                 segment: int = 0) -> None:
        if escape_time is None:
            raise RuntimeError(
                "JaxBackend requires jax; use the numpy or native backend")
        self.definition = definition
        self.dtype = dtype
        # 0 = the kernel's own default unroll segment.
        self.segment = segment or escape_time.DEFAULT_SEGMENT

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        return [escape_time.compute_tile(_spec_for(w, self.definition),
                                         w.max_iter, dtype=self.dtype,
                                         segment=self.segment)
                for w in workloads]


class MegaTileHandle:
    """One tile's slice of a fused megakernel launch: the on-device uint8
    plane plus this tile's bf16 scouting census (a 0-d device scalar).
    Quacks like a plain dispatch handle for the pipeline's materialize
    stage (``copy_to_host_async`` lookahead included); the census is
    read only at materialize time, after the pixel wait has already
    synchronized the launch, so it never adds a device round-trip."""

    __slots__ = ("pixels", "scout")

    def __init__(self, pixels, scout) -> None:
        self.pixels = pixels
        self.scout = scout

    def copy_to_host_async(self) -> None:
        start = getattr(self.pixels, "copy_to_host_async", None)
        if start is not None:
            start()


class PallasBackend:
    """TPU throughput path: the Pallas block-early-exit kernel (f32 only;
    coordinates generated in-kernel, so nothing but three scalars crosses
    host->device per tile).  Falls back to interpret mode off-TPU, which
    is correct but slow — use :func:`auto_backend` unless testing.

    The phase split (host-side dispatch/queue time vs materialize — the
    latter includes the wait for device completion AND the device->host
    transfer) is recorded as registry histograms under
    :data:`~distributedmandelbrot_tpu.obs.names.HIST_BACKEND_PHASE_SECONDS`
    with a ``phase`` label.  This replaced an unsynchronized ``phase_us``
    dict, which lost updates the moment two pipeline threads shared the
    backend; the registry's instruments take its lock per observation.

    Beyond the batch protocol, the backend exposes the per-tile
    dispatch/materialize pair the pipelined executor
    (:mod:`distributedmandelbrot_tpu.worker.pipeline`) schedules over
    every local device.
    """

    def __init__(self, definition: int = CHUNK_WIDTH,
                 clamp: bool = False,
                 registry: Optional[Registry] = None) -> None:
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            compute_tile_pallas_device, compute_tiles_mega_pallas)
        from distributedmandelbrot_tpu.parallel.sharding import (
            compute_tiles_mega_sharded)
        self._dispatch = compute_tile_pallas_device
        self._dispatch_mega = compute_tiles_mega_pallas
        self._dispatch_mesh = compute_tiles_mega_sharded
        # Escape hatch for the fused route (DMTPU_MEGA=0): dispatch_many
        # then degrades to a per-tile loop without touching callers.
        self._mega_enabled = os.environ.get("DMTPU_MEGA", "1") != "0"
        # Escape hatch for the mesh route (DMTPU_MESH=0): fused batches
        # then stay on one device per launch, the pre-mesh behavior.
        self._mesh_enabled = os.environ.get("DMTPU_MESH", "1") != "0"
        self.definition = definition
        self.clamp = clamp
        self.registry = registry if registry is not None else Registry()
        self.spans = None  # SpanRecorder once the worker binds one

    def bind_registry(self, registry: Registry) -> None:
        """Adopt the worker's registry so the phase histograms land where
        the exporter scrapes.  Called at worker construction, before any
        compute thread exists, so no observation can straddle the swap."""
        self.registry = registry

    def bind_spans(self, recorder) -> None:
        """Adopt the worker's span recorder: the batch path then records
        per-tile compute/d2h spans itself (it knows the tile keys; the
        worker loop only sees batch boundaries).  Same construction-time
        timing contract as :meth:`bind_registry`."""
        self.spans = recorder

    def _observe_phase(self, phase: str, seconds: float) -> None:
        self.registry.observe(obs_names.HIST_BACKEND_PHASE_SECONDS,
                              seconds, labels={"phase": phase})

    def devices(self) -> list:
        """Dispatch targets, in the shared mesh placement order."""
        from distributedmandelbrot_tpu.parallel.mesh import device_ring
        return device_ring()

    @property
    def mesh_width(self) -> int:
        """Devices one fused launch spans on the mesh route (1 = the
        route is off: a single local device, ``DMTPU_MESH=0``, or the
        fused path itself disabled).  The pipelined executor reads this
        to account dispatch-stage permits per device, not per launch."""
        if not (self._mega_enabled and self._mesh_enabled):
            return 1
        return max(1, len(self.devices()))

    def _mxu_shadow(self, specs, max_iters) -> None:
        """Census-only MXU mode: run the advisory bf16 panel census for
        one fused batch and record what it predicts.  Host-blocking but
        tiny (a ``CENSUS_PANEL**2`` sub-grid per tile, <=64 steps)."""
        from distributedmandelbrot_tpu.ops.mxu_iteration import (
            mxu_census_counts)
        from distributedmandelbrot_tpu.ops.pallas_escape import _params_row
        try:
            rows = [_params_row(s) for s in specs]
            counts = mxu_census_counts(rows, max_iters,
                                       height=specs[0].height,
                                       width=specs[0].width)
        except Exception:
            # Advisory-only by contract: a census failure must never
            # take down the real dispatch it shadows.
            logger.debug("mxu census shadow failed", exc_info=True)
            return
        self.registry.inc(obs_names.WORKER_KERNEL_MXU_CENSUS,
                          by=int(counts.sum()))

    def dispatch_tile(self, workload: Workload, device=None):
        """Enqueue one tile's kernel on ``device``; returns the handle to
        pass to :meth:`materialize_tile` (an on-device array, or a host
        array when the tile fell back to the XLA path)."""
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            PallasUnsupported)
        spec = _spec_for(workload, self.definition)
        t0 = time.monotonic()
        try:
            handle = self._dispatch(spec, workload.max_iter,
                                    clamp=self.clamp, device=device)
        except PallasUnsupported:
            # Intentional rejections only (granule, int32 cap, or
            # sub-f32-resolution pitch); other errors propagate.  A
            # pitch the kernel declined would alias identically on
            # the XLA f32 path, so those tiles fall back to f64 —
            # honoring the rejection's point, not just re-routing it.
            dt = (np.float32 if spec_f32_resolvable(spec)
                  else np.float64)
            handle = escape_time.compute_tile(spec, workload.max_iter,
                                              clamp=self.clamp, dtype=dt)
        self._observe_phase(obs_names.PHASE_DISPATCH,
                            time.monotonic() - t0)
        return handle

    def dispatch_many(self, workloads: Sequence[Workload],
                      device=None) -> list:
        """Fuse a same-shaped tile batch into ONE megakernel launch on
        ``device``; returns per-tile handles (:class:`MegaTileHandle`
        slices of the fused output) in workload order, so the
        materialize/upload stages downstream are batch-oblivious.

        This is the default dispatch route for fused batches — the
        per-call dispatch constant is paid once per batch instead of
        once per tile (ROADMAP item 4; BENCH_r05's 610-vs-1461 Mpix/s
        gap).  With more than one local device (and ``device=None``,
        i.e. the caller did not pin the launch) the batch additionally
        shards over the ``tiles`` mesh axis so ONE launch drives every
        chip (the mesh route; ``DMTPU_MESH=0`` opts out, and a
        mesh-unsupported batch demotes to the single-device fused
        launch).  Falls back to the per-tile :meth:`dispatch_tile` loop
        (which has its own XLA fallback) when the batch is a singleton,
        when any tile's shape/pitch/budget is Pallas-unsupported, or
        under ``DMTPU_MEGA=0``.  One unsupported tile demotes the whole
        batch: mixed routes would reorder completion against the
        per-device window the executor leases, for a case (odd shapes
        on the farm path) that is already the slow path.

        The MXU gate (``ops/mxu_iteration``) resolves here too: in
        ``full`` mode the fused kernels run the matmul-form recurrence
        (bit-parity proven on this platform); in ``census`` mode the
        recurrence stays on the VPU form and the advisory shadow census
        runs alongside, with the demotion counted.
        """
        from distributedmandelbrot_tpu.ops.mxu_iteration import mxu_mode
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            PallasUnsupported)
        if len(workloads) == 1 or not self._mega_enabled:
            return [self.dispatch_tile(w, device) for w in workloads]
        specs = [_spec_for(w, self.definition) for w in workloads]
        max_iters = [w.max_iter for w in workloads]
        mode = mxu_mode()
        t0 = time.monotonic()
        tiles = None
        mesh_n = self.mesh_width if device is None else 1
        if mesh_n > 1:
            try:
                tiles, scout = self._dispatch_mesh(
                    specs, max_iters, clamp=self.clamp,
                    use_mxu=(mode == "full"))
            except PallasUnsupported:
                tiles = None  # demote to the single-device fused launch
                flight.note(obs_events.WKR_DEMOTE, key=workloads[0].key,
                            route="mesh_to_fused", tiles=len(workloads))
        if tiles is None:
            mesh_n = 1
            try:
                tiles, scout = self._dispatch_mega(
                    specs, max_iters, clamp=self.clamp, device=device,
                    use_mxu=(mode == "full"))
            except PallasUnsupported:
                flight.note(obs_events.WKR_DEMOTE, key=workloads[0].key,
                            route="fused_to_per_tile",
                            tiles=len(workloads))
                return [self.dispatch_tile(w, device) for w in workloads]
        self.registry.inc(obs_names.WORKER_KERNEL_FUSED_LAUNCHES)
        self.registry.inc(obs_names.WORKER_KERNEL_FUSED_TILES,
                          by=len(workloads))
        if mesh_n > 1:
            self.registry.inc(obs_names.WORKER_MESH_LAUNCHES)
            self.registry.inc(obs_names.WORKER_MESH_DEVICES, by=mesh_n)
        if mode == "full":
            self.registry.inc(obs_names.WORKER_KERNEL_MXU_LAUNCHES)
        elif mode == "census":
            self.registry.inc(obs_names.WORKER_KERNEL_MXU_DEMOTIONS)
            flight.note(obs_events.WKR_DEMOTE, key=workloads[0].key,
                        route="mxu_census", tiles=len(workloads))
            self._mxu_shadow(specs, max_iters)
        self._observe_phase(obs_names.PHASE_DISPATCH,
                            time.monotonic() - t0)
        return [MegaTileHandle(tiles[i], scout[i, 0])
                for i in range(len(workloads))]

    def materialize_tile(self, handle) -> np.ndarray:
        """Device->host transfer of one dispatched tile -> flat uint8.

        Dropping the device reference here (the handle dies with this
        frame) is what makes output buffers recycle: with the executor's
        bounded per-device window, the allocator holds at most ``depth``
        output tiles per chip and reuses them across dispatches instead
        of growing with the batch."""
        t0 = time.monotonic()
        if isinstance(handle, MegaTileHandle):
            out = np.asarray(handle.pixels).reshape(-1)
            # The pixel wait above synchronized the launch, so the
            # census scalar is a free host read.
            pruned = int(np.asarray(handle.scout))
            if pruned:
                self.registry.inc(obs_names.WORKER_KERNEL_BF16_PRUNED,
                                  by=pruned)
        else:
            out = np.asarray(handle).reshape(-1)
        self._observe_phase(obs_names.PHASE_MATERIALIZE,
                            time.monotonic() - t0)
        return out

    def compute_batch(self, workloads: Sequence[Workload]) -> list[np.ndarray]:
        # Two-phase: dispatch every tile's kernel first (the device queue
        # runs them back to back), then materialize — compute of tile k
        # overlaps the device->host transfer of tile k-1.
        if self.spans is None:
            pending = [self.dispatch_tile(w) for w in workloads]
            return [self.materialize_tile(p) for p in pending]
        clock = self.spans.clock
        pending = []
        for w in workloads:
            t0 = clock()
            handle = self.dispatch_tile(w)
            pending.append((w, handle, t0,
                            clock()))
        out = []
        for w, handle, t_disp, t_disp_end in pending:
            self.spans.record(obs_names.SPAN_DISPATCH, w.key,
                              t_disp, t_disp_end)
            t0 = clock()
            out.append(self.materialize_tile(handle))
            t1 = clock()
            # d2h = the materialize call (device wait + D2H); compute =
            # dispatch start -> materialized, so d2h nests inside it.
            self.spans.record(obs_names.SPAN_D2H, w.key, t0, t1)
            self.spans.record(obs_names.SPAN_COMPUTE, w.key, t_disp, t1)
        return out


def recompute_unresolvable_f32(workloads: Sequence[Workload],
                               out: list, definition: int, *,
                               clamp: bool = False) -> list:
    """Replace (by list-slot assignment, never in-place buffer writes —
    gathered device arrays are read-only) the pixels of tiles whose
    pitch aliases in f32 with f64 recomputes.  The single copy of the
    recompute action shared by the mesh backend and the SPMD worker;
    the threshold itself is geometry.spec_f32_resolvable."""
    for i, w in enumerate(workloads):
        spec = _spec_for(w, definition)
        if not spec_f32_resolvable(spec):
            out[i] = escape_time.compute_tile(spec, w.max_iter,
                                              clamp=clamp,
                                              dtype=np.float64)
    return out


def auto_backend(definition: int = CHUNK_WIDTH,
                 dtype: np.dtype | None = None) -> ComputeBackend:
    """Best available single-device backend.

    ``dtype=None`` (the default) picks the best precision/speed trade
    per platform: Pallas f32 on a live TPU, else the native C++ kernel
    when it builds — faster than JAX-on-CPU *and* bit-exact f64, the
    reference worker's own precision
    (``DistributedMandelbrotWorkerCUDA.py:39``) — else portable JAX.

    An EXPLICIT dtype pins the output semantics (a farm of
    heterogeneous hosts must not mix f32 and f64 tiles because only
    some of them have g++): f32 selects the f32 fast paths
    (Pallas/JAX), f64 the bit-exact paths (native/JAX)."""
    # Identity checks against None, never `in`/`==`: numpy treats None
    # as "the default dtype" so np.dtype(float64) == None is True(!) and
    # a membership test would route an explicit f64 to the f32 paths.
    want = None if dtype is None else np.dtype(dtype)
    if (want is None or want == np.dtype(np.float32)) \
            and definition >= 128:
        try:
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                pallas_available)
            if pallas_available():
                return PallasBackend(definition=definition)
        except Exception:
            # Fallback chain by design, but never a silent one: probe
            # failures here decide which kernel a whole farm runs.
            logger.debug("pallas probe failed; falling through",
                         exc_info=True)
    if want is None or want == np.dtype(np.float64):
        try:
            from distributedmandelbrot_tpu import native as native_mod
            if native_mod.native_supported():
                return NativeBackend(definition=definition)
        except Exception:
            logger.debug("native probe failed; falling through",
                         exc_info=True)
    if escape_time is None:
        # jax absent entirely (protocol-smoke CI lanes): the golden
        # numpy path is slow but always importable.
        logger.warning("jax unavailable; auto backend falling back to "
                       "NumpyBackend")
        return NumpyBackend(definition=definition)
    return JaxBackend(definition=definition,
                      dtype=np.float32 if want is None else dtype)
