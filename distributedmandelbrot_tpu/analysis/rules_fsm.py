"""``fsm-*`` rules: explicit-state checking of the extracted protocol
automata (v4 of the analysis stack).

``rules_proto``'s frame-parity model is linear: it mirrors the source
order of reads and writes and cannot follow the session tier's loops,
capability-gated arms, or piggybacked grants.  This family closes that
blind spot by model checking instead of mirroring: :mod:`.fsm` lifts
each wire exchange's endpoints into nondeterministic send/recv automata
and :mod:`.explore` exhaustively walks the asynchronous client x server
product under every realistic capability configuration.

- ``fsm-dual`` — a send with no matching receive arm on the peer,
  either statically (no arm for the label at all) or dynamically (a
  reachable product state wedges with an unconsumable queue head).
  The crash-interleaving model's exactly-once assertion reports here
  too: a double commit is the persistence pipeline's dual failure.
- ``fsm-deadlock`` — a reachable product state where both endpoints
  wait forever, a state that cannot reach end-of-stream (liveness), or
  a crash interleaving that quiesces with the tile lost.
- ``fsm-cap-gate`` — hello-mask asymmetry: a receive arm demands a
  capability the sender does not guarantee when emitting that label.
- ``fsm-dead-arm`` — a receive arm no explored configuration of any
  exchange ever exercises (the PR 13 redirect refactor's leftovers),
  or a ``faults.hit`` crashpoint seam the crash model does not cover.

Like the rest of the package: stdlib ``ast`` only, never imports the
modules under analysis, and skips silently on fixture projects that
lack the endpoint qualnames.
"""

from __future__ import annotations

import ast
from typing import Optional

from distributedmandelbrot_tpu.analysis import explore, fsm
from distributedmandelbrot_tpu.analysis.astutil import attr_chain, cached_walk
from distributedmandelbrot_tpu.analysis.engine import (PACKAGE, Finding,
                                                       Project, Rule)

RULES = (
    Rule("fsm-dual", "fsm", "error",
         "every reachable send needs a matching receive arm on the peer "
         "(product exploration; crash model's exactly-once)"),
    Rule("fsm-deadlock", "fsm", "error",
         "no reachable product state may wait forever or lose "
         "liveness-to-EOS (crash model's no-lost-tile)"),
    Rule("fsm-cap-gate", "fsm", "error",
         "a receive arm must not demand capabilities the sender does "
         "not guarantee for that label"),
    Rule("fsm-dead-arm", "fsm", "warning",
         "receive arms never exercised in any explored configuration, "
         "and crashpoint seams outside the crash model"),
)

_BY_KIND = {
    "dual": "fsm-dual",
    "crash-dual": "fsm-dual",
    "deadlock": "fsm-deadlock",
    "liveness": "fsm-deadlock",
    "crash-lost": "fsm-deadlock",
    "cap-gate": "fsm-cap-gate",
}

_SEVERITY = {r.id: r.severity for r in RULES}

FAULTS_SUFFIX = "utils/faults.py"


def _fallback_origin(pair: fsm.EndpointPair) -> tuple:
    for auto in (pair.client, pair.server):
        for e in auto.edges:
            if e.origin[0]:
                return e.origin
    return ("", 0)


def _mk(rule: str, origin: tuple, message: str) -> Finding:
    path, line = origin
    return Finding(rule, _SEVERITY[rule], path, line or 1, message)


def _violation_findings(rep: explore.ExploreReport) -> list[Finding]:
    out: list[Finding] = []
    seen: set = set()
    for pr in rep.pairs:
        fb = _fallback_origin(pr.pair)
        for v in pr.violations:
            rule = _BY_KIND.get(v.kind)
            if rule is None:
                continue
            origin = v.origin if v.origin[0] else fb
            key = (rule, origin, v.message)
            if key in seen:
                continue
            seen.add(key)
            out.append(_mk(rule, origin, v.message))
    return out


def _dead_arm_findings(rep: explore.ExploreReport) -> list[Finding]:
    return [
        _mk("fsm-dead-arm", origin,
            f"receive arm for {label} is never exercised in any "
            f"explored configuration of any exchange")
        for origin, label in rep.dead_arms()]


def _crash_findings(project: Project) -> list[Finding]:
    """The persistence-pipeline model check, anchored at the faults
    module that registers the crash seams.  Only meaningful on the real
    tree (fixture projects carry no faults module)."""
    faults_rel: Optional[str] = None
    for rel in sorted(project.files):
        if rel.endswith(FAULTS_SUFFIX):
            faults_rel = rel
            break
    if faults_rel is None:
        return []
    out: list[Finding] = []
    rep = explore.explore_crash_model()
    for v in rep.violations:
        rule = _BY_KIND.get(v.kind)
        if rule is not None:
            out.append(_mk(rule, (faults_rel, 1), v.message))
    for seam in sorted(set(explore.CRASH_SEAMS) - rep.seams_fired):
        out.append(_mk(
            "fsm-dead-arm", (faults_rel, 1),
            f"crash seam {seam!r} never fired in the interleaving "
            f"model — its window predicate is unreachable"))
    # Coverage the other way: every crashpoint the code registers via
    # faults.hit("...") must be a seam the model crashes at, or the
    # model's exactly-once proof silently excludes that window.
    for rel in sorted(project.files):
        sf = project.files[rel]
        for node in cached_walk(sf.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "hit" \
                    or "faults" not in chain[:-1]:
                continue
            point = node.args[0].value
            if point not in explore.CRASH_SEAMS:
                out.append(_mk(
                    "fsm-dead-arm", (rel, node.args[0].lineno),
                    f"crashpoint seam {point!r} is not covered by the "
                    f"crash-interleaving model (register it in "
                    f"analysis/explore.py CRASH_SEAMS)"))
    return out


def check(project: Project) -> list[Finding]:
    pairs = fsm.build_pairs(project)
    out: list[Finding] = []
    if pairs:
        rep = explore.explore_all(pairs)
        out.extend(_violation_findings(rep))
        out.extend(_dead_arm_findings(rep))
    out.extend(_crash_findings(project))
    return out
