"""Protocol automata lifted from the wire endpoints (the v4 engine layer).

PR 6's ``proto-frames`` compares *linear* frame sequences; the session
tier stopped being linear several PRs ago — capability negotiation,
piggybacked grants, REDIRECT arms and batched leases make both
endpoints genuine state machines.  This module extracts each endpoint
into a nondeterministic send/recv automaton straight from the stdlib
AST (never importing the package — same contract as every other
analysis module), so :mod:`.explore` can compose client x server and
exhaustively check dual conformance, deadlock freedom and liveness.

Model (message granularity, payload-blind):

- **States** are program points; every state is auto-named
  ``func:L<line>`` so findings can name the stuck pair.
- **Edges** are ``send``/``recv``/``eps`` transitions labeled with a
  wire *message*: a purpose/status byte constant (``PURPOSE_SESSION``,
  ``QUERY_ACCEPT``), a frame type (``FRAME_UPLOAD``, from the
  ``SESSION_FRAME.pack`` first argument), or a header struct name
  (``SESSION_HELLO``, ``QUERY``, ``SESSION_REPLY``).  Everything else
  on the wire — grant lists, upload bodies, redirect payloads, span
  reports — is payload and invisible here (``proto-frames`` /
  ``wire-*`` keep covering it).
- **Guards**: capability tests (``flags & SESSION_FLAG_X``,
  ``negotiated & SESSION_FLAG_X``) stamp edges with positive/negative
  cap atoms; ``ring_slice is not None`` stamps the ``SHARDED``
  pseudo-atom (server-side deployment shape, not a hello flag).
- **Counters**: ``xs = []`` / ``xs.append`` / ``for .. in
  enumerate(xs)`` pairs become bounded counters so the pipelined
  upload window (send N, then read N acks) explores finitely.
- **Faults**: a recv inside ``try/except ConnectionError`` gets a
  sibling ``recv EOS`` edge into the handler — connection drop as a
  first-class transition (the server's clean end-of-session path and
  the client's legacy-hello fallback both fall out of this).
- ``raise`` paths are dropped (crash-stop): a branch that can only
  raise contributes no edges, so defensive validation never shows up
  as a protocol move.  In particular ``if not caps & X: raise`` models
  capability *gating*, and a selector mismatch arm (``if frame_type
  not in want: raise``) models the *absence* of a receive arm.

Soundness caveats (documented in the README): payload values are not
tracked, helper splicing is depth-bounded, comprehension bodies are
not walked (all comprehension-embedded wire ops in the tree are
payload reads), and unknown branch conditions fork nondeterministically
— the automaton over-approximates behaviors, so exploration findings
are real reachability facts of the *model*, not of every concrete run.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional, Sequence

from distributedmandelbrot_tpu.analysis import callgraph
from distributedmandelbrot_tpu.analysis.astutil import attr_chain
from distributedmandelbrot_tpu.analysis.engine import PACKAGE, Project

__all__ = ["Automaton", "Edge", "EndpointPair", "build_pairs",
           "build_query_pairs", "build_session_pair", "to_dot"]

EOS = "EOS"          # synthesized end-of-stream message
WILD = "?"           # a status byte the extractor could not resolve

SEND, RECV, EPS = "send", "recv", "eps"

# Header structs that ARE messages; every other struct read/write is
# payload.  SESSION_FRAME is special: its pack/unpack first field is
# the frame type, so it turns into per-FRAME_* labeled edges instead.
FRAME_HEADER_STRUCT = "SESSION_FRAME"
MSG_STRUCTS = {"SESSION_HELLO", "QUERY", "RENDER_QUERY_TAIL",
               "SESSION_QUERY_TAIL", "SESSION_REPLY"}

# Status/purpose byte constants that are messages; RESPONSE_* /
# WIRE_CODEC_* bytes ride inside frame payloads and stay invisible.
_BYTE_LABEL_PREFIXES = ("PURPOSE_", "QUERY_")
_BYTE_LABELS_EXTRA = {"SESSION_ACCEPT"}

_SEND_BYTE = {"send_byte", "write_byte"}
_SEND_MANY = {"send_all", "send_parts"}
_RECV_EXACT = {"recv_exact", "read_exact"}
_RECV_BYTE = {"recv_byte", "read_byte"}
_IGNORED_WIRE = {"send_u32", "write_u32", "recv_u32", "read_u32"}

# Exception names whose handler represents the peer hanging up (EOS) —
# recvs inside such a try get the sibling fault edge.
_EOS_EXC_NAMES = {"ConnectionError", "ConnectionResetError", "OSError",
                  "EOFError", "TimeoutError", "IncompleteReadError"}

_SPLICE_DEPTH = 6
# Truncating the frontier drops continuations and leaves dangling
# states that read as phantom deadlocks downstream, so the cap must sit
# above the real peak (~112 items in the session dispatch loop).
_FRONTIER_CAP = 512

CLIENT_SESSION_CLASS = f"{PACKAGE}/worker/client.py::DistributerSession"
SERVER_SESSION_HANDLER = (f"{PACKAGE}/coordinator/distributer.py::"
                          f"Distributer._handle_session")


def _is_byte_label(name: str) -> bool:
    return (name in _BYTE_LABELS_EXTRA
            or any(name.startswith(p) and not name.endswith("_WIRE_SIZE")
                   for p in _BYTE_LABEL_PREFIXES))


def cap_atom_of(const_name: str) -> Optional[str]:
    """``SESSION_FLAG_RLE`` -> ``"RLE"`` (the exploration cap atom)."""
    if const_name.startswith("SESSION_FLAG_"):
        return const_name[len("SESSION_FLAG_"):]
    return None


@dataclass(frozen=True)
class Edge:
    """One labeled transition.  ``cops`` are counter guard/update ops
    applied atomically with the move: ``("inc"|"dec"|"reset"|"gt0"|
    "eq0", counter_index)``."""

    src: int
    dst: int
    kind: str            # send | recv | eps
    label: str           # message, or "" for eps
    pos: frozenset = frozenset()
    neg: frozenset = frozenset()
    cops: tuple = ()
    fault: bool = False  # EOS-sibling edges (only enabled by faults)
    origin: tuple = ("", 0)  # (relpath, line)


class Automaton:
    """A nondeterministic send/recv automaton for one endpoint."""

    def __init__(self, name: str, role: str) -> None:
        self.name = name
        self.role = role  # "client" | "server"
        self.edges: list[Edge] = []
        self.state_names: dict[int, str] = {}
        self.done: set[int] = set()
        self.n_counters = 0
        self._n_states = 0
        self._out: Optional[dict[int, list[Edge]]] = None
        self._memo: dict = {}
        self._edge_set: set[Edge] = set()
        self._live: Optional[dict[int, frozenset]] = None
        self.start = self.new_state("start")

    def new_state(self, name: str) -> int:
        s = self._n_states
        self._n_states += 1
        self.state_names[s] = name
        return s

    def memo_state(self, key: tuple, name: str) -> int:
        """Shared successor state: frontier items at the same program
        point taking the same move converge instead of minting copies
        (keeps the automaton near-linear in source size)."""
        st = self._memo.get(key)
        if st is None:
            st = self.new_state(name)
            self._memo[key] = st
        return st

    def new_counter(self) -> int:
        k = self.n_counters
        self.n_counters += 1
        return k

    def add_edge(self, edge: Edge) -> Edge:
        if edge in self._edge_set:
            return edge
        self._edge_set.add(edge)
        self.edges.append(edge)
        self._out = None
        self._live = None
        return edge

    def out(self, state: int) -> list[Edge]:
        if self._out is None:
            self._out = {}
            for e in self.edges:
                self._out.setdefault(e.src, []).append(e)
        return self._out.get(state, [])

    def describe(self, state: int) -> str:
        return f"{self.role}@{self.state_names.get(state, state)}"

    def live_counters(self) -> dict[int, frozenset]:
        """Backward liveness per state: counter k is live when some
        path ahead tests it (gt0/eq0/dec) before resetting it.  Dead
        counters can be normalized to zero during exploration — stale
        window counts from a finished exchange would otherwise
        multiply the product state space for no semantic reason.
        Cached: the exploration asks once per capability config but
        the answer only depends on the (frozen) edge set."""
        if self._live is not None:
            return self._live
        live: dict[int, set] = {s: set() for s in self.state_names}
        changed = True
        while changed:
            changed = False
            for e in self.edges:
                uses = {k for op, k in e.cops
                        if op in ("gt0", "eq0", "dec")}
                kills = {k for op, k in e.cops if op == "reset"}
                new = uses | (live.get(e.dst, set()) - kills)
                if not new <= live[e.src]:
                    live[e.src] |= new
                    changed = True
        self._live = {s: frozenset(v) for s, v in live.items()}
        return self._live


@dataclass
class EndpointPair:
    """One composed exchange: a client automaton and its server peer."""

    name: str
    kind: str  # "session" | "query"
    client: Automaton
    server: Automaton


# -- abstract values --------------------------------------------------------
#
# The extractor's tiny value domain: frozenset of constant names
# ("FRAME_UPLOAD", "True"), Tup for literal tuples, Cond for the
# `(a, b) if caps & X else (a,)` idiom, Ctr for counter-linked lists,
# RxSel for a received-but-not-yet-tested selector (frame type or
# status byte), Probe for `DICT.get(selector)` results, None=unknown.

@dataclass(frozen=True)
class Tup:
    items: tuple


@dataclass(frozen=True)
class Cond:
    atom: str
    then: object
    other: object


@dataclass(frozen=True)
class Ctr:
    index: int


@dataclass(frozen=True)
class RxSel:
    src: int            # state the selecting recv happens from
    excluded: frozenset  # labels already ruled out
    origin: tuple = ("", 0)


@dataclass(frozen=True)
class Probe:
    var: str
    keys: frozenset     # dict keys (label constants)


class _Item:
    """One frontier element: a program point plus its abstract context."""

    __slots__ = ("state", "env", "pos", "neg", "eos", "pending")

    def __init__(self, state: int, env: dict, pos: frozenset, neg: frozenset,
                 eos: Optional[int] = None,
                 pending: Optional[tuple] = None) -> None:
        self.state = state
        self.env = env
        self.pos = pos
        self.neg = neg
        self.eos = eos            # handler-entry state for EOS siblings
        self.pending = pending    # (src, dst, varname) deferred byte send

    def fork(self, **kw) -> "_Item":
        it = _Item(self.state, dict(self.env), self.pos, self.neg,
                   self.eos, self.pending)
        for k, v in kw.items():
            setattr(it, k, v)
        return it

# -- extraction -------------------------------------------------------------

class Extractor:
    """AST -> automaton, threading a frontier of :class:`_Item` through
    each statement block.  One instance per automaton build."""

    def __init__(self, project: Project, auto: Automaton) -> None:
        self.project = project
        self.auto = auto
        self.graph = callgraph.graph_for(project)
        self._ctr_by_node: dict[int, int] = {}

    # -- small helpers ----------------------------------------------------

    def _origin(self, relpath: str, node: ast.AST) -> tuple:
        return (relpath, getattr(node, "lineno", 0))

    def _flush_pending(self, item: _Item, origin: tuple) -> None:
        """Deferred byte send that no test ever resolved: wildcard."""
        if item.pending is not None:
            src, dst, _var, porigin = item.pending
            self.auto.add_edge(Edge(src, dst, SEND, WILD, item.pos,
                                    item.neg, (), False, porigin))
            item.pending = None

    # -- abstract evaluation ----------------------------------------------

    def _const_name(self, expr: ast.expr) -> Optional[str]:
        """``proto.FRAME_UPLOAD`` / bare ``FRAME_UPLOAD`` -> name."""
        chain = attr_chain(expr)
        if chain and chain[-1].isupper():
            return chain[-1]
        return None

    def _eval(self, expr: ast.expr, item: _Item):
        if isinstance(expr, ast.Constant):
            if expr.value is True:
                return frozenset({"True"})
            if expr.value is False:
                return frozenset({"False"})
            if expr.value is None:
                return frozenset({"None"})
            return None
        if isinstance(expr, ast.Name):
            return item.env.get(expr.id)
        name = self._const_name(expr)
        if name is not None:
            return frozenset({name})
        if isinstance(expr, ast.Tuple):
            return Tup(tuple(self._eval(e, item) for e in expr.elts))
        if isinstance(expr, ast.IfExp):
            g = self._cap_guard(expr.test, item)
            if g is not None:
                atom, positive = g
                then = self._eval(expr.body, item)
                other = self._eval(expr.orelse, item)
                if positive:
                    return Cond(atom, then, other)
                return Cond(atom, other, then)
            return None
        if isinstance(expr, ast.List) and not expr.elts:
            k = self._ctr_by_node.get(id(expr))
            if k is None:
                k = self.auto.new_counter()
                self._ctr_by_node[id(expr)] = k
            return Ctr(k)
        if isinstance(expr, ast.Call):
            # enumerate(xs) / list(xs) / sorted(xs): transparent wrappers
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in ("enumerate", "list", "sorted",
                                         "tuple", "reversed") and expr.args:
                return self._eval(expr.args[0], item)
        return None

    def _cond_members(self, value) -> Optional[list[tuple[str, frozenset,
                                                          frozenset]]]:
        """Flatten a (possibly Cond-wrapped) tuple of label constants to
        ``(label, extra_pos, extra_neg)`` rows; None if not that shape."""
        def consts(v) -> Optional[set[str]]:
            if isinstance(v, Tup):
                out: set[str] = set()
                for it in v.items:
                    if isinstance(it, frozenset) and len(it) == 1:
                        out.add(next(iter(it)))
                    else:
                        return None
                return out
            if isinstance(v, frozenset):
                return set(v)
            return None

        if isinstance(value, Cond):
            then, other = consts(value.then), consts(value.other)
            if then is None or other is None:
                return None
            rows = []
            for c in sorted(then | other):
                if c in then and c in other:
                    rows.append((c, frozenset(), frozenset()))
                elif c in then:
                    rows.append((c, frozenset({value.atom}), frozenset()))
                else:
                    rows.append((c, frozenset(), frozenset({value.atom})))
            return rows
        flat = consts(value)
        if flat is None:
            return None
        return [(c, frozenset(), frozenset()) for c in sorted(flat)]

    # -- guard analysis ---------------------------------------------------

    def _cap_guard(self, test: ast.expr,
                   item: _Item) -> Optional[tuple[str, bool]]:
        """(atom, positive) for capability tests; None otherwise."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            g = self._cap_guard(test.operand, item)
            if g is not None:
                return (g[0], not g[1])
            return None
        if isinstance(test, ast.BinOp) and isinstance(test.op, ast.BitAnd):
            for side in (test.right, test.left):
                name = self._const_name(side)
                if name:
                    atom = cap_atom_of(name)
                    if atom:
                        return (atom, True)
            return None
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            chain = attr_chain(test.left)
            if chain and chain[-1] == "ring_slice" \
                    and isinstance(test.comparators[0], ast.Constant) \
                    and test.comparators[0].value is None:
                if isinstance(test.ops[0], ast.IsNot):
                    return ("SHARDED", True)
                if isinstance(test.ops[0], ast.Is):
                    return ("SHARDED", False)
        return None

    # -- wire-op classification -------------------------------------------

    def _unwrap(self, expr: ast.expr) -> ast.expr:
        """Peel `await`, `self._read(x)`, `asyncio.wait_for(x, t)`."""
        while True:
            if isinstance(expr, ast.Await):
                expr = expr.value
                continue
            if isinstance(expr, ast.Call):
                chain = attr_chain(expr.func)
                if chain and chain[-1] == "_read" and len(expr.args) == 1:
                    expr = expr.args[0]
                    continue
                if chain and chain[-1] == "wait_for" and expr.args:
                    expr = expr.args[0]
                    continue
            if isinstance(expr, ast.IfExp):
                # both arms of the timeout idiom wrap the same read
                expr = expr.body
                continue
            return expr

    def _struct_of_size(self, expr: ast.expr) -> Optional[str]:
        """``proto.X.size`` / ``proto.X_WIRE_SIZE`` -> ``X``."""
        chain = attr_chain(expr)
        if not chain:
            return None
        if chain[-1] == "size" and len(chain) > 1 and chain[-2].isupper():
            return chain[-2]
        if chain[-1].endswith("_WIRE_SIZE"):
            return chain[-1][:-len("_WIRE_SIZE")]
        return None

    def _wire_call(self, expr: ast.expr) -> Optional[tuple[str, ast.Call]]:
        """(op_name, call) when expr is a framing wire op (unwrapped)."""
        expr = self._unwrap(expr)
        if not isinstance(expr, ast.Call):
            return None
        chain = attr_chain(expr.func)
        if not chain:
            return None
        name = chain[-1]
        if name in (_SEND_BYTE | _SEND_MANY | _RECV_EXACT | _RECV_BYTE
                    | _IGNORED_WIRE):
            return (name, expr)
        if name == "write" and len(chain) >= 2 \
                and chain[-2] in ("writer", "w"):
            return ("write", expr)
        return None

    def _pack_labels(self, call: ast.Call,
                     item: _Item) -> Optional[list[tuple[str, frozenset,
                                                         frozenset]]]:
        """``STRUCT.pack(..)`` -> message rows, or None if payload."""
        chain = attr_chain(call.func)
        if not (chain and chain[-1] == "pack" and len(chain) > 1):
            return None
        struct = chain[-2]
        if struct == FRAME_HEADER_STRUCT:
            if not call.args:
                return []
            rows = self._cond_members(self._eval(call.args[0], item))
            return rows or []
        if struct in MSG_STRUCTS:
            return [(struct, frozenset(), frozenset())]
        return []  # payload struct: ignored

    # -- sends / recvs ----------------------------------------------------

    def _emit_send_rows(self, item: _Item, rows, origin: tuple) -> None:
        if not rows:
            return
        labels = tuple(sorted({r[0] for r in rows}))
        dst = self.auto.memo_state(("sent", item.state, labels),
                                   "sent " + "/".join(labels))
        for label, pos, neg in rows:
            self.auto.add_edge(Edge(item.state, dst, SEND, label,
                                    item.pos | pos, item.neg | neg,
                                    (), False, origin))
        item.state = dst

    def _do_send_byte(self, call: ast.Call, item: _Item,
                      origin: tuple) -> None:
        if len(call.args) < 2:
            return
        val = self._eval(call.args[1], item)
        rows = self._cond_members(val)
        if rows is not None:
            rows = [r for r in rows if _is_byte_label(r[0])]
            self._emit_send_rows(item, rows, origin)
            return
        # unknown status variable: deferred send, resolved by later
        # `status == CONST` tests, wildcard-flushed otherwise.
        self._flush_pending(item, origin)
        if isinstance(call.args[1], ast.Name):
            dst = self.auto.memo_state(("replied", item.state, origin),
                                       f"replied:L{origin[1]}")
            item.pending = (item.state, dst, call.args[1].id, origin)
            item.state = dst

    def _do_send_many(self, call: ast.Call, item: _Item,
                      origin: tuple) -> None:
        rows: list = []
        stack: list[ast.expr] = list(call.args)
        while stack:
            a = stack.pop(0)
            if isinstance(a, ast.Starred):
                continue
            if isinstance(a, (ast.List, ast.Tuple)):
                stack = list(a.elts) + stack
                continue
            if isinstance(a, ast.Call):
                got = self._pack_labels(a, item)
                if got:
                    rows.extend(got)
        self._emit_send_rows(item, rows, origin)

    def _recv_edge(self, item: _Item, label: str, origin: tuple) -> None:
        dst = self.auto.memo_state(("got", item.state, label),
                                   f"got {label}")
        self.auto.add_edge(Edge(item.state, dst, RECV, label, item.pos,
                                item.neg, (), False, origin))
        self._eos_sibling(item, origin)
        item.state = dst

    def _eos_sibling(self, item: _Item, origin: tuple) -> None:
        if item.eos is not None:
            self.auto.add_edge(Edge(item.state, item.eos, RECV, EOS,
                                    item.pos, item.neg, (), True, origin))

    # -- recv classification for assignments ------------------------------

    def _recv_assign(self, value: ast.expr, names: list, item: _Item,
                     ctx: "_Ctx") -> bool:
        """Handle ``x = <wire recv>`` shapes; True when consumed."""
        v = self._unwrap(value)
        struct = None
        if isinstance(v, ast.Call):
            chain = attr_chain(v.func)
            if chain and chain[-1] == "unpack" and len(chain) > 1 \
                    and chain[-2].isupper() and v.args:
                inner = self._unwrap(v.args[0])
                wc = self._wire_call(inner)
                if wc is None:
                    return False  # unpack of an already-read buffer
                struct = chain[-2]
                v = inner
        wc = self._wire_call(v)
        if wc is None:
            return False
        name, call = wc
        origin = self._origin(ctx.relpath, call)
        if name in _RECV_BYTE:
            self._flush_pending(item, origin)
            if names and names[0]:
                item.env[names[0]] = RxSel(item.state, frozenset(), origin)
                self._eos_sibling(item, origin)
            else:
                self._recv_edge(item, WILD, origin)
            return True
        if name in _RECV_EXACT:
            self._flush_pending(item, origin)
            if struct is None and len(call.args) > 1:
                struct = self._struct_of_size(call.args[1])
            if struct == FRAME_HEADER_STRUCT:
                if names and names[0]:
                    item.env[names[0]] = RxSel(item.state, frozenset(),
                                               origin)
                    self._eos_sibling(item, origin)
                else:
                    self._recv_edge(item, WILD, origin)
            elif struct in MSG_STRUCTS:
                self._recv_edge(item, struct, origin)
            # payload read: invisible
            return True
        if name in _IGNORED_WIRE:
            return True
        return False

    # -- branching --------------------------------------------------------

    def _var_test(self, test: ast.expr, item: _Item):
        """Selector/flag tests -> ``(var, rows, mode)``.  ``mode`` is
        "match" (then-branch = those labels) or "invert" (else-branch =
        those labels)."""
        neg = False
        while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            neg, test = not neg, test.operand
        if isinstance(test, ast.Name):
            val = item.env.get(test.id)
            if isinstance(val, frozenset) and val <= {"True", "False",
                                                      "None"}:
                rows = [("True", frozenset(), frozenset())]
                return (test.id, rows, "invert" if neg else "match")
            return None
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.left, ast.Name)):
            return None
        var = test.left.id
        op, comp = test.ops[0], test.comparators[0]
        val = item.env.get(var)
        if isinstance(val, Probe) and isinstance(comp, ast.Constant) \
                and comp.value is None \
                and isinstance(op, (ast.Is, ast.IsNot)):
            hit = isinstance(op, ast.IsNot) != neg
            rows = [(k, frozenset(), frozenset()) for k in sorted(val.keys)]
            return (val.var, rows, "match" if hit else "invert")
        if isinstance(op, (ast.Eq, ast.In)):
            eq = not neg
        elif isinstance(op, (ast.NotEq, ast.NotIn)):
            eq = neg
        else:
            return None
        rows = self._cond_members(self._eval(comp, item))
        if not rows:
            return None
        return (var, rows, "match" if eq else "invert")

    def _resolve_rows(self, var: str, rows, item: _Item,
                      take: bool) -> list[_Item]:
        """Items for the branch where ``var`` IS one of ``rows``
        (take=True) or is NOT (take=False)."""
        val = item.env.get(var)
        labels = frozenset(r[0] for r in rows)
        if item.pending is not None and item.pending[2] == var:
            src, dst, _, porigin = item.pending
            if take:
                for label, pos, neg in rows:
                    self.auto.add_edge(Edge(src, dst, SEND, label,
                                            item.pos | pos, item.neg | neg,
                                            (), False, porigin))
                it = item.fork(pending=None)
                it.env[var] = labels
                return [it]
            return [item.fork()]
        if isinstance(val, RxSel):
            if take:
                out = []
                for label, pos, neg in rows:
                    if label in val.excluded:
                        continue
                    it = item.fork(pos=item.pos | pos, neg=item.neg | neg)
                    if _is_byte_label(label) or label.startswith("FRAME_"):
                        dst = self.auto.memo_state(
                            ("got", val.src, label), f"got {label}")
                        self.auto.add_edge(Edge(val.src, dst, RECV, label,
                                                it.pos, it.neg, (), False,
                                                val.origin))
                        it.state = dst
                    # non-wire byte (RESPONSE_* etc.): payload, no edge
                    it.env[var] = frozenset({label})
                    out.append(it)
                return out
            it = item.fork()
            it.env[var] = RxSel(val.src, val.excluded | labels)
            return [it]
        if isinstance(val, frozenset):
            if take:
                out = []
                for label, pos, neg in rows:
                    if label not in val:
                        continue
                    it = item.fork(pos=item.pos | pos, neg=item.neg | neg)
                    it.env[var] = frozenset({label})
                    out.append(it)
                return out
            rest = val - labels
            if not rest:
                return []
            it = item.fork()
            it.env[var] = rest
            return [it]
        # unknown variable: fork both ways
        return [item.fork()]

    def _branch(self, test: ast.expr, item: _Item,
                ctx: "_Ctx") -> tuple[list[_Item], list[_Item]]:
        g = self._cap_guard(test, item)
        if g is not None:
            atom, positive = g
            if positive:
                return ([item.fork(pos=item.pos | {atom})],
                        [item.fork(neg=item.neg | {atom})])
            return ([item.fork(neg=item.neg | {atom})],
                    [item.fork(pos=item.pos | {atom})])
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            pos, neg = set(), set()
            for v in test.values:
                gv = self._cap_guard(v, item)
                if gv is not None:
                    (pos if gv[1] else neg).add(gv[0])
            then = item.fork(pos=item.pos | frozenset(pos),
                             neg=item.neg | frozenset(neg))
            return ([then], [item.fork()])
        vt = self._var_test(test, item)
        if vt is not None:
            var, rows, mode = vt
            if mode == "match":
                return (self._resolve_rows(var, rows, item, True),
                        self._resolve_rows(var, rows, item, False))
            return (self._resolve_rows(var, rows, item, False),
                    self._resolve_rows(var, rows, item, True))
        return ([item.fork()], [item.fork()])

    # -- statement walk ---------------------------------------------------

    def _dedup(self, items: list[_Item]) -> list[_Item]:
        seen: set = set()
        out: list[_Item] = []
        for it in items:
            key = (it.state, it.pos, it.neg, it.pending,
                   tuple(sorted((k, repr(v)) for k, v in it.env.items())))
            if key not in seen:
                seen.add(key)
                out.append(it)
        return out[:_FRONTIER_CAP]

    def _run_block(self, stmts: Sequence[ast.stmt], items: list[_Item],
                   ctx: "_Ctx", returns: list, breaks: list,
                   continues: list) -> list[_Item]:
        for stmt in stmts:
            nxt: list[_Item] = []
            for item in items:
                nxt.extend(self._do_stmt(stmt, item, ctx, returns,
                                         breaks, continues))
            items = self._dedup(nxt)
            if not items:
                break
        return items

    def _do_stmt(self, stmt: ast.stmt, item: _Item, ctx: "_Ctx",
                 returns: list, breaks: list,
                 continues: list) -> list[_Item]:
        if isinstance(stmt, ast.Return):
            origin = self._origin(ctx.relpath, stmt)
            self._flush_pending(item, origin)
            if isinstance(stmt.value, ast.Call) or isinstance(
                    stmt.value, ast.Await):
                call = self._unwrap(stmt.value)
                if isinstance(call, ast.Call):
                    spliced = self._try_splice(call, item, ctx)
                    if spliced is not None:
                        returns.extend(spliced)
                        return []
            rv = frozenset({"None"}) if stmt.value is None \
                else self._eval(stmt.value, item)
            returns.append((item, rv))
            return []
        if isinstance(stmt, ast.Raise):
            return []  # crash-stop: defensive paths contribute no edges
        if isinstance(stmt, ast.Break):
            breaks.append(item)
            return []
        if isinstance(stmt, ast.Continue):
            continues.append(item)
            return []
        if isinstance(stmt, ast.If):
            then_items, else_items = self._branch(stmt.test, item, ctx)
            out = self._run_block(stmt.body, then_items, ctx, returns,
                                  breaks, continues)
            out = out + self._run_block(stmt.orelse, else_items, ctx,
                                        returns, breaks, continues)
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._do_loop(stmt, item, ctx, returns)
        if isinstance(stmt, ast.Try):
            return self._do_try(stmt, item, ctx, returns, breaks,
                                continues)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._run_block(stmt.body, [item], ctx, returns,
                                   breaks, continues)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            return self._do_assign(stmt.targets[0], stmt.value, item, ctx)
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            return self._do_assign(stmt.target, stmt.value, item, ctx)
        if isinstance(stmt, ast.Expr):
            return self._do_expr(stmt.value, item, ctx)
        return [item]

    def _do_loop(self, node, item: _Item, ctx: "_Ctx",
                 returns: list) -> list[_Item]:
        origin = self._origin(ctx.relpath, node)
        self._flush_pending(item, origin)
        header = self.auto.memo_state(
            ("loop", item.state, origin),
            f"{ctx.func}:L{node.lineno}")
        self.auto.add_edge(Edge(item.state, header, EPS, "", item.pos,
                                item.neg, (), False, origin))
        breaks: list[_Item] = []
        continues: list[_Item] = []
        exits: list[_Item] = []
        body_item = item.fork(state=header)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for n in _target_names(node.target):
                body_item.env.pop(n, None)
            it_val = self._eval(node.iter, item)
            b = self.auto.memo_state(("loop-iter", header),
                                     f"{ctx.func}:L{node.lineno}:iter")
            x = self.auto.memo_state(("loop-done", header),
                                     f"{ctx.func}:L{node.lineno}:done")
            if isinstance(it_val, Ctr):
                self.auto.add_edge(Edge(header, b, EPS, "", item.pos,
                                        item.neg,
                                        (("gt0", it_val.index),
                                         ("dec", it_val.index)),
                                        False, origin))
                self.auto.add_edge(Edge(header, x, EPS, "", item.pos,
                                        item.neg,
                                        (("eq0", it_val.index),),
                                        False, origin))
            else:
                self.auto.add_edge(Edge(header, b, EPS, "", item.pos,
                                        item.neg, (), False, origin))
                self.auto.add_edge(Edge(header, x, EPS, "", item.pos,
                                        item.neg, (), False, origin))
            body_item.state = b
            exits.append(item.fork(state=x))
        else:
            infinite = (isinstance(node.test, ast.Constant)
                        and node.test.value is True)
            if not infinite:
                x = self.auto.memo_state(
                    ("loop-done", header),
                    f"{ctx.func}:L{node.lineno}:done")
                self.auto.add_edge(Edge(header, x, EPS, "", item.pos,
                                        item.neg, (), False, origin))
                exits.append(item.fork(state=x))
        falls = self._run_block(node.body, [body_item], ctx, returns,
                                breaks, continues)
        for it in falls + continues:
            self._flush_pending(it, origin)
            self.auto.add_edge(Edge(it.state, header, EPS, "", it.pos,
                                    it.neg, (), False, origin))
        return exits + breaks

    def _catches_eos(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        names: list[str] = []
        if t is None:
            return False
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            chain = attr_chain(e)
            if chain:
                names.append(chain[-1])
        return bool(set(names) & _EOS_EXC_NAMES)

    def _do_try(self, node: ast.Try, item: _Item, ctx: "_Ctx",
                returns: list, breaks: list,
                continues: list) -> list[_Item]:
        eos_handler = next((h for h in node.handlers
                            if self._catches_eos(h)), None)
        h_entry: Optional[int] = None
        if eos_handler is not None:
            h_entry = self.auto.memo_state(
                ("on-eof", ctx.relpath, node.lineno),
                f"{ctx.func}:L{node.lineno}:on-eof")
        body_item = item.fork(
            eos=h_entry if h_entry is not None else item.eos)
        falls = self._run_block(node.body, [body_item], ctx, returns,
                                breaks, continues)
        out = [it.fork(eos=item.eos) for it in falls]
        if eos_handler is not None:
            hitem = item.fork(state=h_entry)
            out += self._run_block(eos_handler.body, [hitem], ctx,
                                   returns, breaks, continues)
        return out

    def _do_assign(self, target: ast.expr, value: ast.expr, item: _Item,
                   ctx: "_Ctx") -> list[_Item]:
        names: list[Optional[str]] = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, ast.Tuple):
            names = [e.id if isinstance(e, ast.Name) else None
                     for e in target.elts]
        if self._recv_assign(value, names, item, ctx):
            for i, n in enumerate(names):
                if n and not (i == 0 and isinstance(item.env.get(n),
                                                    RxSel)):
                    item.env.pop(n, None)
            return [item]
        v = self._unwrap(value)
        if isinstance(v, ast.Call):
            chain = attr_chain(v.func)
            if chain and chain[-1] == "unpack" and len(chain) > 1 \
                    and v.args and isinstance(v.args[0], ast.Name) \
                    and isinstance(item.env.get(v.args[0].id), RxSel):
                sel = item.env.pop(v.args[0].id)
                if names and names[0]:
                    item.env[names[0]] = sel
                return [item]
            if chain and chain[-1] == "get" and len(chain) == 2 \
                    and len(v.args) >= 1 and isinstance(v.args[0],
                                                        ast.Name) \
                    and names and names[0]:
                keys = self._module_dict_keys(chain[0], ctx)
                if keys is not None:
                    item.env[names[0]] = Probe(v.args[0].id, keys)
                    return [item]
            spliced = self._try_splice(v, item, ctx)
            if spliced is not None:
                out: list[_Item] = []
                for ex, rv in spliced:
                    self._bind(ex, names, rv)
                    out.append(ex)
                return out
        val = self._eval(value, item)
        if isinstance(val, Ctr):
            origin = self._origin(ctx.relpath, value)
            dst = self.auto.memo_state(
                ("reset", item.state, val.index),
                f"{ctx.func}:L{getattr(value, 'lineno', 0)}:reset")
            self.auto.add_edge(Edge(item.state, dst, EPS, "", item.pos,
                                    item.neg, (("reset", val.index),),
                                    False, origin))
            item.state = dst
        self._bind(item, names, val if len(names) == 1 else None)
        if len(names) > 1:
            for n in names:
                if n:
                    item.env.pop(n, None)
        return [item]

    def _bind(self, item: _Item, names: list, rv) -> None:
        if len(names) == 1 and names[0]:
            item.env[names[0]] = rv
        elif len(names) > 1 and isinstance(rv, Tup) \
                and len(rv.items) == len(names):
            for n, v in zip(names, rv.items):
                if n:
                    item.env[n] = v

    def _module_dict_keys(self, dict_name: str,
                          ctx: "_Ctx") -> Optional[frozenset]:
        sf = self.project.file(ctx.relpath)
        if sf is None:
            return None
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == dict_name \
                    and isinstance(node.value, ast.Dict):
                keys = set()
                for k in node.value.keys:
                    name = self._const_name(k) if k is not None else None
                    if name is None:
                        return None
                    keys.add(name)
                return frozenset(keys)
        return None

    def _do_expr(self, value: ast.expr, item: _Item,
                 ctx: "_Ctx") -> list[_Item]:
        v = self._unwrap(value)
        if not isinstance(v, ast.Call):
            return [item]
        chain = attr_chain(v.func)
        name = chain[-1] if chain else None
        origin = self._origin(ctx.relpath, v)
        if name in _SEND_BYTE:
            self._do_send_byte(v, item, origin)
            return [item]
        if name in _SEND_MANY:
            self._do_send_many(v, item, origin)
            return [item]
        if name == "write" and len(chain) >= 2 \
                and chain[-2] in ("writer", "w"):
            self._do_send_many(v, item, origin)
            return [item]
        if name in _RECV_BYTE | _RECV_EXACT:
            self._recv_assign(value, [], item, ctx)
            return [item]
        if name in _IGNORED_WIRE or name in ("drain", "close", "hit",
                                             "sleep", "record", "inc",
                                             "observe", "info", "debug",
                                             "warning"):
            return [item]
        if name in ("append", "extend") and len(chain) >= 2:
            ctr = item.env.get(chain[-2])
            if isinstance(ctr, Ctr):
                # count BEFORE any wire op in the argument: the inc
                # guard then bounds the window before the send fires,
                # keeping send/ack counts matched under the bound.
                dst = self.auto.memo_state(
                    ("inc", item.state, ctr.index),
                    f"{ctx.func}:L{getattr(v, 'lineno', 0)}:+1")
                self.auto.add_edge(Edge(item.state, dst, EPS, "",
                                        item.pos, item.neg,
                                        (("inc", ctr.index),),
                                        False, origin))
                item.state = dst
            items = [item]
            for a in v.args:
                aa = self._unwrap(a)
                if isinstance(aa, ast.Call):
                    nxt: list[_Item] = []
                    for it in items:
                        sp = self._try_splice(aa, it, ctx)
                        if sp is not None:
                            nxt.extend(ex for ex, _ in sp)
                        else:
                            self._do_expr(a, it, ctx)
                            nxt.append(it)
                    items = nxt
            return items
        spliced = self._try_splice(v, item, ctx)
        if spliced is not None:
            return [ex for ex, _ in spliced]
        return [item]

    # -- helper splicing --------------------------------------------------

    def _try_splice(self, call: ast.Call, item: _Item,
                    ctx: "_Ctx") -> Optional[list]:
        if ctx.depth <= 0:
            return None
        qual = self.graph.resolve_node(call)
        if qual is None:
            return None
        info = self.graph.function(qual)
        if info is None or qual in ctx.active:
            return None
        return self._call_function(info, qual, call, item, ctx)

    def _call_function(self, info, qual: str, call: Optional[ast.Call],
                       item: _Item, ctx: "_Ctx") -> list:
        self._flush_pending(item, (info.relpath, info.node.lineno))
        params = [a.arg for a in info.node.args.args]
        if info.cls and params and params[0] in ("self", "cls"):
            params = params[1:]
        env: dict = {}
        if call is not None:
            pos_args = [a for a in call.args
                        if not isinstance(a, ast.Starred)]
            for p, a in zip(params, pos_args):
                env[p] = self._eval(a, item)
            for kw in call.keywords:
                if kw.arg:
                    env[kw.arg] = self._eval(kw.value, item)
        cctx = _Ctx(info.relpath, info.cls, info.name, ctx.depth - 1,
                    ctx.active | {qual})
        entry = item.fork(env=env, pending=None)
        returns: list = []
        falls = self._run_block(info.node.body, [entry], cctx, returns,
                                [], [])
        out: list = []
        end_origin = (info.relpath, info.node.lineno)
        for it in falls:
            self._flush_pending(it, end_origin)
            out.append((self._restore(it, item), frozenset({"None"})))
        for it, rv in returns:
            self._flush_pending(it, end_origin)
            out.append((self._restore(it, item), rv))
        return out

    def _restore(self, ex: _Item, caller: _Item) -> _Item:
        return caller.fork(state=ex.state, pos=ex.pos, neg=ex.neg,
                           pending=None)

    def splice_qualname(self, qual: str, item: _Item,
                        depth: int = _SPLICE_DEPTH) -> Optional[list]:
        info = self.graph.function(qual)
        if info is None:
            return None
        ctx = _Ctx(info.relpath, info.cls, info.name, depth,
                   frozenset({qual}))
        return self._call_function(info, qual, None, item, ctx)


@dataclass(frozen=True)
class _Ctx:
    relpath: str
    cls: Optional[str]
    func: str
    depth: int
    active: frozenset = frozenset()


def _target_names(target: ast.expr) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Tuple):
        out: list[str] = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    return []

# -- endpoint builders ------------------------------------------------------

_CLIENT_SKIP_METHODS = {"connect", "close", "connected"}


def _class_methods(project: Project, relpath: str,
                   cls_name: str) -> list[ast.FunctionDef]:
    sf = project.file(relpath)
    if sf is None:
        return []
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return [n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))]
    return []


def _is_property(fn: ast.AST) -> bool:
    return any(isinstance(d, ast.Name) and d.id == "property"
               for d in getattr(fn, "decorator_list", []))


def build_session_pair(project: Project) -> Optional[EndpointPair]:
    """The persistent-session exchange: ``DistributerSession`` (every
    public wire method reachable from a hub state, modelling the owner
    thread's free interleaving) vs ``Distributer._handle_session``."""
    graph = callgraph.graph_for(project)
    client_rel = f"{PACKAGE}/worker/client.py"
    connect_qual = f"{CLIENT_SESSION_CLASS}.connect"
    if graph.function(connect_qual) is None \
            or graph.function(SERVER_SESSION_HANDLER) is None:
        return None

    # client: connect -> hub -> {public methods} -> hub -> EOS
    a = Automaton("session", "client")
    ex = Extractor(project, a)
    item0 = _Item(a.start, {}, frozenset(), frozenset())
    res = ex.splice_qualname(connect_qual, item0) or []
    hub = a.new_state("session-hub")
    closed = a.new_state("closed")
    legacy = a.new_state("legacy-fallback")
    a.done |= {closed, legacy}
    cinfo = graph.function(connect_qual)
    syn = (cinfo.relpath, cinfo.node.lineno)
    for it, rv in res:
        vals = rv if isinstance(rv, frozenset) else frozenset({"True",
                                                               "False"})
        if "False" in vals:
            a.add_edge(Edge(it.state, legacy, EPS, "", it.pos, it.neg,
                            (), False, syn))
        if vals - {"False", "None"}:
            a.add_edge(Edge(it.state, hub, EPS, "", it.pos, it.neg,
                            (), False, syn))
    for fn in _class_methods(project, client_rel, "DistributerSession"):
        if fn.name.startswith("_") or fn.name in _CLIENT_SKIP_METHODS \
                or _is_property(fn):
            continue
        qual = f"{CLIENT_SESSION_CLASS}.{fn.name}"
        mres = ex.splice_qualname(qual, _Item(hub, {}, frozenset(),
                                              frozenset())) or []
        for it, _rv in mres:
            a.add_edge(Edge(it.state, hub, EPS, "", it.pos, it.neg,
                            (), False, (client_rel, fn.lineno)))
    a.add_edge(Edge(hub, closed, SEND, EOS, origin=syn))

    # server: accept-loop purpose byte, then the session handler
    s = Automaton("session", "server")
    sx = Extractor(project, s)
    sinfo = graph.function(SERVER_SESSION_HANDLER)
    sorigin = (sinfo.relpath, sinfo.node.lineno)
    h0 = s.new_state("session-accepted")
    s.add_edge(Edge(s.start, h0, RECV, "PURPOSE_SESSION", origin=sorigin))
    sdone = s.new_state("session-done")
    s.done.add(sdone)
    s.add_edge(Edge(s.start, sdone, RECV, EOS, fault=True, origin=sorigin))
    sres = sx.splice_qualname(SERVER_SESSION_HANDLER,
                              _Item(h0, {}, frozenset(), frozenset())) or []
    for it, _rv in sres:
        s.add_edge(Edge(it.state, sdone, EPS, "", it.pos, it.neg,
                        (), False, sorigin))
    return EndpointPair("session", "session", a, s)


def build_query_pairs(project: Project) -> list[EndpointPair]:
    """One pair per :data:`rules_proto.QUERY_EXCHANGES` row whose two
    endpoints both exist in the project."""
    from distributedmandelbrot_tpu.analysis.rules_proto import \
        QUERY_EXCHANGES
    graph = callgraph.graph_for(project)
    pairs: list[EndpointPair] = []
    for label, client_qual, server_qual in QUERY_EXCHANGES:
        cinfo = graph.function(client_qual)
        sinfo = graph.function(server_qual)
        if cinfo is None or sinfo is None:
            continue
        a = Automaton(label, "client")
        ex = Extractor(project, a)
        corigin = (cinfo.relpath, cinfo.node.lineno)
        res = ex.splice_qualname(client_qual,
                                 _Item(a.start, {}, frozenset(),
                                       frozenset())) or []
        pre = a.new_state("exchange-done")
        closed = a.new_state("closed")
        a.done.add(closed)
        for it, _rv in res:
            a.add_edge(Edge(it.state, pre, EPS, "", it.pos, it.neg,
                            (), False, corigin))
        a.add_edge(Edge(pre, closed, SEND, EOS, origin=corigin))

        s = Automaton(label, "server")
        sx = Extractor(project, s)
        sorigin = (sinfo.relpath, sinfo.node.lineno)
        sdone = s.new_state("served")
        s.done.add(sdone)
        s.add_edge(Edge(s.start, sdone, RECV, EOS, fault=True,
                        origin=sorigin))
        sres = sx.splice_qualname(server_qual,
                                  _Item(s.start, {}, frozenset(),
                                        frozenset())) or []
        for it, _rv in sres:
            s.add_edge(Edge(it.state, sdone, EPS, "", it.pos, it.neg,
                            (), False, sorigin))
        pairs.append(EndpointPair(label, "query", a, s))
    return pairs


def build_pairs(project: Project) -> list[EndpointPair]:
    """Every extractable exchange of the project, session pair first."""
    pairs: list[EndpointPair] = []
    session = build_session_pair(project)
    if session is not None:
        pairs.append(session)
    pairs.extend(build_query_pairs(project))
    return pairs


# -- DOT export -------------------------------------------------------------

def _dot_edge_label(e: Edge) -> str:
    if e.kind == SEND:
        lab = f"!{e.label}"
    elif e.kind == RECV:
        lab = f"?{e.label}"
    else:
        lab = "eps"
    guards = [f"+{g}" for g in sorted(e.pos)]
    guards += [f"-{g}" for g in sorted(e.neg)]
    guards += [f"{op} c{k}" for op, k in e.cops]
    if guards:
        lab += " [" + " ".join(guards) + "]"
    return lab


def to_dot(pairs: Sequence[EndpointPair]) -> str:
    """Graphviz digraph of every automaton, one cluster per endpoint.
    ``!X`` are sends, ``?X`` receives, dashed edges fault transitions."""
    lines = ["digraph fsm {", "  rankdir=LR;", "  node [shape=circle];"]
    for pi, pair in enumerate(pairs):
        for auto in (pair.client, pair.server):
            cid = f"cluster_{pi}_{auto.role}"
            lines.append(f"  subgraph {cid} {{")
            lines.append(f'    label="{pair.name} {auto.role}";')
            prefix = f"p{pi}{auto.role[0]}"
            used = {auto.start} | auto.done
            for e in auto.edges:
                used |= {e.src, e.dst}
            for st in sorted(used):
                name = auto.state_names.get(st, str(st)).replace('"', "'")
                shape = ("doublecircle" if st in auto.done else "circle")
                lines.append(f'    {prefix}_{st} [label="{name}" '
                             f'shape={shape}];')
            for e in auto.edges:
                style = ' style=dashed' if e.fault else ''
                lines.append(
                    f'    {prefix}_{e.src} -> {prefix}_{e.dst} '
                    f'[label="{_dot_edge_label(e)}"{style}];')
            lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"
