"""``proto-*`` rules: wire-exchange conformance over the call graph.

Every exchange in this system is implemented twice — a client emitter
(``worker/client.py``, ``viewer/client.py``) and a coordinator handler
(``coordinator/distributer.py``, ``coordinator/dataserver.py``) — plus
a legacy-degradation branch per side.  PR 3's ``wire-*`` family checks
the *struct formats* agree; this family checks the *conversation*
agrees:

- ``proto-dispatch``: every ``PURPOSE_*`` constant in the canonical
  protocol module has exactly one server dispatch arm
  (``purpose == proto.PURPOSE_X`` in an ``if`` test) and at least one
  client emitter (``send_byte(sock, proto.PURPOSE_X)``).  A new purpose
  byte with no dispatch arm is exactly the bug that silently drops the
  connection on a legacy coordinator.
- ``proto-frames``: the ordered frame sequence a client emits/awaits
  for an exchange must mirror what the matched dispatch arm
  reads/writes.  Sequences are extracted by walking each side's
  function body in source order — splicing resolvable callees via the
  call graph (``_handle_response`` is just ``_ingest_one``) — and
  normalizing each framing op to a symbol: ``BYTE``, ``U32``, a
  canonical struct name (``QUERY``, ``SPANS_HEADER``, …, via
  ``X_WIRE_SIZE`` / ``X.size`` / ``X.pack`` / ``.to_wire()``), or
  ``?`` for payloads whose size is data-dependent (``?`` matches
  anything).  Repeated symbols collapse to first occurrence, so loops
  and retry branches compare cleanly.
- ``proto-exact-read``: every ``X.unpack(...)`` / ``iter_unpack`` /
  ``unpack_from`` of a canonical struct must be fed by an exact-length
  framing read (``recv_exact`` / ``read_exact``) of that same struct's
  size — a raw ``sock.recv(n)`` feed is the classic short-read bug,
  and a read sized by a *different* struct is cross-copy drift.

Stream upgrades: a purpose byte listed in :data:`STREAM_FRAME_SYMBOLS`
turns the connection into a long-lived multiplexed frame stream after
its hello (``PURPOSE_SESSION``).  Source order stops modeling wire
order there — the server loops over interleaved frame types while the
client interleaves pipelined uploads with acks — so sequence parity
checks the hello prefix only: both sides' op lists are truncated at
the first op carrying the stream's frame-header struct
(``SESSION_FRAME``).  Everything inside the stream remains covered by
``proto-exact-read`` and the ``wire-*`` size checks.

Known resolution limit (documented in README): the gateway's
magic-sniffing dual framing (``serve/gateway.py`` reads a bare u32 and
*then* decides legacy-vs-batch) has no purpose byte, so it takes part
in ``proto-exact-read`` and the ``wire-*`` checks but not in sequence
parity.  The viewer<->dataserver query exchange has no purpose byte
either; it is paired explicitly via :data:`QUERY_EXCHANGES`.
"""

from __future__ import annotations

import ast
import struct as struct_mod
from dataclasses import dataclass, field
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis import callgraph
from distributedmandelbrot_tpu.analysis.astutil import (attr_chain,
                                                        cached_walk)
from distributedmandelbrot_tpu.analysis.engine import (PACKAGE, Finding,
                                                       Project, Rule)

RULES = (
    Rule("proto-dispatch", "proto", "error",
         "every PURPOSE_* constant needs exactly one server dispatch arm "
         "and at least one client emitter"),
    Rule("proto-frames", "proto", "error",
         "client and server frame sequences of a wire exchange must agree"),
    Rule("proto-exact-read", "proto", "error",
         "fixed-size struct unpack must be fed by an exact-length framing "
         "read of the same struct"),
)

PROTOCOL_SUFFIX = "net/protocol.py"

# Exchanges with no purpose byte, paired by hand: (label, client emitter
# qualname, server handler qualname).  Checked only when both sides
# exist in the project, so fixture projects are unaffected.
QUERY_EXCHANGES = (
    ("query",
     f"{PACKAGE}/viewer/client.py::DataClient._fetch_once",
     f"{PACKAGE}/coordinator/dataserver.py::DataServer._handle_connection"),
    # The rendered-tile framing: both qualnames cover the post-magic
    # exchange (the client's magic u32 is sent by its caller, mirroring
    # the server, whose accept loop consumes the magic before
    # dispatching to the handler).
    ("render_query",
     f"{PACKAGE}/viewer/client.py::DataClient._render_exchange",
     f"{PACKAGE}/serve/gateway.py::TileGateway._serve_render"),
    # The session framing: same magic-sent-by-caller convention as the
    # rendered exchange; the reply header (SESSION_REPLY) precedes the
    # standard status byte on both sides.
    ("session_query",
     f"{PACKAGE}/viewer/client.py::DataClient._session_exchange",
     f"{PACKAGE}/serve/gateway.py::TileGateway._serve_session"),
)

# Purpose bytes that upgrade the connection to a multiplexed frame
# stream after their hello, mapped to the stream's frame-header struct.
# Sequence parity for these compares the hello prefix only: both sides
# truncate at the first op carrying the frame-header symbol.
STREAM_FRAME_SYMBOLS = {"PURPOSE_SESSION": "SESSION_FRAME"}

# Exchanges INSIDE a multiplexed stream, paired by hand like
# QUERY_EXCHANGES: (label, client exchange method, server frame
# handler, frame-header struct).  The stream's frame header is read by
# the server's session loop but written by the client's exchange
# method, so ops carrying the header symbol are filtered from BOTH
# sides before comparison — the header itself stays covered by
# ``proto-exact-read`` and the ``wire-*`` size checks.  This is what
# extends full sequence parity to the batched lease frames
# (FRAME_LEASE_REQN/GRANTN), which the hello-prefix truncation above
# would otherwise leave unchecked.
SESSION_EXCHANGES = (
    ("lease_reqn",
     f"{PACKAGE}/worker/client.py::DistributerSession._request_batchn",
     f"{PACKAGE}/coordinator/distributer.py::"
     f"Distributer._session_lease_reqn",
     "SESSION_FRAME"),
    # The sharded control plane's ring exchange (FRAME_RING_REQ ->
    # FRAME_RING_INFO): the client's skew probe against the shard's
    # authoritative slice identity.
    ("ring_req",
     f"{PACKAGE}/worker/client.py::DistributerSession.ring_info",
     f"{PACKAGE}/coordinator/distributer.py::"
     f"Distributer._session_ring_req",
     "SESSION_FRAME"),
)

# Frame-sequence wildcard: a payload whose length is data-dependent.
WILD = "?"

_RECV_EXACT = {"recv_exact", "read_exact"}
_RECV_U32 = {"recv_u32", "read_u32"}
_RECV_BYTE = {"recv_byte", "read_byte"}
_SEND_U32 = {"send_u32", "write_u32"}
_SEND_BYTE = {"send_byte", "write_byte"}


@dataclass
class ProtoTable:
    """Canonical symbols parsed (never imported) from net/protocol.py."""

    relpath: str
    structs: dict[str, str] = field(default_factory=dict)  # name -> format
    purposes: dict[str, int] = field(default_factory=dict)  # name -> line

    def size_of(self, symbol: str) -> Optional[int]:
        if symbol == "BYTE":
            return 1
        if symbol == "U32":
            return 4
        fmt = self.structs.get(symbol)
        if fmt is None:
            return None
        try:
            return struct_mod.calcsize(fmt)
        except struct_mod.error:
            return None


def _load_table(project: Project) -> Optional[ProtoTable]:
    for rel in sorted(project.files):
        if rel.endswith(PROTOCOL_SUFFIX):
            break
    else:
        return None
    table = ProtoTable(rel)
    for node in project.files[rel].tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        value = node.value
        if (isinstance(value, ast.Call)
                and (attr_chain(value.func) or [""])[-1] == "Struct"
                and value.args
                and isinstance(value.args[0], ast.Constant)
                and isinstance(value.args[0].value, str)):
            table.structs[name] = value.args[0].value
        elif name.startswith("PURPOSE_"):
            table.purposes[name] = node.lineno
    return table


# -- frame-op extraction ---------------------------------------------------

@dataclass(frozen=True)
class Op:
    direction: str  # "send" | "recv"
    symbol: str


def _last(chain: Optional[list[str]]) -> str:
    return chain[-1] if chain else ""


def _purpose_arg(call: ast.Call, table: ProtoTable) -> Optional[str]:
    for arg in call.args:
        chain = attr_chain(arg)
        if chain and chain[-1] in table.purposes:
            return chain[-1]
    return None


class _Extractor:
    """Ordered frame ops per function, splicing resolvable callees."""

    def __init__(self, graph: callgraph.CallGraph, table: ProtoTable) -> None:
        self.graph = graph
        self.table = table
        self._memo: dict[str, tuple[list[Op], set[str]]] = {}
        self._stack: set[str] = set()
        self.emitters: dict[str, set[str]] = {}  # purpose -> emitter quals

    def function_ops(self, qual: str) -> tuple[list[Op], set[str]]:
        """(ordered frame ops, purpose bytes emitted) for a function."""
        if qual in self._memo:
            return self._memo[qual]
        if qual in self._stack:
            return [], set()
        info = self.graph.function(qual)
        if info is None:
            return [], set()
        self._stack.add(qual)
        ops, purposes = self.body_ops(info.node.body)
        self._stack.discard(qual)
        self._memo[qual] = (ops, purposes)
        for p in purposes:
            self.emitters.setdefault(p, set()).add(qual)
        return ops, purposes

    def body_ops(self, stmts: list[ast.stmt]) -> tuple[list[Op], set[str]]:
        ops: list[Op] = []
        purposes: set[str] = set()
        packbufs = self._packbufs(stmts)

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                verdict = self._classify(node, packbufs)
                if verdict == "opaque":
                    return  # payload already counted (pack / purpose byte)
                if isinstance(verdict, str) and verdict in self.table.purposes:
                    purposes.add(verdict)
                    return
                if isinstance(verdict, Op):
                    ops.append(verdict)
                    return
                # Not a frame op: arguments evaluate first, then the
                # callee body runs — splice in that order.
                for child in ast.iter_child_nodes(node):
                    visit(child)
                callee = self.graph.resolve_node(node)
                if callee is not None:
                    # Splice the callee's frame ops, but NOT its emitted
                    # purposes: an emitter is the function whose own body
                    # sends the purpose byte, not every caller above it.
                    inner_ops, _ = self.function_ops(callee)
                    ops.extend(inner_ops)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in stmts:
            visit(stmt)
        return ops, purposes

    @staticmethod
    def _packbufs(stmts: list[ast.stmt]) -> set[str]:
        """Local names built up via ``buf = bytearray(); buf += X.pack()``
        — their eventual ``send_all`` is skipped because each ``pack``
        already produced a send op in source order."""
        out: set[str] = set()
        for stmt in stmts:
            for node in cached_walk(stmt):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)
                        and _last(attr_chain(node.value.func)) == "bytearray"):
                    out.add(node.targets[0].id)
        return out

    def _classify(self, call: ast.Call, packbufs: set[str]):
        """Op for a frame call, a purpose name for a purpose-byte send,
        ``"opaque"`` for already-counted payloads, None otherwise."""
        chain = attr_chain(call.func)
        last = _last(chain)
        if last in _RECV_EXACT:
            size = call.args[1] if len(call.args) > 1 else None
            return Op("recv", self._symbol(size))
        if last in _RECV_U32:
            return Op("recv", "U32")
        if last in _RECV_BYTE:
            return Op("recv", "BYTE")
        if last in _SEND_U32:
            return Op("send", "U32")
        if last in _SEND_BYTE:
            purpose = _purpose_arg(call, self.table)
            return purpose if purpose is not None else Op("send", "BYTE")
        if last == "send_all":
            payload = call.args[1] if len(call.args) > 1 else None
            if payload is not None and self._is_packbuf(payload, packbufs):
                return "opaque"
            return Op("send", self._symbol(payload))
        if (last == "write" and chain is not None and len(chain) >= 2
                and "writer" in chain[-2]):
            payload = call.args[0] if call.args else None
            if payload is not None and self._is_packbuf(payload, packbufs):
                return "opaque"
            return Op("send", self._symbol(payload))
        if (last == "pack" and chain is not None and len(chain) >= 2
                and chain[-2] in self.table.structs):
            return Op("send", chain[-2])
        return None

    @staticmethod
    def _is_packbuf(expr: ast.expr, packbufs: set[str]) -> bool:
        if (isinstance(expr, ast.Call)
                and _last(attr_chain(expr.func)) == "bytes" and expr.args):
            expr = expr.args[0]
        return isinstance(expr, ast.Name) and expr.id in packbufs

    def _symbol(self, expr: Optional[ast.expr]) -> str:
        """Normalize a size/payload expression to a frame symbol."""
        if expr is None:
            return WILD
        if isinstance(expr, ast.Await):
            return self._symbol(expr.value)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
            if expr.value == 1:
                return "BYTE"
            if expr.value == 4:
                return "U32"
            return f"BYTES:{expr.value}"
        if isinstance(expr, ast.BinOp):
            for side in (expr.right, expr.left):
                sym = self._symbol(side)
                if sym != WILD and not sym.startswith("BYTES:") \
                        and sym not in ("BYTE", "U32"):
                    return sym
            return WILD
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            last = _last(chain)
            if last == "to_wire":
                return "WORKLOAD"
            if (last == "pack" and chain is not None and len(chain) >= 2
                    and chain[-2] in self.table.structs):
                return chain[-2]
            return WILD
        chain = attr_chain(expr)
        if chain:
            last = chain[-1]
            if last.endswith("_WIRE_SIZE"):
                return last[:-len("_WIRE_SIZE")]
            if last == "size" and len(chain) >= 2 \
                    and chain[-2] in self.table.structs:
                return chain[-2]
            if last.isupper() and any(c.isalpha() for c in last):
                return last  # opaque named size (e.g. CHUNK_PIXELS)
        return WILD


# -- sequence comparison ---------------------------------------------------

def _stream_prefix(ops: list[Op], symbol: str) -> list[Op]:
    """Ops up to (excluding) the first one carrying a stream's
    frame-header struct — the point where source order stops modeling
    wire order (see :data:`STREAM_FRAME_SYMBOLS`)."""
    for i, op in enumerate(ops):
        if op.symbol == symbol:
            return ops[:i]
    return ops


def _first_occurrence(ops: list[Op], direction: str) -> list[str]:
    seen: list[str] = []
    for op in ops:
        if op.direction == direction and op.symbol not in seen:
            seen.append(op.symbol)
    return seen


def _compatible(a: str, b: str, table: ProtoTable) -> bool:
    if a == b or WILD in (a, b):
        return True
    for x, y in ((a, b), (b, a)):
        if x.startswith("BYTES:"):
            size = table.size_of(y)
            if size is not None:
                return int(x.split(":", 1)[1]) == size
            return True  # unknown named size: cannot judge, stay quiet
    sa, sb = table.size_of(a), table.size_of(b)
    if sa is None or sb is None:
        return True  # at least one side opaque — conservative
    return sa == sb


def _sequence_mismatch(client: list[str], server: list[str],
                       table: ProtoTable) -> bool:
    if len(client) != len(server):
        return True
    return any(not _compatible(c, s, table)
               for c, s in zip(client, server))


# -- dispatch-arm discovery ------------------------------------------------

def _purpose_tests(test: ast.expr, table: ProtoTable) -> set[str]:
    """PURPOSE_* names an ``if`` test dispatches on (handles the
    ``purpose == proto.PURPOSE_X and self.accept_spans`` shape and
    membership tests over tuples)."""
    out: set[str] = set()
    for node in cached_walk(test):
        if isinstance(node, ast.Compare):
            for expr in [node.left, *node.comparators]:
                for sub in cached_walk(expr):
                    chain = attr_chain(sub) if isinstance(
                        sub, (ast.Name, ast.Attribute)) else None
                    if chain and chain[-1] in table.purposes:
                        out.add(chain[-1])
    return out


@dataclass
class _Arm:
    purpose: str
    relpath: str
    line: int
    body: list[ast.stmt]


def _dispatch_arms(graph: callgraph.CallGraph,
                   table: ProtoTable) -> list[_Arm]:
    arms: list[_Arm] = []
    for info in graph.functions.values():
        for node in cached_walk(info.node):
            if not isinstance(node, ast.If):
                continue
            for purpose in sorted(_purpose_tests(node.test, table)):
                arms.append(_Arm(purpose, info.relpath, node.lineno,
                                 node.body))
    return arms


# -- proto-exact-read ------------------------------------------------------

_UNPACKERS = {"unpack", "unpack_from", "iter_unpack"}


def _find_read_call(expr: ast.expr) -> Optional[ast.Call]:
    """The framing read (or raw ``.recv``) feeding an expression."""
    for node in cached_walk(expr):
        if isinstance(node, ast.Call):
            last = _last(attr_chain(node.func))
            if last in _RECV_EXACT or last == "recv":
                return node
    return None


def _feeding_exprs(fn: callgraph.FunctionNode,
                   name: str) -> Iterator[ast.expr]:
    """Every expression assigned to a local name in a function
    (both branches of ``x = A if cond else B``)."""
    for node in cached_walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            value = node.value
            if isinstance(value, ast.IfExp):
                yield value.body
                yield value.orelse
            else:
                yield value


def _exact_read_findings(graph: callgraph.CallGraph, table: ProtoTable,
                         extractor: _Extractor) -> Iterator[Finding]:
    rule = RULES[2]
    for info in graph.functions.values():
        for node in cached_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not (chain and len(chain) >= 2
                    and chain[-1] in _UNPACKERS
                    and chain[-2] in table.structs and node.args):
                continue
            struct_name = chain[-2]
            arg = node.args[0]
            feeds = ([arg] if not isinstance(arg, ast.Name)
                     else list(_feeding_exprs(info.node, arg.id)))
            for feed in feeds:
                read = _find_read_call(feed)
                if read is None:
                    continue  # param-fed or sliced — conservative
                if _last(attr_chain(read.func)) == "recv":
                    yield Finding(
                        rule.id, rule.severity, info.relpath, node.lineno,
                        f"{struct_name}.{chain[-1]} fed by raw .recv() — "
                        f"use an exact-length framing read "
                        f"(recv_exact/read_exact)")
                    continue
                size_expr = read.args[1] if len(read.args) > 1 else None
                sym = extractor._symbol(size_expr)
                if sym in (WILD, struct_name):
                    continue
                expected = table.size_of(struct_name)
                got = (int(sym.split(":", 1)[1])
                       if sym.startswith("BYTES:") else table.size_of(sym))
                if got is not None and expected is not None \
                        and got == expected:
                    continue
                yield Finding(
                    rule.id, rule.severity, info.relpath, node.lineno,
                    f"{struct_name}.{chain[-1]} fed by a read sized as "
                    f"{sym}, not {struct_name}")


# -- entry point -----------------------------------------------------------

def check(project: Project) -> list[Finding]:
    table = _load_table(project)
    if table is None:
        return []
    graph = callgraph.graph_for(project)
    extractor = _Extractor(graph, table)
    # Walk every function once so emitter registration is complete.
    for qual in list(graph.functions):
        extractor.function_ops(qual)

    findings: list[Finding] = []
    dispatch_rule, frames_rule = RULES[0], RULES[1]
    arms = _dispatch_arms(graph, table)
    arms_by_purpose: dict[str, list[_Arm]] = {}
    for arm in arms:
        arms_by_purpose.setdefault(arm.purpose, []).append(arm)

    for purpose, line in sorted(table.purposes.items()):
        n_arms = len(arms_by_purpose.get(purpose, []))
        if n_arms == 0:
            findings.append(Finding(
                dispatch_rule.id, dispatch_rule.severity, table.relpath,
                line, f"{purpose} has no server dispatch arm"))
        elif n_arms > 1:
            findings.append(Finding(
                dispatch_rule.id, "warning", table.relpath, line,
                f"{purpose} has {n_arms} server dispatch arms "
                f"(expected exactly one)"))
        if not extractor.emitters.get(purpose):
            findings.append(Finding(
                dispatch_rule.id, dispatch_rule.severity, table.relpath,
                line, f"{purpose} has no client emitter"))

    # Frame-sequence parity: each emitter against each dispatch arm.
    for purpose, emitter_quals in sorted(extractor.emitters.items()):
        stream_symbol = STREAM_FRAME_SYMBOLS.get(purpose)
        for arm in arms_by_purpose.get(purpose, []):
            server_ops, _ = extractor.body_ops(arm.body)
            if stream_symbol is not None:
                server_ops = _stream_prefix(server_ops, stream_symbol)
            for emitter in sorted(emitter_quals):
                client_ops, _ = extractor.function_ops(emitter)
                if stream_symbol is not None:
                    client_ops = _stream_prefix(client_ops, stream_symbol)
                findings.extend(_frame_findings(
                    purpose, emitter, client_ops, arm.relpath, arm.line,
                    server_ops, table, frames_rule))

    for label, client_qual, server_qual in QUERY_EXCHANGES:
        if graph.function(client_qual) is None \
                or graph.function(server_qual) is None:
            continue
        client_ops, _ = extractor.function_ops(client_qual)
        server_ops, _ = extractor.function_ops(server_qual)
        server_info = graph.function(server_qual)
        findings.extend(_frame_findings(
            label, client_qual, client_ops, server_info.relpath,
            server_info.node.lineno, server_ops, table, frames_rule))

    for label, client_qual, server_qual, frame_symbol in SESSION_EXCHANGES:
        if graph.function(client_qual) is None \
                or graph.function(server_qual) is None:
            continue
        client_ops = [op for op in extractor.function_ops(client_qual)[0]
                      if op.symbol != frame_symbol]
        server_ops = [op for op in extractor.function_ops(server_qual)[0]
                      if op.symbol != frame_symbol]
        server_info = graph.function(server_qual)
        findings.extend(_frame_findings(
            label, client_qual, client_ops, server_info.relpath,
            server_info.node.lineno, server_ops, table, frames_rule))

    findings.extend(_exact_read_findings(graph, table, extractor))
    return findings


def _frame_findings(label: str, emitter: str, client_ops: list[Op],
                    server_relpath: str, server_line: int,
                    server_ops: list[Op], table: ProtoTable,
                    rule: Rule) -> Iterator[Finding]:
    emitter_name = emitter.rsplit("::", 1)[-1]
    pairs = (("send", "recv", "client sends", "server reads"),
             ("recv", "send", "client awaits", "server writes"))
    for cdir, sdir, clabel, slabel in pairs:
        cseq = _first_occurrence(client_ops, cdir)
        sseq = _first_occurrence(server_ops, sdir)
        if _sequence_mismatch(cseq, sseq, table):
            yield Finding(
                rule.id, rule.severity, server_relpath, server_line,
                f"{label}: {clabel} [{', '.join(cseq) or '-'}] "
                f"({emitter_name}) but {slabel} "
                f"[{', '.join(sseq) or '-'}]")
