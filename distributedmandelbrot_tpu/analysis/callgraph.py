"""Intra-package call graph over stdlib ``ast`` (the v2 engine layer).

PR 3's rules are intra-function: a one-level helper defeats the lock
rules, and the wire rules can only compare symbols, not the actual
send/recv sequence a handler reaches through ``self._handle_x()``.
This module gives every rule family the missing piece: a conservative,
resolution-by-name call graph built purely from the parsed sources —
the package under analysis is NEVER imported (the tier-1 gate measures
that), and the whole build is one AST walk per file, well inside the
sub-second budget.

What resolves (and nothing more):

- ``self.m()``            -> the enclosing class's method ``m`` (MRO by
  lexical base-class names, project classes only);
- ``self.attr.m()``       -> ``m`` on ``attr``'s inferred class.  Types
  come from ``__init__``-parameter annotations assigned to ``self.attr``
  (``scheduler: TileScheduler``), direct construction
  (``self.x = ClassName(...)``), the guard idiom
  (``x if x is not None else ClassName()``), and one propagation pass
  for ``self.x = self.y`` / ``self.x = self.y.z`` chains;
- ``f()``                 -> a module-level function of the same module
  or an imported project function; ``ClassName()`` -> its ``__init__``;
- ``mod.f()``             -> a function in an imported project module
  (``framing.read_u32`` style), or a method on a local variable whose
  class was inferred from an annotation / construction;
- ``ClassName.m()``       -> static/class-method style calls.

Everything else — callbacks, ``getattr``, lambdas, calls through
containers, stdlib/third-party targets — stays *unresolved*: the graph
reports the call site with ``callee=None`` and rule families must treat
it as "unknown", never as "safe to assume absent".  Nested ``def``s and
lambdas are not walked as part of their enclosing function (their bodies
run at some later call, exactly like the lock walk's reasoning).

Qualified names are ``"<relpath>::Class.method"`` /
``"<relpath>::function"`` — stable across runs, unique per project.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis.astutil import (FunctionNode,
                                                        attr_chain,
                                                        cached_walk)
from distributedmandelbrot_tpu.analysis.engine import PACKAGE, Project

__all__ = ["CallGraph", "CallSite", "ClassInfo", "FunctionInfo",
           "graph_for"]


@dataclass
class FunctionInfo:
    """One module-level function or method in the project."""

    qualname: str
    relpath: str
    name: str
    cls: Optional[str]  # enclosing class name, None for module functions
    node: FunctionNode


@dataclass
class CallSite:
    """One textual call inside a function body, in source order."""

    line: int
    chain: Optional[list[str]]  # lexical dotted chain; None if non-lexical
    callee: Optional[str]       # resolved qualname, None when unresolved
    node: ast.Call


@dataclass
class ClassInfo:
    name: str
    relpath: str
    node: ast.ClassDef
    methods: dict[str, FunctionNode] = field(default_factory=dict)
    bases: list[str] = field(default_factory=list)
    # self.<attr> -> inferred class name (project classes only)
    attr_types: dict[str, str] = field(default_factory=dict)


class _ModuleEnv:
    """Per-module name environment: local defs + project imports."""

    def __init__(self, relpath: str) -> None:
        self.relpath = relpath
        self.functions: dict[str, FunctionNode] = {}
        self.classes: dict[str, ClassInfo] = {}
        # local alias -> (module relpath, symbol or None for module alias)
        self.imports: dict[str, tuple[str, Optional[str]]] = {}


def _annotation_class(ann: Optional[ast.expr]) -> Optional[str]:
    """Best-effort class name out of an annotation expression: ``X``,
    ``mod.X``, ``Optional[X]``, ``X | None``, ``"X"`` all yield ``X``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
        return _annotation_class(ann)
    if isinstance(ann, ast.Name):
        return None if ann.id == "None" else ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Subscript):
        base = attr_chain(ann.value)
        if base and base[-1] == "Optional":
            return _annotation_class(ann.slice)
        return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return (_annotation_class(ann.left)
                or _annotation_class(ann.right))
    return None


def _module_relpath(project: Project, dotted: str) -> Optional[str]:
    """Project relpath for a dotted module name, or None if external."""
    parts = dotted.split(".")
    if parts[0] != PACKAGE:
        return None
    for candidate in ("/".join(parts) + ".py",
                      "/".join(parts) + "/__init__.py"):
        if project.file(candidate) is not None:
            return candidate
    return None


class CallGraph:
    """Functions, classes, and resolved call sites for one project."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: dict[str, FunctionInfo] = {}
        self.calls: dict[str, list[CallSite]] = {}
        # class name -> every definition (duplicates legal across modules)
        self.classes: dict[str, list[ClassInfo]] = {}
        self._envs: dict[str, _ModuleEnv] = {}
        self._by_node: dict[int, Optional[str]] = {}
        for sf in sorted(project.files.values(), key=lambda s: s.relpath):
            self._index_module(sf.relpath, sf.tree)
        self._infer_attr_types()
        for env in self._envs.values():
            self._resolve_module(env)

    # -- indexing ----------------------------------------------------------

    def _index_module(self, relpath: str, tree: ast.Module) -> None:
        env = _ModuleEnv(relpath)
        self._envs[relpath] = env
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                env.functions[node.name] = node
                self._add_function(relpath, None, node)
            elif isinstance(node, ast.ClassDef):
                info = ClassInfo(node.name, relpath, node)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        info.methods[sub.name] = sub
                        self._add_function(relpath, node.name, sub)
                for base in node.bases:
                    chain = attr_chain(base)
                    if chain:
                        info.bases.append(chain[-1])
                env.classes[node.name] = info
                self.classes.setdefault(node.name, []).append(info)
            elif isinstance(node, ast.ImportFrom):
                self._index_import_from(env, node)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    mod = _module_relpath(self.project, alias.name)
                    if mod is not None:
                        local = alias.asname or alias.name.split(".")[0]
                        env.imports[local] = (mod, None)

    def _index_import_from(self, env: _ModuleEnv,
                           node: ast.ImportFrom) -> None:
        if node.level:
            # Relative import: anchor on this module's own package dir.
            base = env.relpath.rsplit("/", 1)[0].split("/")
            base = base[:len(base) - (node.level - 1)]
            dotted = ".".join(base + ([node.module] if node.module else []))
        else:
            dotted = node.module or ""
        for alias in node.names:
            local = alias.asname or alias.name
            # `from pkg.net import framing` — the name itself may be a
            # submodule rather than a symbol.
            as_module = _module_relpath(self.project,
                                        f"{dotted}.{alias.name}")
            if as_module is not None:
                env.imports[local] = (as_module, None)
                continue
            mod = _module_relpath(self.project, dotted)
            if mod is not None:
                env.imports[local] = (mod, alias.name)

    def _add_function(self, relpath: str, cls: Optional[str],
                      node: FunctionNode) -> None:
        qual = (f"{relpath}::{cls}.{node.name}" if cls
                else f"{relpath}::{node.name}")
        self.functions[qual] = FunctionInfo(qual, relpath, node.name,
                                            cls, node)

    # -- attribute-type inference ------------------------------------------

    def _infer_attr_types(self) -> None:
        # Pass 1: direct evidence (construction, annotated params).
        for env in self._envs.values():
            for info in env.classes.values():
                self._direct_attr_types(env, info)
        # Pass 2: one propagation round for self.x = self.y(.z) chains.
        for env in self._envs.values():
            for info in env.classes.values():
                self._propagated_attr_types(env, info)

    def _direct_attr_types(self, env: _ModuleEnv, info: ClassInfo) -> None:
        for meth in info.methods.values():
            params = {a.arg: _annotation_class(a.annotation)
                      for a in (meth.args.posonlyargs + meth.args.args
                                + meth.args.kwonlyargs)}
            for node in cached_walk(meth):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = attr_chain(node.targets[0])
                    typ = self._expr_class(env, node.value, params)
                elif isinstance(node, ast.AnnAssign):
                    # `self.b: "B" = b` — the annotation IS the evidence.
                    target = attr_chain(node.target)
                    typ = _annotation_class(node.annotation)
                else:
                    continue
                if not (target and len(target) == 2
                        and target[0] == "self"):
                    continue
                if typ is not None and target[1] not in info.attr_types:
                    info.attr_types[target[1]] = typ

    def _propagated_attr_types(self, env: _ModuleEnv,
                               info: ClassInfo) -> None:
        for meth in info.methods.values():
            for node in cached_walk(meth):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                target = attr_chain(node.targets[0])
                if not (target and len(target) == 2
                        and target[0] == "self"
                        and target[1] not in info.attr_types):
                    continue
                value = attr_chain(node.value)
                if not value or value[0] != "self":
                    continue
                typ: Optional[str] = info.name
                for attr in value[1:]:
                    owner = self._class_named(env, typ) if typ else None
                    typ = owner.attr_types.get(attr) if owner else None
                    if typ is None:
                        break
                if typ is not None:
                    info.attr_types[target[1]] = typ

    def _expr_class(self, env: _ModuleEnv, expr: ast.expr,
                    params: dict[str, Optional[str]]) -> Optional[str]:
        """Class name an expression evaluates to, or None.  Guard idioms
        (``x if x is not None else Cls()``, ``x or Cls()``) resolve when
        every candidate agrees."""
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if chain:
                resolved = self._resolve_name_to_class(env, chain)
                if resolved is not None:
                    return resolved.name
            return None
        if isinstance(expr, ast.Name):
            return params.get(expr.id)
        if isinstance(expr, ast.IfExp):
            cands = {self._expr_class(env, e, params)
                     for e in (expr.body, expr.orelse)}
            cands.discard(None)
            return cands.pop() if len(cands) == 1 else None
        if isinstance(expr, ast.BoolOp):
            cands = {self._expr_class(env, e, params)
                     for e in expr.values}
            cands.discard(None)
            return cands.pop() if len(cands) == 1 else None
        return None

    # -- name resolution ---------------------------------------------------

    def _class_named(self, env: Optional[_ModuleEnv],
                     name: Optional[str]) -> Optional[ClassInfo]:
        """Resolve a bare class name: same module, then imports, then a
        globally unique definition."""
        if name is None:
            return None
        if env is not None:
            local = env.classes.get(name)
            if local is not None:
                return local
            imp = env.imports.get(name)
            if imp is not None:
                mod, symbol = imp
                target = self._envs.get(mod)
                if target is not None and symbol is not None:
                    found = target.classes.get(symbol)
                    if found is not None:
                        return found
        defs = self.classes.get(name, [])
        return defs[0] if len(defs) == 1 else None

    def _resolve_name_to_class(self, env: _ModuleEnv,
                               chain: list[str]) -> Optional[ClassInfo]:
        if len(chain) == 1:
            return self._class_named(env, chain[0])
        if len(chain) == 2:
            imp = env.imports.get(chain[0])
            if imp is not None and imp[1] is None:  # module alias
                target = self._envs.get(imp[0])
                if target is not None:
                    return target.classes.get(chain[1])
        return None

    def resolve_method(self, cls_name: Optional[str], method: str,
                       *, env: Optional[_ModuleEnv] = None,
                       _seen: Optional[set[str]] = None) -> Optional[str]:
        """Qualname of ``cls.method``, walking lexical bases."""
        info = self._class_named(env, cls_name)
        if info is None or cls_name is None:
            return None
        if method in info.methods:
            return f"{info.relpath}::{info.name}.{method}"
        seen = _seen if _seen is not None else set()
        if info.name in seen:
            return None
        seen.add(info.name)
        owner_env = self._envs.get(info.relpath)
        for base in info.bases:
            found = self.resolve_method(base, method, env=owner_env,
                                        _seen=seen)
            if found is not None:
                return found
        return None

    # -- call-site resolution ----------------------------------------------

    def _resolve_module(self, env: _ModuleEnv) -> None:
        for name, node in env.functions.items():
            qual = f"{env.relpath}::{name}"
            self.calls[qual] = self._function_calls(env, None, node)
        for info in env.classes.values():
            for name, node in info.methods.items():
                qual = f"{env.relpath}::{info.name}.{name}"
                self.calls[qual] = self._function_calls(env, info, node)

    def _function_calls(self, env: _ModuleEnv, cls: Optional[ClassInfo],
                        fn: FunctionNode) -> list[CallSite]:
        locals_types = self._local_types(env, cls, fn)
        sites: list[CallSite] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # a nested def runs later, not as part of fn
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                callee = self._resolve_call(env, cls, chain, locals_types)
                sites.append(CallSite(node.lineno, chain, callee, node))
                self._by_node[id(node)] = callee
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        return sites

    def _local_types(self, env: _ModuleEnv, cls: Optional[ClassInfo],
                     fn: FunctionNode) -> dict[str, str]:
        """Local name -> class, from annotations and construction."""
        out: dict[str, str] = {}
        for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
            typ = _annotation_class(a.annotation)
            if typ is not None and self._class_named(env, typ) is not None:
                out[a.arg] = typ
        for node in cached_walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if isinstance(node.value, ast.Call):
                chain = attr_chain(node.value.func)
                if chain:
                    found = self._resolve_name_to_class(env, chain)
                    if found is not None:
                        out.setdefault(name, found.name)
            elif cls is not None:
                value = attr_chain(node.value)
                if value and len(value) == 2 and value[0] == "self":
                    typ = cls.attr_types.get(value[1])
                    if typ is not None:
                        out.setdefault(name, typ)
        return out

    def _resolve_call(self, env: _ModuleEnv, cls: Optional[ClassInfo],
                      chain: Optional[list[str]],
                      locals_types: dict[str, str]) -> Optional[str]:
        if not chain:
            return None
        if chain[0] == "self":
            if cls is None:
                return None
            if len(chain) == 2:
                return self.resolve_method(cls.name, chain[1], env=env)
            if len(chain) == 3:
                typ = cls.attr_types.get(chain[1])
                return self.resolve_method(typ, chain[2], env=env)
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in env.functions:
                return f"{env.relpath}::{name}"
            found = self._class_named(env, name)
            if found is not None:
                return self.resolve_method(found.name, "__init__",
                                           env=self._envs[found.relpath])
            imp = env.imports.get(name)
            if imp is not None and imp[1] is not None:
                target = self._envs.get(imp[0])
                if target is not None and imp[1] in target.functions:
                    return f"{imp[0]}::{imp[1]}"
            return None
        if len(chain) == 2:
            base, meth = chain
            imp = env.imports.get(base)
            if imp is not None and imp[1] is None:  # module alias call
                target = self._envs.get(imp[0])
                if target is not None:
                    if meth in target.functions:
                        return f"{imp[0]}::{meth}"
                    if meth in target.classes:
                        return self.resolve_method(meth, "__init__",
                                                   env=target)
                return None
            typ = locals_types.get(base)
            if typ is not None:
                return self.resolve_method(typ, meth, env=env)
            found = self._class_named(env, base)
            if found is not None:
                return self.resolve_method(found.name, meth, env=env)
        return None

    # -- queries -----------------------------------------------------------

    def callees(self, qualname: str) -> list[CallSite]:
        return self.calls.get(qualname, [])

    def resolve_node(self, call: ast.Call) -> Optional[str]:
        """Resolved callee for a call node seen during the build (rules
        walking the same ASTs use this to splice callees in their own
        traversal order)."""
        return self._by_node.get(id(call))

    def function(self, qualname: str) -> Optional[FunctionInfo]:
        return self.functions.get(qualname)

    def class_info(self, relpath: str, name: str) -> Optional[ClassInfo]:
        env = self._envs.get(relpath)
        return env.classes.get(name) if env else None

    def method_qualnames(self, relpath: str, cls: str) -> Iterator[str]:
        info = self.class_info(relpath, cls)
        if info is not None:
            for name in info.methods:
                yield f"{relpath}::{cls}.{name}"

    def reachable(self, qualname: str, *, max_depth: int = 32
                  ) -> dict[str, tuple[str, ...]]:
        """Every function transitively reachable from ``qualname``
        through RESOLVED calls, mapped to one exemplar call path
        (tuple of qualnames, caller first).  Cycle-safe; unresolved
        calls contribute nothing (the conservative reading is the rule
        family's job)."""
        out: dict[str, tuple[str, ...]] = {}
        frontier: list[tuple[str, tuple[str, ...]]] = [(qualname, ())]
        depth = 0
        while frontier and depth < max_depth:
            depth += 1
            nxt: list[tuple[str, tuple[str, ...]]] = []
            for qual, path in frontier:
                for site in self.calls.get(qual, ()):
                    callee = site.callee
                    if callee is None or callee in out \
                            or callee == qualname:
                        continue
                    out[callee] = path + (qual,)
                    nxt.append((callee, path + (qual,)))
            frontier = nxt
        return out


def graph_for(project: Project) -> CallGraph:
    """Build (or reuse) the project's call graph.  Rule modules run in
    sequence over the same Project instance; one build serves all."""
    cached = getattr(project, "_callgraph", None)
    if isinstance(cached, CallGraph) and cached.project is project:
        return cached
    graph = CallGraph(project)
    project._callgraph = graph
    return graph
