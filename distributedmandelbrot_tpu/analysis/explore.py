"""Explicit-state exploration of the composed protocol automata.

Takes the client x server :class:`~.fsm.EndpointPair` automata and
exhaustively explores their asynchronous product (two bounded FIFO
queues, one per direction) under every realistic capability
configuration, checking:

- **dual conformance** — every message that actually arrives at a peer
  finds a matching receive arm there (statically: every send label has
  *some* receive arm; dynamically: no run wedges with an unconsumable
  queue head);
- **deadlock freedom** — no reachable global state where both
  endpoints wait forever;
- **liveness-to-EOS** — from every reachable state some continuation
  reaches a terminal state (both endpoints closed or torn down).

Bounded-model-checking semantics: queue occupancy and loop counters
are bounded, and a global state blocked *only* by one of those bounds
is recorded as a truncation (coverage boundary), never as a finding.
Faults are first-class: an endpoint that aborts (a modeled ``raise``)
or closes pushes ``EOS``, and the peer either takes a fault arm
(``try/except ConnectionError``) or aborts in turn.

The same explicit-state engine drives :class:`CrashSpec` — a compact
spec of the lease -> accept -> persist-queue -> group-commit ->
checkpoint/restore pipeline with crash transitions at every registered
``utils/faults.py`` seam — asserting exactly-once commits and
no-lost-tile across all interleavings.

Stdlib-only, never imports the package under analysis.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

from distributedmandelbrot_tpu.analysis.fsm import (EOS, EPS, RECV, SEND,
                                                    WILD, Automaton,
                                                    EndpointPair)

__all__ = ["CRASH_SEAMS", "CapReport", "CrashReport", "CrashSpec",
           "ExploreConfig", "ExploreReport", "PairReport", "Violation",
           "cap_configs", "cap_gate_violations", "explore_all",
           "explore_crash_model", "explore_pair", "static_dual_violations"]

SESSION_ATOMS = ("RLE", "GRANTN", "SHARD")
SHARDED = "SHARDED"  # deployment-shape pseudo-atom (server has a ring)


@dataclass(frozen=True)
class ExploreConfig:
    queue_bound: int = 3
    ctr_bound: int = 2
    max_states: int = 20000


@dataclass(frozen=True)
class Violation:
    kind: str           # dual | deadlock | liveness | cap-gate | crash-*
    pair: str
    caps: frozenset
    message: str
    origin: tuple       # (relpath, line) anchor


@dataclass
class CapReport:
    caps: frozenset
    n_states: int = 0
    truncations: int = 0
    aborts: int = 0
    terminal_reached: bool = False
    complete: bool = True   # False when max_states was hit
    violations: list = field(default_factory=list)


@dataclass
class PairReport:
    pair: EndpointPair
    configs: list = field(default_factory=list)
    static_violations: list = field(default_factory=list)

    @property
    def visited_caps(self) -> set:
        return {c.caps for c in self.configs}

    @property
    def violations(self) -> list:
        out = list(self.static_violations)
        for c in self.configs:
            out.extend(c.violations)
        return out


@dataclass
class ExploreReport:
    pairs: list = field(default_factory=list)
    traversed: set = field(default_factory=set)   # (origin, label) recvs
    recv_arms: set = field(default_factory=set)   # all non-fault recvs

    @property
    def violations(self) -> list:
        out = []
        for p in self.pairs:
            out.extend(p.violations)
        return out

    def dead_arms(self) -> list:
        """Receive arms never exercised in any configuration of any
        pair, unioned by source origin (an arm shared by several
        exchanges is dead only if unexercised everywhere)."""
        alive = {key for key in self.traversed}
        return sorted(k for k in self.recv_arms if k not in alive)


# -- capability configurations ----------------------------------------------

def _subsets(atoms: Sequence[str]):
    n = len(atoms)
    for mask in range(1 << n):
        yield frozenset(a for i, a in enumerate(atoms)
                        if mask & (1 << i))


def cap_configs(pair: EndpointPair) -> list[frozenset]:
    """Realistic cap products.  Session: an unsharded server never
    negotiates SHARD (4 legacy-to-partial products), a sharded one can
    negotiate any subset (8 products).  Query exchanges only vary in
    deployment shape."""
    if pair.kind == "session":
        out = [s for s in _subsets(("RLE", "GRANTN"))]
        out += [s | {SHARDED} for s in _subsets(SESSION_ATOMS)]
        return out
    return [frozenset(), frozenset({SHARDED})]


# -- static checks ----------------------------------------------------------

def _send_labels(auto: Automaton) -> dict:
    out: dict = {}
    for e in auto.edges:
        if e.kind == SEND and e.label not in (EOS, WILD) \
                and not (e.pos & e.neg):
            out.setdefault(e.label, []).append(e)
    return out


def _recv_labels(auto: Automaton) -> dict:
    out: dict = {}
    for e in auto.edges:
        if e.kind == RECV and e.label != EOS and not (e.pos & e.neg):
            out.setdefault(e.label, []).append(e)
    return out


def static_dual_violations(pair: EndpointPair) -> list[Violation]:
    """A label one side can send with no receive arm at all on the
    other side — unconditional dual-conformance breakage."""
    out: list[Violation] = []
    for sender, receiver in ((pair.client, pair.server),
                             (pair.server, pair.client)):
        recvs = _recv_labels(receiver)
        if WILD in recvs:
            continue  # receiver has a wildcard arm: anything matches
        for label, edges in sorted(_send_labels(sender).items()):
            if label not in recvs:
                e = edges[0]
                out.append(Violation(
                    "dual", pair.name, frozenset(),
                    f"{sender.role} sends {label} but {receiver.role} "
                    f"has no receive arm for it", e.origin))
    return out


def _first_wire_pos(auto: Automaton, start: int) -> frozenset:
    """Intersection of pos-guards over the first wire edges reachable
    from ``start`` via eps moves — the caps a receive arm's *handling*
    demands even when the dispatch edge itself is unguarded."""
    seen = {start}
    q = deque([start])
    acc: Optional[frozenset] = None
    while q:
        st = q.popleft()
        for e in auto.out(st):
            if e.kind == EPS:
                if e.dst not in seen:
                    seen.add(e.dst)
                    q.append(e.dst)
            elif not e.fault:
                acc = e.pos if acc is None else (acc & e.pos)
    return acc if acc is not None else frozenset()


def cap_gate_violations(pair: EndpointPair) -> list[Violation]:
    """Hello-mask asymmetry: the receiver's arm for a label demands a
    capability the sender does not guarantee when emitting it."""
    out: list[Violation] = []
    for sender, receiver in ((pair.client, pair.server),
                             (pair.server, pair.client)):
        sends = _send_labels(sender)
        recvs = _recv_labels(receiver)
        for label in sorted(set(sends) & set(recvs)):
            sreq = None
            for e in sends[label]:
                p = e.pos - {SHARDED}
                sreq = p if sreq is None else (sreq & p)
            rreq = None
            for e in recvs[label]:
                p = (e.pos | _first_wire_pos(receiver, e.dst)) - {SHARDED}
                rreq = p if rreq is None else (rreq & p)
            if rreq and not rreq <= (sreq or frozenset()):
                e = recvs[label][0]
                out.append(Violation(
                    "cap-gate", pair.name, frozenset(rreq),
                    f"{receiver.role} only accepts {label} under caps "
                    f"{sorted(rreq)} but {sender.role} sends it under "
                    f"{sorted(sreq or frozenset())}", e.origin))
    return out


# -- product exploration ----------------------------------------------------

def _enabled(auto: Automaton, caps: frozenset) -> dict:
    out: dict = {}
    for e in auto.edges:
        if e.pos <= caps and not (e.neg & caps):
            out.setdefault(e.src, []).append(e)
    return out


def _prune_eps(auto: Automaton, enabled: dict) -> dict:
    """Drop eps moves into states that are dead under these caps (a
    method entry whose only continuation is cap-gated away), so the
    model never walks into an artifact stuck state."""
    changed = True
    while changed:
        changed = False
        for st in list(enabled):
            keep = [e for e in enabled[st]
                    if not (e.kind == EPS and e.dst not in auto.done
                            and not enabled.get(e.dst))]
            if len(keep) != len(enabled[st]):
                changed = True
                if keep:
                    enabled[st] = keep
                else:
                    del enabled[st]
    return enabled


def _apply_cops(cops: tuple, ctrs: tuple,
                bound: int) -> tuple[Optional[tuple], bool]:
    """(new counters, counter_blocked).  inc saturating would desync
    matched send/ack windows, so a blocked inc disables the move and
    flags truncation instead."""
    if not cops:
        return ctrs, False
    cs = list(ctrs)
    for op, k in cops:
        if op == "gt0":
            if cs[k] <= 0:
                return None, False
        elif op == "eq0":
            if cs[k] != 0:
                return None, False
        elif op == "dec":
            if cs[k] <= 0:
                return None, False
            cs[k] -= 1
        elif op == "inc":
            if cs[k] >= bound:
                return None, True
            cs[k] += 1
        elif op == "reset":
            cs[k] = 0
    return tuple(cs), False


def _closure(state: int, ctrs: tuple, en: dict, auto: Automaton,
             live: dict, cfg: ExploreConfig, memo: dict) -> tuple:
    """Eps-closure of one endpoint from a program point: the wire
    moves (send/recv edges with updated counters), the done states,
    and whether an abort (raise-only dead end) or a bound truncation
    is reachable via internal moves alone.  Interleaving the peer
    against invisible internal steps only multiplies the product, so
    the product is built over wire points exclusively."""
    key = (state, ctrs)
    got = memo.get(key)
    if got is not None:
        return got
    wire: list = []
    dones: list = []
    abort = trunc = False
    seen = {key}
    stack = [key]
    while stack:
        s, c = stack.pop()
        if s in auto.done:
            dones.append(s)
            continue
        edges = en.get(s)
        if not edges:
            abort = True
            continue
        for e in edges:
            nc, cblocked = _apply_cops(e.cops, c, cfg.ctr_bound)
            if nc is None:
                trunc |= cblocked
                continue
            lv = live.get(e.dst, frozenset())
            nc = tuple(v if k in lv else 0 for k, v in enumerate(nc))
            if e.kind == EPS:
                nxt = (e.dst, nc)
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
            else:
                wire.append((e, nc))
    res = (wire, dones, abort, trunc)
    memo[key] = res
    return res


def _explore_config(pair: EndpointPair, caps: frozenset,
                    cfg: ExploreConfig, traversed: set) -> CapReport:
    rep = CapReport(caps=caps)
    cen = _prune_eps(pair.client, _enabled(pair.client, caps))
    sen = _prune_eps(pair.server, _enabled(pair.server, caps))
    autos = (pair.client, pair.server)
    ens = (cen, sen)
    lives = (pair.client.live_counters(), pair.server.live_counters())
    memos: tuple = ({}, {})

    init = (pair.client.start, pair.server.start, (), (),
            (0,) * pair.client.n_counters,
            (0,) * pair.server.n_counters, True, True)
    seen = {init}
    queue = deque([init])
    succ: dict = {}
    terminals: set = set()
    cdone, sdone = pair.client.done, pair.server.done
    queue_bound, max_states = cfg.queue_bound, cfg.max_states

    while queue:
        st = queue.popleft()
        if len(seen) > max_states:
            rep.complete = False
            break
        cs, ss, qc, qs, cc, sc, ca, sa = st
        if (not ca or cs in cdone) and (not sa or ss in sdone):
            rep.terminal_reached = True
            terminals.add(st)
            continue
        nexts: list = []
        truncated = False
        stuck_dual: Optional[tuple] = None
        stuck_recv_origin: Optional[tuple] = None
        for side in (0, 1):
            auto = autos[side]
            # Successor tuples are built inline per side (the product
            # layout is (cs, ss, qc, qs, cc, sc, ca, sa)) — a per-state
            # closure here dominated the whole exploration's runtime.
            if side == 0:
                state, ctrs, alive = cs, cc, ca
                out_q, in_q = qc, qs
            else:
                state, ctrs, alive = ss, sc, sa
                out_q, in_q = qs, qc
            if not alive or state in auto.done:
                continue

            wire, dones, can_abort, can_trunc = _closure(
                state, ctrs, ens[side], auto, lives[side], cfg,
                memos[side])
            truncated |= can_trunc
            if can_abort:
                # raise-only program point: endpoint aborts, peer
                # observes the teardown as EOS.
                rep.aborts += 1
                nexts.append(
                    (state, ss, out_q + (EOS,), in_q, ctrs, sc, False, sa)
                    if side == 0 else
                    (cs, state, in_q, out_q + (EOS,), cc, ctrs, ca, False))
            for d in dones:
                nexts.append(
                    (d, ss, out_q, in_q, ctrs, sc, alive, sa)
                    if side == 0 else
                    (cs, d, in_q, out_q, cc, ctrs, ca, alive))
            has_recv = has_eos_arm = False
            for e, nctrs in wire:
                if e.kind == SEND:
                    if e.label == EOS:
                        nout = out_q + (EOS,)
                    elif len(out_q) < queue_bound:
                        nout = out_q + (e.label,)
                    else:
                        truncated = True
                        continue
                    nexts.append(
                        (e.dst, ss, nout, in_q, nctrs, sc, alive, sa)
                        if side == 0 else
                        (cs, e.dst, in_q, nout, cc, nctrs, ca, alive))
                else:  # RECV
                    has_recv = True
                    if e.label == EOS:
                        has_eos_arm = True
                    if stuck_recv_origin is None:
                        stuck_recv_origin = e.origin
                    if not in_q:
                        continue
                    head = in_q[0]
                    if e.label == EOS:
                        if head != EOS:
                            continue
                        # sticky: a closed peer stays closed
                        nin = in_q
                    elif head == EOS:
                        continue
                    elif e.label == WILD or head == WILD \
                            or head == e.label:
                        traversed.add((e.origin, e.label))
                        nin = in_q[1:]
                    else:
                        continue
                    nexts.append(
                        (e.dst, ss, out_q, nin, nctrs, sc, alive, sa)
                        if side == 0 else
                        (cs, e.dst, nin, out_q, cc, nctrs, ca, alive))
            if has_recv and in_q and in_q[0] != EOS and stuck_dual is None:
                stuck_dual = (auto.role, auto.describe(state), in_q[0])
            if has_recv and not has_eos_arm and in_q and in_q[0] == EOS:
                # recv on a dead connection without a fault arm: the
                # exception tears this endpoint down too.
                rep.aborts += 1
                nexts.append(
                    (state, ss, out_q + (EOS,), in_q, ctrs, sc, False, sa)
                    if side == 0 else
                    (cs, state, in_q, out_q + (EOS,), cc, ctrs, ca, False))

        if not nexts:
            if truncated:
                rep.truncations += 1
                terminals.add(st)  # bound artifact: acceptable sink
            elif stuck_dual is not None:
                role, desc, head = stuck_dual
                rep.violations.append(Violation(
                    "dual", pair.name, caps,
                    f"{desc} cannot consume {head} under caps "
                    f"{sorted(caps)} (peer at "
                    f"{autos[0].describe(cs) if role != 'client' else autos[1].describe(ss)})",
                    stuck_recv_origin or ("", 0)))
            else:
                rep.violations.append(Violation(
                    "deadlock", pair.name, caps,
                    f"stuck state pair {pair.client.describe(cs)} <-> "
                    f"{pair.server.describe(ss)} under caps "
                    f"{sorted(caps)}: both endpoints wait forever",
                    stuck_recv_origin or ("", 0)))
            continue
        succ[st] = nexts
        for n in nexts:
            if n not in seen:
                seen.add(n)
                queue.append(n)

    rep.n_states = len(seen)
    if rep.complete and not rep.violations:
        # liveness-to-EOS: every explored state must be co-reachable
        # from a terminal (or bound-truncated) sink.
        pred: dict = {}
        for st, ns in succ.items():
            for n in ns:
                pred.setdefault(n, []).append(st)
        co = set(terminals)
        bfs = deque(terminals)
        while bfs:
            st = bfs.popleft()
            for p in pred.get(st, ()):
                if p not in co:
                    co.add(p)
                    bfs.append(p)
        wedged = [st for st in seen if st not in co]
        if wedged:
            st = wedged[0]
            rep.violations.append(Violation(
                "liveness", pair.name, caps,
                f"{pair.client.describe(st[0])} <-> "
                f"{pair.server.describe(st[1])} under caps "
                f"{sorted(caps)} cannot reach end-of-stream",
                ("", 0)))
    return rep


def explore_pair(pair: EndpointPair,
                 cfg: Optional[ExploreConfig] = None,
                 traversed: Optional[set] = None) -> PairReport:
    cfg = cfg or ExploreConfig()
    traversed = traversed if traversed is not None else set()
    rep = PairReport(pair=pair)
    rep.static_violations.extend(static_dual_violations(pair))
    rep.static_violations.extend(cap_gate_violations(pair))
    for caps in cap_configs(pair):
        rep.configs.append(_explore_config(pair, caps, cfg, traversed))
    return rep


def explore_all(pairs: Sequence[EndpointPair],
                cfg: Optional[ExploreConfig] = None) -> ExploreReport:
    cfg = cfg or ExploreConfig()
    report = ExploreReport()
    for pair in pairs:
        report.pairs.append(explore_pair(pair, cfg, report.traversed))
        for auto in (pair.client, pair.server):
            for e in auto.edges:
                if e.kind == RECV and not e.fault \
                        and e.label not in (EOS, WILD) \
                        and not (e.pos & e.neg):
                    report.recv_arms.add((e.origin, e.label))
    return report


# -- crash-interleaving model of the persistence pipeline -------------------

CRASH_SEAMS = (
    "coord.between_accept_and_persist",
    "store.before_chunk_write",
    "store.after_chunk_write",
    "store.after_index_append",
    "recovery.mid_checkpoint",
)


@dataclass(frozen=True)
class CrashSpec:
    """Compact spec of the scheduler lease -> accept -> persist-queue
    -> group-commit -> checkpoint/restore pipeline for one tile.  The
    knobs exist so tests can knock out a defense and watch the
    corresponding invariant break:

    - ``claim_dedup``: the scheduler refuses to re-lease a tile whose
      in-memory state is already complete (off -> double commit).
    - ``pending_exclusion``: a checkpoint never records a tile as
      complete while its chunk is still volatile in the accept/persist
      window (off -> a crash loses the tile for good).
    """

    claim_dedup: bool = True
    pending_exclusion: bool = True
    max_crashes: int = 2


@dataclass
class CrashReport:
    n_states: int = 0
    violations: list = field(default_factory=list)
    seams_fired: set = field(default_factory=set)
    quiescent_ok: int = 0


# state tuple indices for the crash model
# (leased, complete, unqueued, queued, wphase, blob, index, commits,
#  crash_since_commit, ckpt, ckpt_pending, crashes)
_W_IDLE, _W_PICKED, _W_BLOBBED, _W_APPENDED = 0, 1, 2, 3
_COMMIT_CAP = 3


def _crash_transitions(st: tuple, spec: CrashSpec):
    (leased, complete, unqueued, queued, wphase, blob, index, commits,
     since, ckpt, pending, crashes) = st
    out = []

    def emit(name, **kw):
        s = dict(leased=leased, complete=complete, unqueued=unqueued,
                 queued=queued, wphase=wphase, blob=blob, index=index,
                 commits=commits, since=since, ckpt=ckpt,
                 pending=pending, crashes=crashes)
        s.update(kw)
        out.append((name, (s["leased"], s["complete"], s["unqueued"],
                           s["queued"], s["wphase"], s["blob"],
                           s["index"], s["commits"], s["since"],
                           s["ckpt"], s["pending"], s["crashes"])))

    busy = unqueued or queued or wphase != _W_IDLE
    if not leased and not busy and (not complete or not spec.claim_dedup):
        emit("lease", leased=True)
    if leased:
        emit("accept", leased=False, complete=True, unqueued=True)
    if unqueued:
        emit("enqueue", unqueued=False, queued=True)
    if queued:
        emit("persist_pick", queued=False, wphase=_W_PICKED)
    if wphase == _W_PICKED:
        emit("chunk_write", wphase=_W_BLOBBED, blob=1)
    if wphase == _W_BLOBBED and commits < _COMMIT_CAP:
        emit("index_append", wphase=_W_APPENDED, index=1,
             commits=commits + 1, since=False)
    if wphase == _W_APPENDED:
        emit("persist_done", wphase=_W_IDLE)
    if pending is None:
        snap = complete and (not busy if spec.pending_exclusion else True)
        emit("checkpoint_begin", pending=snap)
    else:
        emit("checkpoint_end", ckpt=pending, pending=None)

    if crashes < spec.max_crashes:
        windows = {
            "coord.between_accept_and_persist": unqueued,
            "store.before_chunk_write": wphase == _W_PICKED,
            "store.after_chunk_write": wphase == _W_BLOBBED,
            "store.after_index_append": wphase == _W_APPENDED,
            "recovery.mid_checkpoint": pending is not None,
        }
        recovered = bool(index) or bool(ckpt)
        for seam, enabled in windows.items():
            if enabled:
                emit(seam, leased=False, complete=recovered,
                     unqueued=False, queued=False, wphase=_W_IDLE,
                     since=True, pending=None, crashes=crashes + 1)
    return out


def explore_crash_model(spec: Optional[CrashSpec] = None) -> CrashReport:
    spec = spec or CrashSpec()
    rep = CrashReport()
    init = (False, False, False, False, _W_IDLE, 0, 0, 0, False, False,
            None, 0)
    seen = {init}
    queue = deque([init])
    while queue:
        st = queue.popleft()
        (leased, complete, unqueued, queued, wphase, blob, index,
         commits, since, ckpt, pending, crashes) = st
        moves = _crash_transitions(st, spec)
        for name, nxt in moves:
            if name == "index_append" and index == 1 and not since:
                rep.violations.append(Violation(
                    "crash-dual", "crash-model", frozenset(),
                    "exactly-once violated: tile committed twice with "
                    "no crash in between (lease/claim dedup broken)",
                    ("", 0)))
                continue
            if name in CRASH_SEAMS:
                rep.seams_fired.add(name)
            if nxt not in seen:
                seen.add(nxt)
                queue.append(nxt)
        busy = (leased or unqueued or queued or wphase != _W_IDLE
                or pending is not None)
        lease_open = not busy and (not complete or not spec.claim_dedup)
        if not busy and not lease_open:
            # quiescent: nothing in flight and the scheduler will never
            # hand the tile out again — it had better be durable.
            if index == 1 and commits >= 1:
                rep.quiescent_ok += 1
            else:
                rep.violations.append(Violation(
                    "crash-lost", "crash-model", frozenset(),
                    "no-lost-tile violated: pipeline quiesced with the "
                    "tile marked complete but never durably committed "
                    "(checkpoint recorded a volatile accept)", ("", 0)))
    rep.n_states = len(seen)
    return rep
