"""Wire-format parity rules.

The reference system's canonical defect was the same constant typed
into three files (DataChunk.cs, worker, viewer) with nothing checking
the copies agree.  Post-dedup, this repo keeps every struct format in
exactly one place and these rules keep it that way:

``wire-literal`` — a struct format *string literal* (``struct.Struct``,
``struct.pack``/``unpack``/``unpack_from``/``pack_into``/``calcsize``)
in any module outside the canonical set.  Canonical modules:
net/protocol.py, net/framing.py, core/workload.py, storage/index.py,
serve/render.py (the PNG container), and codecs/ (each owns its own
on-disk format).  Everyone else must import the precompiled
``struct.Struct`` objects from net/protocol.py.

``wire-size`` — inside the canonical modules, every ``NAME_WIRE_SIZE =
<int>`` constant must equal ``struct.calcsize`` of the ``NAME = struct.
Struct("...")`` it describes, and the documented composition
``QUERY == u32 level + QUERY_TAIL`` must hold byte-for-byte (the
gateway reads the leading u32 alone to sniff the batch magic).

``wire-parity`` — the protocol-speaking modules must actually
reference the canonical symbols for the messages they speak (via
``proto.X`` or ``from ...net.protocol import X``); a module that stops
doing so has, by construction, re-typed the format somewhere.  Modules
absent from the project (fixture runs) are skipped.
"""

from __future__ import annotations

import ast
import struct as _struct
from typing import Optional

from distributedmandelbrot_tpu.analysis.astutil import (cached_walk,
                                                        call_chain,
                                                        dotted_names)
from distributedmandelbrot_tpu.analysis.engine import (PACKAGE, Finding,
                                                       Project, Rule,
                                                       SourceFile)

RULES = (
    Rule("wire-literal", "wire", "error",
         "struct format literal outside the canonical wire modules"),
    Rule("wire-size", "wire", "error",
         "wire size constant disagrees with its struct format"),
    Rule("wire-parity", "wire", "error",
         "protocol-speaking module does not use the canonical structs"),
)

PROTOCOL = f"{PACKAGE}/net/protocol.py"

CANONICAL = frozenset({
    PROTOCOL,
    f"{PACKAGE}/net/framing.py",
    f"{PACKAGE}/core/workload.py",
    f"{PACKAGE}/storage/index.py",
    # The PNG container: big-endian chunk/IHDR formats are PNG's, not
    # the dmtpu wire protocol's, and live only in the render module.
    f"{PACKAGE}/serve/render.py",
})
CANONICAL_PREFIXES = (f"{PACKAGE}/codecs/",)

STRUCT_FUNCS = frozenset({"Struct", "pack", "unpack", "unpack_from",
                          "pack_into", "calcsize", "iter_unpack"})

# module -> canonical net/protocol.py symbols it must reference.
REQUIRED_SYMBOLS = {
    f"{PACKAGE}/coordinator/dataserver.py": ("QUERY",),
    f"{PACKAGE}/coordinator/distributer.py": ("SPANS_HEADER", "SPAN_SYNC",
                                              "SPAN_RECORD"),
    f"{PACKAGE}/serve/gateway.py": ("QUERY", "QUERY_TAIL"),
    f"{PACKAGE}/viewer/client.py": ("QUERY", "BATCH_HEADER"),
    f"{PACKAGE}/worker/client.py": ("WORKLOAD_WIRE_SIZE", "SPANS_HEADER",
                                    "SPAN_SYNC", "SPAN_RECORD"),
}

# Span wire frames whose format must lead with the QUERY key triple
# (level, index_real, index_imag as 3 x u32): keyed frames share one
# prefix so a reader can always peel the key the same way.
KEYED_SPAN_STRUCTS = ("SPAN_SYNC", "SPAN_RECORD")


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for rel in sorted(project.files):
        if rel in CANONICAL or rel.startswith(CANONICAL_PREFIXES):
            findings.extend(_check_sizes(project.files[rel]))
        else:
            findings.extend(_check_literals(project.files[rel]))
    for rel, symbols in REQUIRED_SYMBOLS.items():
        sf = project.file(rel)
        if sf is not None:
            findings.extend(_check_parity(sf, symbols))
    return findings


# -- wire-literal -----------------------------------------------------------

def _format_literal(call: ast.Call) -> Optional[str]:
    chain = call_chain(call)
    if not chain or chain[0] != "struct" or chain[-1] not in STRUCT_FUNCS:
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _check_literals(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    for node in cached_walk(sf.tree):
        if isinstance(node, ast.Call):
            fmt = _format_literal(node)
            if fmt is not None:
                out.append(Finding(
                    "wire-literal", "error", sf.relpath, node.lineno,
                    f'struct format "{fmt}" re-typed outside the canonical '
                    f'wire modules (import the precompiled Struct from '
                    f'net/protocol.py)'))
    return out


# -- wire-size --------------------------------------------------------------

def _module_constants(sf: SourceFile) -> tuple[dict[str, str], dict[str, int]]:
    """Top-level ``NAME = struct.Struct("fmt")`` and ``NAME = <int>``."""
    fmts: dict[str, str] = {}
    ints: dict[str, int] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            name, value = node.target.id, node.value
        else:
            continue
        if isinstance(value, ast.Call):
            fmt = _format_literal(value)
            if fmt is not None and call_chain(value) == ["struct", "Struct"]:
                fmts[name] = fmt
        elif isinstance(value, ast.Constant) and isinstance(value.value, int) \
                and not isinstance(value.value, bool):
            ints[name] = value.value
    return fmts, ints


def _calcsize(fmt: str) -> Optional[int]:
    try:
        return _struct.calcsize(fmt)
    except _struct.error:
        return None


def _check_sizes(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    fmts, ints = _module_constants(sf)
    for name, fmt in fmts.items():
        size_name = f"{name}_WIRE_SIZE"
        declared = ints.get(size_name)
        if declared is None:
            continue
        actual = _calcsize(fmt)
        if actual is None:
            out.append(Finding(
                "wire-size", "error", sf.relpath, 1,
                f'{name}: invalid struct format "{fmt}"'))
        elif actual != declared:
            out.append(Finding(
                "wire-size", "error", sf.relpath, 1,
                f'{size_name} = {declared} but struct.calcsize("{fmt}") '
                f'= {actual}'))
    if sf.relpath == PROTOCOL and "QUERY" in fmts and "QUERY_TAIL" in fmts:
        head, tail = fmts["QUERY"], fmts["QUERY_TAIL"]
        if head != "<I" + tail.lstrip("<"):
            out.append(Finding(
                "wire-size", "error", sf.relpath, 1,
                f'QUERY ("{head}") must be a leading u32 followed '
                f'byte-for-byte by QUERY_TAIL ("{tail}"): the gateway '
                f'sniffs the first u32 for the batch magic'))
    if sf.relpath == PROTOCOL:
        key_prefix = fmts.get("QUERY", "<III")
        for name in KEYED_SPAN_STRUCTS:
            fmt = fmts.get(name)
            if fmt is not None and not fmt.startswith(key_prefix):
                out.append(Finding(
                    "wire-size", "error", sf.relpath, 1,
                    f'{name} ("{fmt}") must lead with the QUERY key '
                    f'triple ("{key_prefix}"): keyed frames share the '
                    f'tile-key prefix'))
    return out


# -- wire-parity ------------------------------------------------------------

def _protocol_refs(sf: SourceFile) -> set[str]:
    """Protocol symbols this module references: names imported from
    net.protocol, plus ``<alias>.NAME`` for any alias of the module."""
    aliases: set[str] = set()
    imported: set[str] = set()
    for node in cached_walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("net.protocol"):
                imported.update(a.asname or a.name for a in node.names)
            elif node.module.endswith(".net"):
                for a in node.names:
                    if a.name == "protocol":
                        aliases.add(a.asname or "protocol")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("net.protocol"):
                    aliases.add(a.asname or a.name)
    refs = set(imported)
    if aliases:
        for dotted in dotted_names(sf.tree):
            head, _, last = dotted.rpartition(".")
            if head in aliases:
                refs.add(last)
    return refs


def _check_parity(sf: SourceFile, symbols: tuple[str, ...]) -> list[Finding]:
    refs = _protocol_refs(sf)
    return [Finding(
        "wire-parity", "error", sf.relpath, 1,
        f"module speaks the {sym} message but never references "
        f"net/protocol.py's canonical {sym} (re-typed format?)")
        for sym in symbols if sym not in refs]
