"""JAX tracing purity/precision rules for ops/ and parallel/.

A function is *traced* when it is decorated with anything whose dotted
name ends in ``jit`` / ``pjit`` (this sees through ``@partial(jax.jit,
static_argnames=...)`` — the repo's idiom), when its name is passed to
``jit()`` explicitly, or when it is handed to ``pallas_call`` as the
kernel.  Inside a traced function the Python interpreter runs ONCE, at
trace time, so:

``jax-impure`` — ``print``, ``time.*``, ``random.*`` / ``np.random.*``,
and ``global`` statements execute at trace time only (or worse, retrace
per call) and silently vanish from the compiled computation.

``jax-host-sync`` — ``np.asarray`` / ``np.array`` on a tracer,
``.block_until_ready()``, and ``float()`` force a device->host transfer
mid-trace; they either fail under jit or destroy async dispatch.

``jax-dtype`` — 64-bit dtype literals (``float64`` & co.) silently
downgrade to 32-bit unless x64 mode is on; modules must route through
utils/precision (``ensure_x64``).  The warning fires only in modules
that do NOT import ``ensure_x64`` — escape_time.py and families.py
import it and their host wrappers call it before dispatching into jit.

``jax-dtype-mix`` — half-precision dtype literals (``bfloat16`` /
``float16`` / ``half``) in a traced function: a bf16 value that leaks
into an output expression silently costs ~3 decimal digits, and escape
counts are a bit-exact contract.  Mirrors the x64 gate: the warning is
silenced in modules that import from ``ops/mixed_precision`` — the
reviewed opt-in whose helpers (``scout_cast``/``scout_const``) mark
half precision as advisory-only (see that module's parity-guard
contract).
"""

from __future__ import annotations

import ast
from typing import Iterator

from distributedmandelbrot_tpu.analysis.astutil import (FunctionNode,
                                                        cached_walk,
                                                        call_chain,
                                                        dotted_names)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project, Rule,
                                                       SourceFile)

RULES = (
    Rule("jax-impure", "jax", "error",
         "Python side effect inside a jit/pjit/pallas-traced function"),
    Rule("jax-host-sync", "jax", "error",
         "host synchronization inside a traced function"),
    Rule("jax-dtype", "jax", "warning",
         "64-bit dtype literal in a traced function bypassing "
         "utils/precision"),
    Rule("jax-dtype-mix", "jax", "warning",
         "half-precision dtype literal in a traced function bypassing "
         "ops/mixed_precision"),
)

SCOPE_DIRS = ("ops", "parallel")

JIT_NAMES = ("jit", "pjit")

DTYPE_64 = frozenset({"float64", "int64", "uint64", "complex128"})

DTYPE_HALF = frozenset({"bfloat16", "float16", "half"})

NUMPY_HEADS = ("np", "numpy", "jnp")


def _is_traced_decorator(dec: ast.expr) -> bool:
    return any(d.rsplit(".", 1)[-1] in JIT_NAMES for d in dotted_names(dec))


def _traced_functions(sf: SourceFile) -> Iterator[FunctionNode]:
    """Functions compiled by XLA: jit-decorated, jit-wrapped by name, or
    passed to pallas_call as the kernel."""
    wrapped: set[str] = set()
    for node in cached_walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = call_chain(node)
        if not chain:
            continue
        last = chain[-1]
        if (last in JIT_NAMES or last == "pallas_call") and node.args \
                and isinstance(node.args[0], ast.Name):
            wrapped.add(node.args[0].id)
    for node in cached_walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in wrapped \
                    or any(_is_traced_decorator(d)
                           for d in node.decorator_list):
                yield node


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.in_dirs(*SCOPE_DIRS):
        has_precision = _imports_ensure_x64(sf)
        has_mixed = _imports_mixed_precision(sf)
        for fn in _traced_functions(sf):
            findings.extend(_check_traced(sf, fn, has_precision,
                                          has_mixed))
    return findings


def _imports_ensure_x64(sf: SourceFile) -> bool:
    for node in cached_walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("precision"):
            if any(a.name == "ensure_x64" for a in node.names):
                return True
    return any(d.endswith("precision.ensure_x64")
               for d in dotted_names(sf.tree))


def _imports_mixed_precision(sf: SourceFile) -> bool:
    """The half-precision opt-in: any import from ops/mixed_precision
    (or a dotted use of its helpers) marks the module as a reviewed
    mixed-precision site.  mixed_precision.py itself hosts the only
    sanctioned literal (at module scope, outside any trace)."""
    for node in cached_walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("mixed_precision"):
            return True
    return any(".mixed_precision." in d or d.startswith("mixed_precision.")
               for d in dotted_names(sf.tree))


def _check_traced(sf: SourceFile, fn: FunctionNode,
                  has_precision: bool,
                  has_mixed: bool = False) -> list[Finding]:
    out: list[Finding] = []

    def flag(rule: str, severity: str, line: int, msg: str) -> None:
        out.append(Finding(rule, severity, sf.relpath, line,
                           f"{msg} (in traced function {fn.name})"))

    # Nested defs inside a traced function are traced too -> full walk,
    # but skip the decorator list (it runs at def time, outside the trace).
    for stmt in fn.body:
        for node in cached_walk(stmt):
            if isinstance(node, ast.Global):
                flag("jax-impure", "error", node.lineno,
                     "global statement: mutation happens at trace time, "
                     "not per call")
            if not isinstance(node, ast.Call):
                continue
            chain = call_chain(node)
            if not chain:
                continue
            dotted = ".".join(chain)
            if chain == ["print"]:
                flag("jax-impure", "error", node.lineno,
                     "print() runs at trace time only (use jax.debug.print)")
            elif chain[0] == "time":
                flag("jax-impure", "error", node.lineno,
                     f"{dotted}() is evaluated once at trace time")
            elif chain[0] == "random" or (len(chain) >= 2
                                          and chain[0] in ("np", "numpy")
                                          and chain[1] == "random"):
                flag("jax-impure", "error", node.lineno,
                     f"{dotted}() is host randomness, frozen at trace time "
                     f"(use jax.random with an explicit key)")
            elif chain[-1] == "block_until_ready":
                flag("jax-host-sync", "error", node.lineno,
                     ".block_until_ready() forces a device sync mid-trace")
            elif chain[0] in ("np", "numpy") and chain[-1] in ("asarray",
                                                              "array"):
                flag("jax-host-sync", "error", node.lineno,
                     f"{dotted}() materializes a tracer on the host")
            elif chain == ["float"]:
                flag("jax-host-sync", "error", node.lineno,
                     "float() on a tracer forces a host transfer")
    if not has_precision:
        for stmt in fn.body:
            for node in cached_walk(stmt):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value in DTYPE_64:
                    flag("jax-dtype", "warning", node.lineno,
                         f'dtype literal "{node.value}" without '
                         f"utils/precision.ensure_x64 in the module")
                elif isinstance(node, ast.Attribute) \
                        and node.attr in DTYPE_64 \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in NUMPY_HEADS:
                    flag("jax-dtype", "warning", node.lineno,
                         f"dtype literal {node.value.id}.{node.attr} "
                         f"without utils/precision.ensure_x64 in the module")
    if not has_mixed:
        for stmt in fn.body:
            for node in cached_walk(stmt):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value in DTYPE_HALF:
                    flag("jax-dtype-mix", "warning", node.lineno,
                         f'dtype literal "{node.value}" without the '
                         f"ops/mixed_precision opt-in in the module")
                elif isinstance(node, ast.Attribute) \
                        and node.attr in DTYPE_HALF \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in NUMPY_HEADS:
                    flag("jax-dtype-mix", "warning", node.lineno,
                         f"dtype literal {node.value.id}.{node.attr} "
                         f"without the ops/mixed_precision opt-in "
                         f"in the module")
    return out
