"""``exc-*`` rules: exception paths that leak resources or evidence.

The taint rules guard what a peer can *send*; these guard what an
exception can *drop*.  Two failure shapes recur in serving stacks:

- ``exc-leak``: a resource is acquired (a scheduler lease via
  ``.claim()``, a socket/file via ``create_connection`` / ``open``) and
  a statement that can raise — an ``await`` or an I/O call — runs while
  the resource is held, outside any ``try`` whose handler or ``finally``
  releases it.  The raise unwinds past the release and the lease waits
  out its expiry (or the fd leaks).  A ``if x is None: ...return``
  failure guard directly after the acquisition is recognized; so is
  handing the resource off (returned, stored on ``self``, ``with``).
- ``exc-swallow``: a bare / ``except Exception`` / ``except
  BaseException`` handler that neither re-raises, logs, counts to obs
  (``.inc(``), nor binds-and-uses the exception object.  Silent
  swallows erase the only evidence a storm leaves behind; at minimum
  the handler owes a counter or a log line.

Both families walk statements in program order (the same walk order the
dataflow layer uses), so "before the try" and "inside the guard" mean
what they mean in the source.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis.astutil import (FunctionNode,
                                                        attr_chain,
                                                        cached_walk,
                                                        class_defs,
                                                        methods_of)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Rule, SourceFile)

RULES = (
    Rule("exc-leak", "exc", "error",
         "a raise while a lease/socket is held unwinds past its release"),
    Rule("exc-swallow", "exc", "warning",
         "overbroad except that neither re-raises, logs, nor counts"),
)

SCOPE_DIRS = ("net", "coordinator", "serve", "worker", "viewer")

# Acquisition shapes: (recognizer, release method names).
_CLAIM_RELEASES = ("finish_claim", "release_claim", "release")
_SOCKET_RELEASES = ("close", "shutdown")

# A statement "can raise" when it awaits or performs I/O.  Narrower than
# "any call" on purpose: setsockopt/level accessors between an acquire
# and a hand-off are not worth a finding, network reads/writes are.
_IO_PREFIXES = ("read", "recv", "send", "write", "drain", "connect",
                "flush")


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.in_dirs(*SCOPE_DIRS):
        for fn in _functions(sf):
            findings.extend(_leak_findings(sf, fn))
        findings.extend(_swallow_findings(sf))
    return findings


def _functions(sf: SourceFile) -> Iterator[FunctionNode]:
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
    for cls in class_defs(sf.tree):
        yield from methods_of(cls)


# -- exc-leak --------------------------------------------------------------

def _acquisition(stmt: ast.stmt) -> Optional[tuple[str, str, tuple]]:
    """(name, what, release method names) if stmt acquires a resource
    into a local."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)):
        return None
    value = stmt.value
    if isinstance(value, ast.Await):
        value = value.value
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func) or [""]
    name = stmt.targets[0].id
    if chain[-1] == "claim":
        return name, "lease claim", _CLAIM_RELEASES
    if chain[-1] in ("create_connection", "open") \
            or (chain[-1] == "socket" and len(chain) >= 2
                and chain[-2] == "socket"):
        return name, "socket/file", _SOCKET_RELEASES
    return None


def _releases(stmt: ast.stmt, name: str, methods: tuple) -> bool:
    """Does this statement release or hand off the resource ``name``?"""
    for node in cached_walk(stmt):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in methods:
                return True
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in cached_walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id == name:
                    return True
        elif isinstance(node, ast.Assign):
            target = attr_chain(node.targets[0]) if node.targets else None
            if target and target[0] == "self" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == name:
                return True
    return False


def _is_failure_guard(stmt: ast.stmt, name: str) -> bool:
    """``if name is None:`` / ``if not name:`` with an escaping body —
    the acquisition failed, so nothing is held on that edge."""
    if not isinstance(stmt, ast.If):
        return False
    test = stmt.test
    guarded = None
    if isinstance(test, ast.Compare) and isinstance(test.left, ast.Name) \
            and len(test.ops) == 1 and isinstance(test.ops[0], ast.Is):
        guarded = test.left.id
    elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        guarded = test.operand.id
    return guarded == name and _escapes(stmt.body)


def _escapes(body: list) -> bool:
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Raise, ast.Return, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If):
        return _escapes(last.body) and _escapes(last.orelse)
    return False


def _can_raise(stmt: ast.stmt) -> Optional[int]:
    """Line of the first await / I/O call in the statement, else None."""
    for node in cached_walk(stmt):
        if isinstance(node, ast.Await):
            return node.lineno
        if isinstance(node, ast.Call):
            name = (attr_chain(node.func) or [""])[-1]
            if name.startswith(_IO_PREFIXES):
                return node.lineno
    return None


def _try_protects(stmt: ast.Try, name: str, methods: tuple) -> bool:
    """A try whose handler or finally releases the resource covers the
    held region — from here on the raised edges release."""
    for handler in stmt.handlers:
        if any(_releases(s, name, methods) for s in handler.body):
            return True
    return any(_releases(s, name, methods) for s in stmt.finalbody)


def _leak_findings(sf: SourceFile, fn: FunctionNode) -> Iterator[Finding]:
    rule = RULES[0]
    yield from _scan_body(sf, rule, list(fn.body))


def _scan_body(sf: SourceFile, rule: Rule,
               body: list) -> Iterator[Finding]:
    for i, stmt in enumerate(body):
        acq = _acquisition(stmt)
        if acq is not None:
            name, what, methods = acq
            yield from _scan_held(sf, rule, body[i + 1:], name, what,
                                  methods, stmt.lineno)
        # Recurse into compound statements for nested acquisitions.
        for sub_body in _sub_bodies(stmt):
            yield from _scan_body(sf, rule, sub_body)


def _sub_bodies(stmt: ast.stmt) -> Iterator[list]:
    for attr in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, attr, None)
        if isinstance(sub, list) and sub \
                and isinstance(sub[0], ast.stmt) \
                and not isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
            yield sub
    for handler in getattr(stmt, "handlers", ()):
        yield handler.body


def _scan_held(sf: SourceFile, rule: Rule, following: list, name: str,
               what: str, methods: tuple, acq_line: int
               ) -> Iterator[Finding]:
    for stmt in following:
        if _is_failure_guard(stmt, name):
            continue
        if isinstance(stmt, ast.Try) and _try_protects(stmt, name,
                                                       methods):
            return
        if _releases(stmt, name, methods):
            return
        line = _can_raise(stmt)
        if line is not None:
            yield Finding(
                rule.id, rule.severity, sf.relpath, line,
                f"{what} {name!r} (line {acq_line}) is still held here "
                f"and this statement can raise — release it in an "
                f"except/finally or move the I/O inside one")
            return  # one finding per acquisition is enough to fix it


# -- exc-swallow -----------------------------------------------------------

_OVERBROAD = (None, "Exception", "BaseException")

_EVIDENCE_CALLS = ("exception", "error", "warning", "info", "debug",
                   "critical", "log", "inc", "print")


def _handler_is_overbroad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    name = (attr_chain(handler.type) or [""])[-1]
    return name in _OVERBROAD


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    # Re-binding the exception and using it is handling, not swallowing
    # (``except BaseException as e: self._error = e``).
    if handler.name:
        for node in cached_walk(handler):
            if isinstance(node, ast.Name) and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return False
    for node in cached_walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _EVIDENCE_CALLS:
                return False
    return True


def _swallow_findings(sf: SourceFile) -> Iterator[Finding]:
    rule = RULES[1]
    for node in cached_walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _handler_is_overbroad(node) and _handler_swallows(node):
            caught = ("bare except" if node.type is None else
                      f"except {(attr_chain(node.type) or ['?'])[-1]}")
            yield Finding(
                rule.id, rule.severity, sf.relpath, node.lineno,
                f"{caught} swallows the exception silently — re-raise, "
                f"log, or count it to obs")
