"""``res-*`` rules: resource lifecycle (threads, sockets, queues, servers).

The farm's availability bugs are rarely logic errors — they are leaked
lifecycles: a non-daemon thread that pins the interpreter after the
worker crashes, a socket dialed outside ``with`` that survives an
exception, an unbounded stage queue that absorbs a stalled consumer
until the host OOMs, a server object nothing ever closes.  These rules
encode the project's lifecycle conventions:

- ``res-thread-join``: every ``threading.Thread(...)`` is either
  ``daemon=True`` or joined — on the name it was assigned to (locals
  and ``self.*`` attrs), through a list iterated by a ``for`` loop
  (``for t in threads: t.join()``), or built via ``threads.append``.
  A thread with no handle at all (``Thread(...).start()``) can never be
  joined and is flagged unless daemonized.
- ``res-socket-close``: a socket / file assigned to a local
  (``create_connection``, ``socket.socket``, ``open``) must be closed
  on some path, used as a context manager, or escape the function
  (returned, stored on ``self``, or passed onward — the caller then
  owns the lifecycle, as ``DistributerClient._connect`` does).
- ``res-queue-unbounded``: a ``queue.Queue()`` with no ``maxsize`` in
  the runtime dirs.  Unbounded queues are legal only when some *other*
  mechanism bounds what producers enqueue (the pipeline executor's
  in-flight window) — that claim belongs next to the queue as an
  audited suppression, not in a reviewer's head.
- ``res-shutdown``: a class that stores a ``ThreadPoolExecutor`` or an
  ``asyncio.start_server`` result on ``self`` must also call
  ``.shutdown()`` / ``.close()`` on it somewhere — no server object
  without a stop path.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis.astutil import (FunctionNode,
                                                        attr_chain,
                                                        cached_walk,
                                                        class_defs,
                                                        methods_of)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Rule, SourceFile)

RULES = (
    Rule("res-thread-join", "res", "error",
         "threads must be daemonized or joined on every handle"),
    Rule("res-socket-close", "res", "warning",
         "sockets/files acquired outside a context manager must be "
         "closed or handed off"),
    Rule("res-queue-unbounded", "res", "warning",
         "queue.Queue() without maxsize needs an audited bounding story"),
    Rule("res-shutdown", "res", "warning",
         "executors and servers stored on self need a shutdown path"),
)

SCOPE_DIRS = ("coordinator", "storage", "serve", "obs", "worker", "viewer",
              "net")


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.in_dirs(*SCOPE_DIRS):
        findings.extend(_thread_findings(sf))
        findings.extend(_socket_findings(sf))
        findings.extend(_queue_findings(sf))
        findings.extend(_shutdown_findings(sf))
    return findings


def _functions(sf: SourceFile) -> Iterator[tuple[Optional[ast.ClassDef],
                                                 FunctionNode]]:
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
    for cls in class_defs(sf.tree):
        for meth in methods_of(cls):
            yield cls, meth


# -- res-thread-join -------------------------------------------------------

def _is_thread_call(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and (attr_chain(node.func) or [""])[-1] == "Thread")


def _daemonized(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            # daemon=False is an explicit "I will join this"; anything
            # non-constant is someone else's decision — stay quiet.
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    return False


def _joined_names(scope: ast.AST) -> set[str]:
    """Names (``"t"`` / ``"self.t"``) that see a ``.join()`` in a scope,
    resolving one level of ``for v in <name>`` loop aliasing so joining
    the loop variable joins the iterated list."""
    loop_alias: dict[str, str] = {}
    for node in cached_walk(scope):
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and isinstance(node.target, ast.Name):
            src = attr_chain(node.iter)
            if src:
                loop_alias[node.target.id] = ".".join(src)
    joined: set[str] = set()
    for node in cached_walk(scope):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] == "join" and len(chain) >= 2:
                owner = ".".join(chain[:-1])
                joined.add(owner)
                if owner in loop_alias:
                    joined.add(loop_alias[owner])
    return joined


def _thread_targets(fn: FunctionNode) -> Iterator[tuple[ast.Call,
                                                        Optional[str]]]:
    """(Thread-constructor call, handle name or None) pairs.  The handle
    is the dotted name the thread — or the list containing it — lives
    under; None means the thread has no joinable handle at all."""
    claimed: set[int] = set()
    for node in cached_walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = attr_chain(node.targets[0])
            name = ".".join(target) if target else None
            values = [node.value]
            if isinstance(node.value, (ast.List, ast.Tuple)):
                values = list(node.value.elts)
            elif isinstance(node.value, ast.ListComp):
                values = [node.value.elt]
            for value in values:
                if _is_thread_call(value):
                    claimed.add(id(value))
                    yield value, name
        elif isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] == "append" and len(chain) >= 2:
                for arg in node.args:
                    if _is_thread_call(arg):
                        claimed.add(id(arg))
                        yield arg, ".".join(chain[:-1])
    for node in cached_walk(fn):
        if _is_thread_call(node) and id(node) not in claimed:
            yield node, None


def _thread_findings(sf: SourceFile) -> Iterator[Finding]:
    rule = RULES[0]
    # A class scope is shared by all its methods — compute its joined
    # set once, not once per method (classes can be large).
    joined_cache: dict[int, set[str]] = {}
    for cls, fn in _functions(sf):
        scope: ast.AST = cls if cls is not None else fn
        if id(scope) not in joined_cache:
            joined_cache[id(scope)] = _joined_names(scope)
        joined = joined_cache[id(scope)]
        for call, handle in _thread_targets(fn):
            if _daemonized(call):
                continue
            if handle is not None and handle in joined:
                continue
            what = (f"thread assigned to {handle}" if handle
                    else "thread with no handle")
            yield Finding(rule.id, rule.severity, sf.relpath, call.lineno,
                          f"{what} is neither daemon=True nor joined")


# -- res-socket-close ------------------------------------------------------

_ACQUIRERS = ("create_connection", "open")


def _is_acquire_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = attr_chain(node.func)
    if not chain:
        return False
    if chain[-1] in _ACQUIRERS:
        return True
    return chain[-1] == "socket" and len(chain) >= 2 \
        and chain[-2] == "socket"


def _socket_findings(sf: SourceFile) -> Iterator[Finding]:
    rule = RULES[1]
    for _cls, fn in _functions(sf):
        acquired: dict[str, int] = {}
        for node in cached_walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _is_acquire_call(node.value)):
                acquired.setdefault(node.targets[0].id, node.lineno)
        if not acquired:
            continue
        released: set[str] = set()
        for node in cached_walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Name):
                        released.add(expr.id)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and len(chain) == 2 and chain[-1] in ("close",
                                                              "shutdown"):
                    released.add(chain[0])
                # Passing the handle onward transfers ownership.
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        released.add(arg.id)
            elif isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name):
                released.add(node.value.id)
            elif isinstance(node, ast.Assign):
                target = attr_chain(node.targets[0]) if node.targets else None
                if target and target[0] == "self" \
                        and isinstance(node.value, ast.Name):
                    released.add(node.value.id)
        for name, line in sorted(acquired.items(), key=lambda kv: kv[1]):
            if name not in released:
                yield Finding(
                    rule.id, rule.severity, sf.relpath, line,
                    f"{name} acquired outside a context manager and "
                    f"never closed, returned, or handed off")


# -- res-queue-unbounded ---------------------------------------------------

def _queue_findings(sf: SourceFile) -> Iterator[Finding]:
    rule = RULES[2]
    for node in cached_walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] != "Queue":
            continue
        if len(chain) >= 2 and chain[-2] not in ("queue",):
            continue  # asyncio.Queue() etc. have their own semantics
        bounded = bool(node.args)
        for kw in node.keywords:
            if kw.arg == "maxsize":
                bounded = not (isinstance(kw.value, ast.Constant)
                               and isinstance(kw.value.value, int)
                               and kw.value.value <= 0)
        if not bounded:
            yield Finding(rule.id, rule.severity, sf.relpath, node.lineno,
                          "unbounded queue.Queue() — bound it or document "
                          "the external bounding mechanism")


# -- res-shutdown ----------------------------------------------------------

_SERVERISH = {
    "ThreadPoolExecutor": ("shutdown",),
    "ProcessPoolExecutor": ("shutdown",),
    "start_server": ("close",),
}


def _shutdown_findings(sf: SourceFile) -> Iterator[Finding]:
    rule = RULES[3]
    for cls in class_defs(sf.tree):
        stored: dict[str, tuple[int, str, tuple[str, ...]]] = {}
        for node in cached_walk(cls):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = attr_chain(node.targets[0])
            if not (target and len(target) == 2 and target[0] == "self"):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if not isinstance(value, ast.Call):
                continue
            kind = (attr_chain(value.func) or [""])[-1]
            if kind in _SERVERISH:
                stored[target[1]] = (node.lineno, kind, _SERVERISH[kind])
        if not stored:
            continue
        closed: set[tuple[str, str]] = set()
        for node in cached_walk(cls):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and len(chain) == 3 and chain[0] == "self":
                    closed.add((chain[1], chain[2]))
        for attr, (line, kind, stoppers) in sorted(stored.items()):
            if not any((attr, stop) in closed for stop in stoppers):
                yield Finding(
                    rule.id, rule.severity, sf.relpath, line,
                    f"self.{attr} holds a {kind} result but {cls.name} "
                    f"never calls {' or '.join(stoppers)}() on it")
